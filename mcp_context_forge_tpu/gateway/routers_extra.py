"""Routers: A2A agents, LLM provider admin, export/import.

Reference: `routers/a2a_router` (via main.py /a2a), `routers/llm_admin.py` /
`llm_config.py`, export/import endpoints (`main.py:3575-3586`).
"""

from __future__ import annotations

import json

from aiohttp import web
from pydantic import ValidationError

from ..schemas import A2AAgentCreate
from ..services.base import NotFoundError, ValidationFailure


def profiler_or_404(request: web.Request):
    """The single gate for EVERY profiling surface (timed capture and
    start/stop/status): opt-in config flag first, then the shared
    JaxProfilerCapture (created only alongside the engine)."""
    if not request.app["ctx"].settings.jax_profile_enabled:
        raise NotFoundError("profiler capture is disabled "
                            "(set MCPFORGE_JAX_PROFILE_ENABLED=true)")
    profiler = request.app.get("jax_profiler")
    if profiler is None:
        raise NotFoundError("tpu_local engine is not enabled")
    return profiler


def setup_extra_routes(app: web.Application) -> None:
    routes = web.RouteTableDef()

    # ------------------------------------------------------------------- A2A
    @routes.get("/a2a")
    async def list_agents(request: web.Request) -> web.Response:
        request["auth"].require("a2a.read")
        agents = await request.app["a2a_service"].list_agents(
            request.query.get("include_inactive") == "true")
        from .pagination import paginate
        return paginate(request, agents,
                        lambda page: [json.loads(a.model_dump_json())
                                      for a in page])

    @routes.post("/a2a")
    async def register_agent(request: web.Request) -> web.Response:
        request["auth"].require("a2a.create")
        try:
            agent = A2AAgentCreate.model_validate(await request.json())
        except (json.JSONDecodeError, ValidationError) as exc:
            raise ValidationFailure(str(exc)) from exc
        created = await request.app["a2a_service"].register_agent(agent)
        return web.json_response(json.loads(created.model_dump_json()), status=201)

    @routes.delete("/a2a/{agent_id}")
    async def delete_agent(request: web.Request) -> web.Response:
        request["auth"].require("a2a.delete")
        await request.app["a2a_service"].delete_agent(request.match_info["agent_id"])
        return web.Response(status=204)

    @routes.post("/a2a/{name}/invoke")
    async def invoke_agent(request: web.Request) -> web.Response:
        request["auth"].require("a2a.invoke")
        # NB: can_read_body flips False once middleware has drained the
        # payload (the bytes stay cached) — parse, don't gate on it
        try:
            payload = await request.json()
        except Exception:
            payload = {}
        hop = int(request.headers.get("x-contextforge-uaid-hop", "0"))
        result = await request.app["a2a_service"].invoke_agent(
            request.match_info["name"], payload, user=request["auth"].user, hop=hop)
        return web.json_response(result)

    # ------------------------------------------------------------- A2A tasks
    @routes.post("/a2a/{name}/tasks")
    async def create_task(request: web.Request) -> web.Response:
        request["auth"].require("a2a.invoke")
        try:
            payload = await request.json()
        except Exception:
            payload = {}
        task = await request.app["a2a_service"].create_task(
            request.match_info["name"], payload, user=request["auth"].user)
        return web.json_response(task, status=201)

    # {name}/tasks registers BEFORE tasks/{task_id}: an agent literally
    # named "tasks" must still resolve its task list
    @routes.get("/a2a/{name}/tasks")
    async def list_tasks(request: web.Request) -> web.Response:
        request["auth"].require("a2a.read")
        return web.json_response(await request.app["a2a_service"].list_tasks(
            request.match_info["name"]))

    @routes.get("/a2a/tasks/{task_id}")
    async def get_task(request: web.Request) -> web.Response:
        request["auth"].require("a2a.read")
        task_id = request.match_info["task_id"]
        service = request.app["a2a_service"]
        try:
            return web.json_response(await service.get_task(task_id))
        except NotFoundError:
            # /a2a/tasks/{x} collides with /a2a/{name}/tasks when an agent is
            # literally named "tasks" — aiohttp's literal-prefix index picks
            # this route regardless of registration order, so disambiguate:
            # an unknown task id that names an existing agent means "list
            # that agent's tasks"
            agent = await request.app["ctx"].db.fetchone(
                "SELECT id FROM a2a_agents WHERE name=? OR slug=?",
                (task_id, task_id))
            if agent:
                return web.json_response(await service.list_tasks(task_id))
            raise

    @routes.post("/a2a/tasks/{task_id}/cancel")
    async def cancel_task(request: web.Request) -> web.Response:
        request["auth"].require("a2a.invoke")
        return web.json_response(await request.app["a2a_service"].cancel_task(
            request.match_info["task_id"]))

    # ------------------------------------------------------------- LLM admin
    @routes.get("/llm/providers")
    async def list_providers(request: web.Request) -> web.Response:
        request["auth"].require("llm.admin")
        return web.json_response(await request.app["llm_provider_service"].list_providers())

    @routes.post("/llm/providers")
    async def create_provider(request: web.Request) -> web.Response:
        request["auth"].require("llm.admin")
        body = await request.json()
        provider = await request.app["llm_provider_service"].create_provider(
            name=body.get("name", ""), provider_type=body.get("provider_type", ""),
            api_base=body.get("api_base", ""), config=body.get("config"))
        return web.json_response(provider, status=201)

    @routes.delete("/llm/providers/{provider_id}")
    async def delete_provider(request: web.Request) -> web.Response:
        request["auth"].require("llm.admin")
        await request.app["llm_provider_service"].delete_provider(
            request.match_info["provider_id"])
        return web.Response(status=204)

    @routes.get("/llm/models")
    async def list_models(request: web.Request) -> web.Response:
        request["auth"].require("llm.admin")
        return web.json_response(await request.app["llm_provider_service"].list_models())

    @routes.post("/llm/providers/{provider_id}/models")
    async def add_model(request: web.Request) -> web.Response:
        request["auth"].require("llm.admin")
        body = await request.json()
        model = await request.app["llm_provider_service"].add_model(
            request.match_info["provider_id"], model_id=body.get("model_id", ""),
            alias=body.get("alias", ""),
            supports_chat=bool(body.get("supports_chat", True)),
            supports_embeddings=bool(body.get("supports_embeddings", False)))
        return web.json_response(model, status=201)

    # ------------------------------------------------- engine introspection
    @routes.get("/admin/engine/steps")
    async def engine_steps(request: web.Request) -> web.Response:
        """Last N engine step summaries from the in-engine ring buffer
        (step kind, batch size, padded shape, duration, tokens emitted) —
        the operator's 'what is the scheduler actually dispatching right
        now' answer for the admin UI. Read-only."""
        request["auth"].require("observability.read")
        from ..services.diagnostics_service import (engine_introspection,
                                                    live_tpu_engine)
        engine = live_tpu_engine(request.app)
        if engine is None:
            raise NotFoundError("tpu_local engine is not enabled")
        try:
            limit = int(request.query.get("limit", "64"))
        except ValueError as exc:
            raise ValidationFailure("limit must be an integer") from exc
        return web.json_response(
            engine_introspection(engine, limit=max(1, min(limit, 1024))))

    @routes.get("/admin/gateway/requests")
    async def gateway_requests(request: web.Request) -> web.Response:
        """The gateway flight recorder's rings (gateway/flight_recorder.py):
        slowest-N requests retained by duration plus the recency window,
        each row carrying its phase vector (edge/auth/plugins/db/engine/
        serialize/handler/error ms) and trace ids, alongside event-loop
        health and the engine-pool backpressure view — the HTTP-tier
        answer to /admin/engine/steps. Read-only."""
        request["auth"].require("observability.read")
        recorder = request.app.get("flight_recorder")
        if recorder is None:
            raise NotFoundError(
                "gateway flight recorder is disabled "
                "(set MCPFORGE_GW_FLIGHT_RECORDER_ENABLED=true)")
        try:
            limit = int(request.query.get("limit", "32"))
        except ValueError as exc:
            raise ValidationFailure("limit must be an integer") from exc
        snapshot = recorder.snapshot(limit=max(1, min(limit, 1024)),
                                     tenant=request.query.get("tenant"))
        sampler = request.app.get("loop_lag_sampler")
        snapshot["loop"] = sampler.snapshot() if sampler is not None else None
        from .flight_recorder import queue_state
        snapshot["backpressure"] = queue_state(request.app)
        # degradation ladder summary (docs/resilience.md): per-component
        # breaker states ride the gateway tab next to backpressure —
        # "disk tier quarantined" belongs on the same screen as queue
        # depth (full detail incl. transitions at GET /admin/faults)
        from ..observability.degradation import get_degradation
        snapshot["degradation"] = get_degradation().status()["components"]
        shedder = request.app.get("overload_shedder")
        snapshot["shed_total"] = (shedder.shed_total
                                  if shedder is not None else None)
        return web.json_response(snapshot)

    @routes.get("/admin/controller")
    async def controller_state(request: web.Request) -> web.Response:
        """The closed-loop serving controller's audit surface
        (tpu_local/controller.py, docs/controller.md): the bounded
        decision ring — signal snapshot in, knob delta out, observed
        effect after the eval window — plus per-replica knob state, the
        live signal-bus aggregates the decisions were made from, and
        the controller's own configuration. Read-only: knobs are only
        ever moved by the control loop itself. Answers "why did K drop
        on replica 1 at 14:03" with the exact numbers it saw."""
        request["auth"].require("observability.read")
        controller = request.app.get("serving_controller")
        if controller is None:
            raise NotFoundError(
                "serving controller is disabled "
                "(set MCPFORGE_CONTROLLER_ENABLED=true)")
        try:
            limit = int(request.query.get("limit", "64"))
        except ValueError as exc:
            raise ValidationFailure("limit must be an integer") from exc
        return web.json_response(
            controller.snapshot(limit=max(1, min(limit, 1024))))

    def _trace_store_or_404(request: web.Request):
        store = request.app.get("trace_store")
        if store is None:
            raise NotFoundError(
                "request forensics trace store is disabled "
                "(set MCPFORGE_TRACE_STORE_ENABLED=true)")
        return store

    @routes.get("/admin/trace")
    async def trace_list(request: web.Request) -> web.Response:
        """Retention stats + newest-first retained trace summaries from
        the tail-sampled trace store (observability/trace_store.py):
        what survived (errors, SLO breaches, slowest per route/tenant,
        exemplar pins, the 1-in-M sample) and why. Read-only."""
        request["auth"].require("observability.read")
        store = _trace_store_or_404(request)
        try:
            limit = int(request.query.get("limit", "64"))
        except ValueError as exc:
            raise ValidationFailure("limit must be an integer") from exc
        return web.json_response(store.snapshot(
            limit=max(1, min(limit, 1024))))

    @routes.get("/admin/trace/{trace_id}")
    async def trace_waterfall(request: web.Request) -> web.Response:
        """THE cross-layer waterfall for one retained trace: the span
        tree (gateway -> provider -> engine -> KV tiers -> pool requeue
        hops), the flight recorder's phase vector, and the engine
        step-ring rows each decode span overlapped (superstep, phases,
        mfu/hbm_frac) — with containment / sum-of-children invariants.
        A p99 exemplar on /metrics clicks through to here. Read-only."""
        request["auth"].require("observability.read")
        store = _trace_store_or_404(request)
        trace_id = request.match_info["trace_id"]
        entry = store.get(trace_id)
        if entry is None:
            raise NotFoundError(
                f"trace {trace_id} is not retained (tail sampling keeps "
                "errors, SLO breaches, slowest-N, exemplars, and a 1-in-"
                f"{store.sample_every} sample); the head-sampled span "
                f"ring at /admin/traces/{trace_id} may still have it")
        from ..observability.trace_store import stitch_waterfall
        recorder = request.app.get("flight_recorder")
        gateway_row = (recorder.find_trace(trace_id)
                       if recorder is not None else None)
        engines: dict = {}
        pool = request.app.get("tpu_engine_pool")
        if pool is not None:
            engines = {r.id: r.engine for r in pool.replicas}
        else:
            engine = request.app.get("tpu_engine")
            if engine is not None:
                engines = {engine.config.replica_id: engine}
        waterfall = stitch_waterfall(entry["spans"],
                                     gateway_row=gateway_row,
                                     engines=engines)
        waterfall["retention"] = {k: entry[k] for k in
                                  ("reasons", "breaches", "route",
                                   "tenant", "status", "truncated")}
        return web.json_response(waterfall)

    @routes.get("/admin/tenants/usage")
    async def tenant_usage(request: web.Request) -> web.Response:
        """Per-tenant usage metering (observability/metering.py): the
        live ledger (prompt/generated/cache-hit tokens, KV-page-seconds,
        current quota window) plus recent rows from the tenant_usage
        rollup table — the accounting plane ROADMAP item 5's distributed
        rate limiter consumes. Read-only."""
        request["auth"].require("observability.read")
        ledger = request.app.get("tenant_ledger")
        if ledger is None:
            raise NotFoundError(
                "tenant metering is disabled "
                "(set MCPFORGE_TENANT_METERING_ENABLED=true)")
        try:
            limit = int(request.query.get("limit", "64"))
        except ValueError as exc:
            raise ValidationFailure("limit must be an integer") from exc
        payload = ledger.snapshot(limit=max(1, min(limit, 1024)))
        rollup = request.app.get("tenant_usage_rollup")
        payload["rollups"] = (await rollup.recent(limit=min(limit * 2, 200))
                              if rollup is not None else [])
        payload["rollup_interval_s"] = (rollup.interval_s
                                        if rollup is not None else None)
        return web.json_response(payload)

    # ------------------------------------------------ prefix-cache fabric

    @routes.post("/admin/fabric/adverts")
    async def fabric_adverts_exchange(request: web.Request) -> web.Response:
        """Cross-supervisor fabric gossip (docs/cache_fabric.md): a peer
        host POSTs its chain-head advert batch; we merge it into the
        local fabric index and reply with OUR adverts — the exchange is
        bidirectional, so a one-way peer list still converges both ways.
        In-fleet workers use the ``fabric.advert`` bus method instead;
        this endpoint is the hop between supervisors."""
        request["auth"].require("admin.all")
        publisher = request.app.get("fabric_publisher")
        if publisher is None or publisher.store is None \
                or getattr(publisher.store, "object_store", None) is None:
            raise NotFoundError(
                "prefix-cache fabric is not enabled "
                "(set MCPFORGE_TPU_LOCAL_TIER_OBJECT_URL)")
        try:
            body = await request.json()
        except json.JSONDecodeError as exc:
            raise ValidationFailure(f"invalid JSON: {exc}") from exc
        if not isinstance(body, dict):
            raise ValidationFailure("body must be an advert batch object")
        try:
            reply = await publisher.handle_advert(body)
        except ValueError as exc:
            raise ValidationFailure(str(exc)) from exc
        return web.json_response(reply)

    @routes.get("/admin/fabric/adverts")
    async def fabric_adverts_status(request: web.Request) -> web.Response:
        """Fabric observability: publisher gossip counters plus the tier
        store's T3/fabric-index stats (read-only twin of the POST
        exchange — operators and the bench read this)."""
        request["auth"].require("observability.read")
        publisher = request.app.get("fabric_publisher")
        if publisher is None:
            raise NotFoundError("prefix-cache fabric is not wired")
        payload = publisher.stats()
        store = publisher.store
        payload["store"] = store.stats() if store is not None else None
        return web.json_response(payload)

    # ------------------------------------------- fault plane + degradation

    @routes.get("/admin/faults")
    async def faults_status(request: web.Request) -> web.Response:
        """The resilience plane's status surface: armed fault rules
        (with fired/call counts), the legal fault-point catalogue, and
        the degradation ladder — per-component breaker states, bounded
        transition history, rollup outage stats (docs/resilience.md).
        Readable even with injection disabled: the degradation half is
        production telemetry, not a chaos tool."""
        request["auth"].require("observability.read")
        from ..observability.degradation import get_degradation
        from ..observability.faults import get_fault_plane
        payload = get_fault_plane().snapshot()
        payload["degradation"] = get_degradation().status()
        rollup = request.app.get("tenant_usage_rollup")
        if rollup is not None:
            payload["degradation"]["rollup"] = rollup.outage_stats()
        shedder = request.app.get("overload_shedder")
        if shedder is not None:
            payload["shedder"] = {
                "enabled": shedder.enabled,
                "shed_at": shedder.shed_at,
                "class_order": shedder.class_order,
                "shed_total": shedder.shed_total,
            }
        return web.json_response(payload)

    @routes.post("/admin/faults")
    async def faults_arm(request: web.Request) -> web.Response:
        """Arm one fault rule (the chaos harness's drive path): body is
        a FaultRule object — {"point", "kind", "mode", "n", "window_s",
        "latency_ms", "scope", "seed", "message"}. 404 unless
        fault_injection_enabled is set (the default-off contract: the
        rule table cannot become non-empty on a production gateway)."""
        request["auth"].require("admin.all")
        from ..observability.faults import FaultRule, get_fault_plane
        plane = get_fault_plane()
        if not plane.enabled:
            raise NotFoundError(
                "fault injection is disabled "
                "(set MCPFORGE_FAULT_INJECTION_ENABLED=true)")
        try:
            body = await request.json()
        except json.JSONDecodeError as exc:
            raise ValidationFailure(f"invalid JSON: {exc}") from exc
        if not isinstance(body, dict):
            raise ValidationFailure("body must be a fault-rule object")
        allowed = ("point", "kind", "mode", "n", "window_s",
                   "latency_ms", "scope", "seed", "message")
        unknown = sorted(set(body) - set(allowed))
        if unknown:
            # fail CLOSED: a typo'd field ("Scope", "latencyMs") must
            # not silently arm a broader fault than the operator asked
            # for (an unscoped always-error db rule takes the whole
            # gateway down instead of one table)
            raise ValidationFailure(
                f"unknown fault-rule field(s) {unknown} "
                f"(allowed: {list(allowed)})")
        try:
            rule = plane.arm(FaultRule(**body))
        except (TypeError, ValueError) as exc:
            raise ValidationFailure(str(exc)) from exc
        return web.json_response(rule.snapshot(), status=201)

    @routes.delete("/admin/faults/{point}")
    async def faults_disarm(request: web.Request) -> web.Response:
        """Disarm one point (no error if it was not armed — disarm is
        the cleanup path and must be idempotent)."""
        request["auth"].require("admin.all")
        from ..observability.faults import get_fault_plane
        removed = get_fault_plane().disarm(request.match_info["point"])
        return web.json_response({"disarmed": removed})

    @routes.delete("/admin/faults")
    async def faults_clear(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        from ..observability.faults import get_fault_plane
        get_fault_plane().clear()
        return web.json_response({"cleared": True})

    @routes.get("/admin/engine/profile/status")
    async def profile_status(request: web.Request) -> web.Response:
        request["auth"].require("observability.read")
        return web.json_response(profiler_or_404(request).status())

    @routes.post("/admin/engine/profile/start")
    async def profile_start(request: web.Request) -> web.Response:
        """Begin an open-ended jax.profiler capture (stop it with
        /admin/engine/profile/stop); operator brackets exactly the
        traffic window they care about."""
        request["auth"].require("admin.all")
        # start_trace/stop_trace write trace files: off the loop
        # (async-blocking-call discipline), serialized by the capture's
        # internal mutex
        import asyncio

        profiler = profiler_or_404(request)
        return web.json_response(await asyncio.to_thread(profiler.start))

    @routes.post("/admin/engine/profile/stop")
    async def profile_stop(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        import asyncio

        profiler = profiler_or_404(request)
        return web.json_response(await asyncio.to_thread(profiler.stop))

    # ---------------------------------------------------------------- plugins
    @routes.get("/plugins")
    async def list_plugins(request: web.Request) -> web.Response:
        request["auth"].require("plugins.manage")
        pm = request.app.get("plugin_manager")
        if pm is None:
            return web.json_response([])
        return web.json_response([{
            "name": p.config.name, "kind": p.config.kind,
            "mode": p.config.mode.value, "priority": p.config.priority,
            "tools": p.config.tools,
        } for p in pm.plugins])

    @routes.post("/plugins/{name}/mode")
    async def set_plugin_mode(request: web.Request) -> web.Response:
        request["auth"].require("plugins.manage")
        body = await request.json()
        ctx = request.app["ctx"]
        name = request.match_info["name"]
        mode = body.get("mode", "enforce")
        from ..plugins.framework import PluginMode
        if mode not in {m.value for m in PluginMode}:
            raise ValidationFailure(
                f"mode must be one of {sorted(m.value for m in PluginMode)}")
        # binding-backed plugins persist the mode so load_bindings()/restart
        # cannot silently revert a runtime disable
        if name.startswith("binding:"):
            await ctx.db.execute("UPDATE plugin_bindings SET mode=? WHERE id=?",
                                 (mode, name.split(":", 1)[1]))
        # runtime enable/disable propagates to every worker over the bus
        await ctx.bus.publish("plugins.control", {"name": name, "mode": mode})
        return web.Response(status=204)

    @routes.post("/plugins/bindings")
    async def create_binding(request: web.Request) -> web.Response:
        request["auth"].require("plugins.manage")
        body = await request.json()
        ctx = request.app["ctx"]
        from ..db.core import to_json as _to_json
        from ..services.base import now as _now
        from ..utils.ids import new_id as _new_id
        binding_id = _new_id()
        await ctx.db.execute(
            "INSERT INTO plugin_bindings (id, plugin_name, scope_type, scope_id,"
            " mode, config, enabled, created_at) VALUES (?,?,?,?,?,?,?,?)",
            (binding_id, body.get("plugin_name", ""),
             body.get("scope_type", "tool"), body.get("scope_id"),
             body.get("mode", "enforce"),
             _to_json(body.get("config", {})), 1, _now()))
        # broadcast reloads every worker (incl. this one via local delivery)
        await ctx.bus.publish("plugins.bindings.changed", {"id": binding_id})
        return web.json_response({"id": binding_id}, status=201)

    @routes.get("/plugins/bindings")
    async def list_bindings(request: web.Request) -> web.Response:
        request["auth"].require("plugins.manage")
        rows = await request.app["ctx"].db.fetchall(
            "SELECT * FROM plugin_bindings ORDER BY created_at")
        return web.json_response(rows)

    @routes.delete("/plugins/bindings/{binding_id}")
    async def delete_binding(request: web.Request) -> web.Response:
        request["auth"].require("plugins.manage")
        await request.app["ctx"].db.execute(
            "DELETE FROM plugin_bindings WHERE id=?",
            (request.match_info["binding_id"],))
        await request.app["ctx"].bus.publish("plugins.bindings.changed",
                                             {"id": request.match_info["binding_id"]})
        return web.Response(status=204)

    # ---------------------------------------------------------- export/import
    @routes.get("/export")
    async def export_config(request: web.Request) -> web.Response:
        request["auth"].require("export.run")
        bundle = await request.app["export_service"].export_all(
            include_secrets=request.query.get("include_secrets") == "true")
        return web.json_response(bundle)

    @routes.post("/import")
    async def import_config(request: web.Request) -> web.Response:
        request["auth"].require("import.run")
        body = await request.json()
        summary = await request.app["export_service"].import_all(
            body, overwrite=request.query.get("overwrite") == "true")
        return web.json_response(summary)

    # ---------------------------------------- MCP Apps (ui:// AppBridge)
    # Reference main.py:10508 (create) / :10576 (session-scoped tools/call)

    def _apps(request: web.Request):
        service = request.app.get("mcp_apps_service")
        if service is None:
            raise web.HTTPNotFound(reason="MCP Apps are disabled")
        return service

    @routes.post("/appbridge/sessions")
    async def create_app_session(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("resources.read")
        service = _apps(request)
        body = await request.json()
        session = await service.create_session(
            mcp_session_id=(body.get("mcpSessionId")
                            or request.headers.get("mcp-session-id", "")),
            user=auth.user,
            server_id=body.get("serverId") or body.get("server_id") or "",
            resource_uri=body.get("resourceUri") or body.get("resource_uri") or "")
        return web.json_response(session, status=201)

    @routes.post("/appbridge/sessions/{app_session_id}/rpc")
    async def app_session_rpc(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("tools.invoke")
        service = _apps(request)
        body = await request.json()
        rpc_id = body.get("id")
        if body.get("method") != "tools/call":
            return web.json_response({
                "jsonrpc": "2.0", "id": rpc_id,
                "error": {"code": -32601,
                          "message": "AppBridge sessions only allow tools/call"}})
        mcp_session_id = (body.get("mcpSessionId")
                          or request.headers.get("mcp-session-id", ""))
        session = await service.get_valid_session(
            request.match_info["app_session_id"], mcp_session_id,
            auth.user, is_admin=auth.is_admin)
        if session is None:
            return web.json_response({
                "jsonrpc": "2.0", "id": rpc_id,
                "error": {"code": -32003, "message": "Access denied"}})
        from ..jsonrpc import JSONRPCError, RPCRequest, error_response
        try:
            response = await request.app["dispatcher"].dispatch(
                RPCRequest.parse(body), auth,
                headers=dict(request.headers),
                server_id=session["server_id"])
        except JSONRPCError as exc:
            return web.json_response(error_response(rpc_id, exc.code,
                                                    str(exc)))
        return web.json_response(response)

    app.add_routes(routes)
