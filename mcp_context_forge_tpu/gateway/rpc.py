"""JSON-RPC / MCP method dispatcher.

Reference: the method switch in `_handle_rpc_authenticated`
(`/root/reference/mcpgateway/main.py:11109`) and `_execute_rpc_tools_call`
(`main.py:10383`). Here it is a handler table over the service layer; the
same dispatcher serves ``POST /rpc`` and the ``/mcp`` streamable-HTTP
transport (and per-virtual-server mounts which scope the catalog).
"""

from __future__ import annotations

import logging
from typing import Any

from .. import PROTOCOL_VERSION, SUPPORTED_PROTOCOL_VERSIONS
from ..jsonrpc import (
    INTERNAL_ERROR,
    INVALID_PARAMS,
    METHOD_NOT_FOUND,
    JSONRPCError,
    RPCRequest,
    method_registry,
    result_response,
)
from ..observability import phases as request_phases
from ..services.base import AppContext, NotFoundError, ValidationFailure
from ..services.auth_service import AuthContext, PermissionDenied
from .serialize import jsonrpc_response_bytes

logger = logging.getLogger(__name__)


class RPCDispatcher:
    def __init__(self, ctx: AppContext, tool_service, resource_service,
                 prompt_service, server_service, completion_service=None,
                 sampling_handler=None):
        self.ctx = ctx
        self.tools = tool_service
        self.resources = resource_service
        self.prompts = prompt_service
        self.servers = server_service
        self.completion = completion_service
        self.sampling = sampling_handler
        self._log_level = "info"

    async def dispatch(self, request: RPCRequest, auth: AuthContext,
                       headers: dict[str, str] | None = None,
                       server_id: str | None = None) -> dict[str, Any] | None:
        """Handle one JSON-RPC request; returns the response (None for
        notifications). ``server_id`` scopes the catalog to a virtual server
        (also enforced for server-scoped tokens, reference main.py:11200)."""
        method = request.method
        params = request.params
        headers = headers or {}
        # server-scoped token enforcement
        if auth.server_id and server_id and auth.server_id != server_id:
            raise JSONRPCError(INVALID_PARAMS, "Token is scoped to a different server")
        effective_server = server_id or auth.server_id

        if request.is_notification or method_registry.is_notification(method):
            await self._handle_notification(method, params, auth)
            return None

        with self.ctx.tracer.span(f"rpc.{method}", {"rpc.method": method,
                                                    "user": auth.user}):
            try:
                result = await self._route(method, params, auth, headers,
                                           effective_server, rpc_id=request.id)
            except JSONRPCError:
                raise
            except NotFoundError as exc:
                raise JSONRPCError(INVALID_PARAMS, str(exc)) from exc
            except PermissionDenied as exc:
                raise JSONRPCError(-32004, str(exc)) from exc
            except ValidationFailure as exc:
                raise JSONRPCError(INVALID_PARAMS, str(exc)) from exc
            except Exception as exc:
                logger.exception("RPC %s failed", method)
                raise JSONRPCError(INTERNAL_ERROR, f"{type(exc).__name__}: {exc}") from exc
        return result_response(request.id, result)

    async def dispatch_bytes(self, request: RPCRequest, auth: AuthContext,
                             headers: dict[str, str] | None = None,
                             server_id: str | None = None) -> bytes | None:
        """``dispatch`` with the response pre-encoded to wire bytes.

        The zero-copy seam for byte-oriented callers (``POST /rpc``):
        the JSON-RPC envelope is assembled from constant fragments around
        one compact result encode (gateway/serialize.py), and the encode
        cost is charged to the flight recorder's ``serialize`` bucket
        here — per route, not as ``handler`` residue."""
        response = await self.dispatch(request, auth, headers=headers,
                                       server_id=server_id)
        if response is None:
            return None
        with request_phases.phase("serialize"):
            return jsonrpc_response_bytes(response)

    async def _route(self, method: str, params: dict[str, Any], auth: AuthContext,
                     headers: dict[str, str], server_id: str | None,
                     rpc_id: Any = None) -> Any:
        if method == "initialize":
            return await self._initialize(params)
        if method == "ping":
            return {}
        if method == "tools/list":
            auth.require("tools.read")
            tools = await self.tools.list_tools(team_ids=auth.teams)
            if server_id:
                allowed = set(await self.servers.server_tool_names(server_id))
                tools = [t for t in tools if t.name in allowed]
            return {"tools": [{
                "name": t.name,
                "description": t.description or "",
                "inputSchema": t.input_schema or {"type": "object"},
                **({"outputSchema": t.output_schema} if t.output_schema else {}),
                **({"annotations": t.annotations} if t.annotations else {}),
            } for t in tools]}
        if method == "tools/call":
            auth.require("tools.invoke")
            name = params.get("name")
            if not name:
                raise JSONRPCError(INVALID_PARAMS, "tools/call requires 'name'")
            if server_id:
                allowed = set(await self.servers.server_tool_names(server_id))
                if name not in allowed:
                    raise JSONRPCError(INVALID_PARAMS,
                                       f"Tool {name!r} not in server scope")
            import asyncio as _asyncio
            run = _asyncio.ensure_future(self.tools.invoke_tool(
                name, params.get("arguments", {}) or {}, request_headers=headers,
                user=auth.user))
            cancellation = self.ctx.extras.get("cancellation_service")
            if cancellation is not None:
                # keys are scoped by user: raw JSON-RPC ids collide across
                # clients (everyone uses id=1) and an unscoped key would let
                # one user cancel another's run
                for key in (rpc_id, (params.get("_meta") or {}).get("requestId"),
                            headers.get("x-request-id")):
                    if key is not None:
                        cancellation.register(f"{auth.user}:{key}", run)
            try:
                return await run
            except _asyncio.CancelledError:
                if run.cancelled():
                    raise JSONRPCError(-32800, "Request cancelled") from None
                # the HANDLER was cancelled (client disconnect/shutdown):
                # propagate, and don't leak the still-running tool task
                run.cancel()
                raise
        if method == "resources/list":
            auth.require("resources.read")
            resources = await self.resources.list_resources()
            return {"resources": [{
                "uri": r.uri, "name": r.name,
                **({"description": r.description} if r.description else {}),
                **({"mimeType": r.mime_type} if r.mime_type else {}),
            } for r in resources if not r.uri_template]}
        if method == "resources/templates/list":
            auth.require("resources.read")
            return {"resourceTemplates": await self.resources.list_templates()}
        if method == "resources/read":
            auth.require("resources.read")
            uri = params.get("uri")
            if not uri:
                raise JSONRPCError(INVALID_PARAMS, "resources/read requires 'uri'")
            pm = self.ctx.plugin_manager
            if pm is not None:
                uri = await pm.resource_pre_fetch(uri, user=auth.user)
            result = await self.resources.read_resource(uri, request_headers=headers)
            if pm is not None:
                result = await pm.resource_post_fetch(uri, result, user=auth.user)
            return result
        if method == "resources/subscribe":
            auth.require("resources.read")
            await self.resources.subscribe(params.get("uri", ""),
                                           headers.get("mcp-session-id", "anonymous"))
            return {}
        if method == "resources/unsubscribe":
            await self.resources.unsubscribe(params.get("uri", ""),
                                             headers.get("mcp-session-id", "anonymous"))
            return {}
        if method == "prompts/list":
            auth.require("prompts.read")
            prompts = await self.prompts.list_prompts()
            return {"prompts": [{
                "name": p.name,
                **({"description": p.description} if p.description else {}),
                "arguments": [a.model_dump(exclude_none=True) for a in p.arguments],
            } for p in prompts]}
        if method == "prompts/get":
            auth.require("prompts.read")
            name = params.get("name")
            if not name:
                raise JSONRPCError(INVALID_PARAMS, "prompts/get requires 'name'")
            pm = self.ctx.plugin_manager
            args = params.get("arguments", {}) or {}
            if pm is not None:
                name, args = await pm.prompt_pre_fetch(name, args, user=auth.user)
            result = await self.prompts.render_prompt(name, args)
            if pm is not None:
                result = await pm.prompt_post_fetch(name, result, user=auth.user)
            return result
        if method == "roots/list":
            return {"roots": []}
        if method == "completion/complete":
            if self.completion is not None:
                return await self.completion.complete(params)
            return {"completion": {"values": [], "total": 0, "hasMore": False}}
        if method == "sampling/createMessage":
            if self.sampling is not None:
                return await self.sampling.create_message(params, user=auth.user)
            raise JSONRPCError(METHOD_NOT_FOUND, "Sampling not configured")
        if method == "logging/setLevel":
            level = params.get("level", "info")
            self._log_level = level
            return {}
        if method == "elicitation/create":
            raise JSONRPCError(METHOD_NOT_FOUND, "Elicitation requires a connected client")
        if method_registry.is_known(method):
            raise JSONRPCError(METHOD_NOT_FOUND, f"Method {method!r} not implemented")
        raise JSONRPCError(METHOD_NOT_FOUND, f"Unknown method {method!r}")

    async def _initialize(self, params: dict[str, Any]) -> dict[str, Any]:
        client_version = params.get("protocolVersion", PROTOCOL_VERSION)
        version = client_version if client_version in SUPPORTED_PROTOCOL_VERSIONS \
            else PROTOCOL_VERSION
        return {
            "protocolVersion": version,
            "capabilities": {
                "tools": {"listChanged": True},
                "resources": {"subscribe": True, "listChanged": True},
                "prompts": {"listChanged": True},
                "logging": {},
                "completions": {},
            },
            "serverInfo": {"name": self.ctx.settings.app_name, "version": "0.1.0"},
        }

    async def _handle_notification(self, method: str, params: dict[str, Any],
                                   auth: AuthContext | None = None) -> None:
        if method == "notifications/initialized":
            return
        if method == "notifications/cancelled":
            cancellation = self.ctx.extras.get("cancellation_service")
            if cancellation is not None and params.get("requestId") is not None:
                user = auth.user if auth is not None else "anonymous"
                await cancellation.cancel(f"{user}:{params.get('requestId')}")
            return
        # progress/message notifications are accepted and dropped at the edge
        return
