"""Shared zero-copy response serialization: SSE framing + JSON-RPC envelopes.

The flight recorder's phase vectors put ``serialize`` among the dominant
buckets after the PR-16 auth fix, and the per-event pattern
``resp.write(b"data: " + json.dumps(event).encode() + b"\n\n")`` was
duplicated across three hot loops (gateway/routers_chat.py, the LLM
surface in tpu_local/server.py, and the /mcp transport). This module is
the ONE encoder all of them share:

- ``encode_json``: compact separators (no space after ``,``/``:``),
  ``ensure_ascii=False`` so multi-byte text is emitted as UTF-8 instead
  of 6-byte ``\\uXXXX`` escapes — both smaller wire bytes and less
  encoder work per event;
- SSE framing is pre-built module-level byte constants joined with one
  ``b"".join`` per event (no repeated bytes-concat reallocations);
- JSON-RPC response envelopes are assembled from pre-encoded fragments
  around the result payload, skipping a second dict walk over the
  envelope — the ``handler`` residue the phase vectors could not
  attribute now lands in an explicit ``serialize`` charge at the /rpc
  route (observability/phases.py).

Streams produced before and after this module must be byte-identical
given the same events (tests/unit/test_serialize.py pins it), so SSE
resume/handoff byte-equality contracts (docs/scaleout.md) are untouched.
"""

from __future__ import annotations

import json
from typing import Any

# SSE framing fragments (the wire grammar around every event)
SSE_DATA = b"data: "
SSE_END = b"\n\n"
SSE_DONE = b"data: [DONE]\n\n"

# JSON-RPC 2.0 response envelope fragments (jsonrpc.result_response as bytes)
_ENV_HEAD = b'{"jsonrpc":"2.0","id":'
_ENV_RESULT = b',"result":'
_ENV_TAIL = b'}'


def encode_json(obj: Any) -> bytes:
    """THE compact encoder: every SSE/JSON-RPC byte producer rides this."""
    return json.dumps(obj, separators=(",", ":"),
                      ensure_ascii=False).encode()


def sse_event(event: Any) -> bytes:
    """One SSE ``data:`` frame for ``event`` (pre-built framing bytes)."""
    return b"".join((SSE_DATA, encode_json(event), SSE_END))


def jsonrpc_result_bytes(request_id: Any, result: Any) -> bytes:
    """Encode ``{"jsonrpc":"2.0","id":...,"result":...}`` from fragments.

    Only the two variable payloads (id, result) are JSON-encoded; the
    envelope itself is constant bytes. Matches ``encode_json(
    result_response(id, result))`` byte-for-byte (key order pinned by
    jsonrpc.result_response's literal)."""
    return b"".join((_ENV_HEAD, encode_json(request_id),
                     _ENV_RESULT, encode_json(result), _ENV_TAIL))


def jsonrpc_response_bytes(response: dict[str, Any]) -> bytes:
    """Bytes for an already-built JSON-RPC response dict.

    Result responses in canonical ``result_response`` shape take the
    fragment fast path; anything else (error responses, extra keys)
    falls back to the shared compact encoder."""
    if (len(response) == 3 and "result" in response
            and response.get("jsonrpc") == "2.0" and "id" in response):
        return jsonrpc_result_bytes(response["id"], response["result"])
    return encode_json(response)
