"""Cursor (keyset) pagination for list endpoints.

Reference: `utils/pagination` + cursor params on every list router
(`/root/reference/mcpgateway/main.py:3575-3586` routers use
``cursor``/``limit``). Semantics carried over:

- ``?limit=N`` caps the page; ``?cursor=...`` resumes AFTER the item the
  cursor names. Keyset (sort-key anchored), not offset — concurrent
  inserts/deletes shift no pages.
- The cursor is opaque (urlsafe base64 of the anchor key); a cursor that
  doesn't decode is a 422, not a silent first page (a truncated cursor
  silently restarting would duplicate work for paging clients).
- Requests with NEITHER param keep the legacy whole-list response shape,
  so existing clients (and the admin UI tables) are unaffected.

Services return materialized pydantic lists (entity counts are
thousands, not millions), so the page is cut router-side over a
deterministic sort — one implementation for every endpoint instead of
N bespoke SQL variants; the DB tier already bounds result sets.
"""

from __future__ import annotations

import base64
import binascii
import json
from typing import Any, Callable, Sequence

from aiohttp import web

from ..services.base import ValidationFailure

MAX_PAGE = 500


def encode_cursor(key: Any) -> str:
    raw = json.dumps(key, separators=(",", ":")).encode()
    return base64.urlsafe_b64encode(raw).decode().rstrip("=")


def decode_cursor(cursor: str) -> Any:
    try:
        pad = "=" * (-len(cursor) % 4)
        return json.loads(base64.urlsafe_b64decode(cursor + pad))
    except (binascii.Error, json.JSONDecodeError, UnicodeDecodeError) as exc:
        raise ValidationFailure(f"Invalid cursor: {exc}") from exc


def paginate(request: web.Request, items: Sequence[Any],
             dump: Callable[[Any], Any],
             key: Callable[[Any], Any] = None) -> web.Response:
    """Respond with a page (``{"items", "next_cursor", "total"}``) when the
    request carries ``limit``/``cursor``, else the legacy full list."""
    limit_q = request.query.get("limit")
    cursor_q = request.query.get("cursor")
    if limit_q is None and cursor_q is None:
        return web.json_response(dump(list(items)))
    if key is None:
        # human-facing order (name/uri), with id appended so the sort key
        # is UNIQUE — keyset pagination with duplicate anchor keys would
        # silently skip the duplicates on resume
        def key(item):
            label = (getattr(item, "name", None)
                     or getattr(item, "uri", None) or "")
            return f"{label}\x00{getattr(item, 'id', '')}"
    settings = request.app["ctx"].settings
    max_page = settings.pagination_max_page_size or MAX_PAGE
    min_page = max(1, settings.pagination_min_page_size)
    try:
        limit = max(min_page,
                    min(int(limit_q or settings.pagination_default_page_size),
                        max_page))
    except ValueError as exc:
        raise ValidationFailure(f"Invalid limit: {limit_q!r}") from exc
    ordered = sorted(items, key=lambda item: str(key(item)))
    start = 0
    if cursor_q:
        anchor = str(decode_cursor(cursor_q))
        # resume strictly after the anchor key; a deleted anchor resumes
        # at the first surviving key past it (keyset semantics)
        while start < len(ordered) and str(key(ordered[start])) <= anchor:
            start += 1
    page = ordered[start:start + limit]
    more = start + limit < len(ordered)
    next_cursor = (encode_cursor(str(key(page[-1])))
                   if more and page else None)
    body = {
        "items": dump(page),
        "next_cursor": next_cursor,
        "total": len(ordered),
    }
    if settings.pagination_include_links:
        # RFC 8288-style affordance (reference pagination_include_links):
        # clients follow `links.next` instead of assembling the query
        from yarl import URL
        body["links"] = {
            "self": str(request.rel_url),
            "next": (str(URL(request.rel_url.path).with_query(
                {**request.query, "cursor": next_cursor,
                 "limit": str(limit)}))
                     if next_cursor else None),
        }
    return web.json_response(body)
