"""Application factory + lifespan.

Reference: `lifespan()` in `/root/reference/mcpgateway/main.py:1429-1760` —
logging → DB bootstrap → bus → services → plugins → telemetry → transports.
Same ordering here via aiohttp cleanup contexts.
"""

from __future__ import annotations

import asyncio
import json
import logging
from pathlib import Path
from typing import AsyncIterator

from aiohttp import web

from ..config import Settings, get_settings
from ..coordination import make_bus, make_lease_manager
from ..coordination.leases import LeaderElector
from ..db import Database, MIGRATIONS
from ..observability import init_tracer, PrometheusRegistry
from ..observability.logging import init_logging
from ..services.auth_service import AuthService
from ..services.base import AppContext
from ..services.gateway_service import GatewayService
from ..services.prompt_service import PromptService
from ..services.resource_service import ResourceService
from ..services.server_service import ServerService
from ..services.tool_service import ToolService
from .middleware import MIDDLEWARES, RateLimiter
from .routers import setup_routes
from .rpc import RPCDispatcher
from .transports.streamable_http import StreamableHTTPTransport
from ..jsonrpc import JSONRPCError, RPCRequest, parse_body

logger = logging.getLogger(__name__)


async def build_app(settings: Settings | None = None) -> web.Application:
    settings = settings or get_settings()
    init_logging(settings.log_level, settings.log_json,
                 buffer_capacity=settings.log_buffer_capacity,
                 file_path=(str(Path(settings.log_folder)
                                / settings.log_file)
                            if settings.log_to_file else None),
                 rotation=settings.log_rotation_enabled,
                 max_mb=settings.log_max_size_mb,
                 backup_count=settings.log_backup_count)

    problems = settings.validate_security()
    if problems:
        raise RuntimeError(f"Refusing to start with insecure configuration: {problems}")

    app = web.Application(middlewares=MIDDLEWARES,
                          client_max_size=settings.max_request_size_bytes)

    from ..db.pg import make_database
    db = make_database(settings.database_url, settings.db_pool_size,
                       busy_timeout_ms=settings.db_sqlite_busy_timeout_ms,
                       max_retries=settings.db_max_retries,
                       retry_interval_ms=settings.db_retry_interval_ms)
    await db.connect()
    await db.migrate(MIGRATIONS)

    hub = None
    hub_client = None
    if settings.bus_backend == "tcp":
        from ..coordination.hub import (CoordinationHub, HubClient, TcpEventBus,
                                        TcpLeaseManager)
        # the hub authenticates workers: bus payloads carry trusted auth
        # context (affinity forwards), so cross-host pub/sub must not be open
        bus_secret = settings.bus_tcp_secret or settings.jwt_secret_key
        if settings.bus_tcp_serve:
            hub = CoordinationHub(settings.bus_tcp_host, settings.bus_tcp_port,
                                  secret=bus_secret)
            await hub.start()
            app["coordination_hub"] = hub
        hub_client = HubClient(settings.bus_tcp_host,
                               hub.bound_port if hub else settings.bus_tcp_port,
                               secret=bus_secret)
        from ..coordination.kv import TcpKVStore

        bus = TcpEventBus(hub_client)
        leases = TcpLeaseManager(hub_client)
        kv_store = TcpKVStore(hub_client)
    else:
        from ..coordination.kv import make_kv

        bus = make_bus(settings.bus_backend, settings.bus_dir)
        leases = make_lease_manager(settings.bus_backend, settings.bus_dir)
        kv_store = make_kv(settings.bus_backend, settings.bus_dir)
    app["kv_store"] = kv_store
    tracer = init_tracer(settings.otel_service_name,
                         settings.otel_exporter if settings.otel_enable else "none")
    # one tenant clamp shared by the metric registry and the usage
    # ledger: bounded tenant label cardinality (top-N + "other"),
    # identical admission on both sides (docs/multitenancy.md)
    from ..observability.tenant import TenantClamp
    from ..observability.trace_store import ExemplarLedger
    tenant_clamp = TenantClamp(settings.tenant_label_clamp)
    metrics = PrometheusRegistry(
        tenant_clamp=tenant_clamp,
        exemplars=ExemplarLedger(enabled=settings.metrics_exemplars))

    ctx = AppContext(settings=settings, db=db, bus=bus, leases=leases,
                     tracer=tracer, metrics=metrics)

    # cross-worker RPC seam (coordination/rpc.py, docs/scaleout.md):
    # elicit/SSE handoff and the shared engine plane all ride this one
    # bus-addressed request/stream layer; subscriptions open in the
    # lifecycle after bus.start()
    from ..coordination.rpc import BusRpc
    bus_rpc = BusRpc(bus, ctx.worker_id, leases=leases,
                     default_timeout_s=settings.gw_rpc_timeout_s,
                     idle_timeout_s=settings.gw_stream_idle_timeout_s)
    app["bus_rpc"] = bus_rpc
    ctx.extras["bus_rpc"] = bus_rpc

    # per-worker metrics aggregation (observability/fleet.py): workers
    # publish their exposition on the bus so any worker can answer
    # /metrics/prometheus?scope=fleet and /admin/slo?scope=fleet with
    # fleet-wide truth
    fleet_metrics = None
    if settings.gw_fleet_metrics:
        from ..observability.fleet import FleetMetrics
        fleet_metrics = FleetMetrics(
            bus, ctx.worker_id, metrics,
            interval_s=settings.gw_fleet_metrics_interval_s)
        app["fleet_metrics"] = fleet_metrics
        ctx.extras["fleet_metrics"] = fleet_metrics

    if settings.otel_db_store:
        # in-DB trace store (reference observability_service: separate-path
        # writes so spans survive failed request transactions). Sampled:
        # errors always, successes over the slow threshold.
        import asyncio as _aio
        import json as _json

        def _db_sink(span) -> None:
            if span.status != "ERROR" and (
                    span.duration_ms or 0) < settings.otel_db_min_duration_ms:
                return

            async def _write() -> None:
                try:
                    await db.execute(
                        "INSERT OR IGNORE INTO observability_spans (span_id,"
                        " trace_id, parent_span_id, name, start_ts, end_ts,"
                        " status, attributes) VALUES (?,?,?,?,?,?,?,?)",
                        (span.span_id, span.trace_id, span.parent_span_id,
                         span.name, span.start_ts, span.end_ts, span.status,
                         _json.dumps({k: str(v) for k, v in
                                      span.attributes.items()})))
                except Exception:
                    pass

            try:
                _aio.get_running_loop().create_task(_write())
            except RuntimeError:
                pass  # span finished outside the loop (tests)

        tracer.add_sink(_db_sink)

    otlp_exporter = None
    if settings.otel_enable and settings.otel_otlp_endpoint:
        # OTLP/HTTP wire export (reference observability.py:970) — runs
        # alongside the memory/db sinks
        import json as _json

        from ..observability.otlp import OTLPExporter
        headers = (_json.loads(settings.otel_otlp_headers)
                   if settings.otel_otlp_headers else None)
        otlp_exporter = OTLPExporter(ctx, settings.otel_otlp_endpoint,
                                     settings.otel_service_name, headers,
                                     max_retries=settings.otel_otlp_retry_max)
        tracer.add_sink(otlp_exporter.sink)
        app["otlp_exporter"] = otlp_exporter

    # request forensics plane (observability/trace_store.py): the
    # tail-sampled trace store rides the tracer as one more sink, next
    # to the OTLP exporter — errors, SLO breaches, slowest-N per
    # route/tenant, exemplar-pinned traces, and a deterministic sample
    # survive; GET /admin/trace/{id} stitches the cross-layer waterfall
    if settings.trace_store_enabled:
        from ..observability.trace_store import TraceStore
        trace_store = TraceStore(
            max_traces=settings.trace_store_max_traces,
            max_spans_per_trace=settings.trace_store_max_spans,
            sample_every=settings.trace_store_sample_every,
            slowest_per_key=settings.trace_store_slowest_per_key,
            idle_finalize_s=settings.trace_store_idle_finalize_s,
            slo_targets={
                "http": settings.slo_http_p95_ms / 1e3,
                "ttft": settings.slo_ttft_p95_ms / 1e3,
                "tpot": settings.slo_tpot_p95_ms / 1e3,
                "queue_wait": settings.slo_queue_wait_p95_ms / 1e3,
            },
            exemplars=metrics.exemplars)
        tracer.add_sink(trace_store.sink)
        app["trace_store"] = trace_store
        ctx.extras["trace_store"] = trace_store
    app["ctx"] = ctx
    app["rate_limiter"] = RateLimiter(settings.rate_limit_rps, settings.rate_limit_burst)

    # gateway data-plane flight recorder + event-loop health
    # (gateway/flight_recorder.py, docs/observability.md): per-request
    # phase attribution rings behind GET /admin/gateway/requests and the
    # loop-lag sampler — the gateway twin of the engine's step ring
    loop_sampler = None
    if settings.gw_flight_recorder_enabled:
        from .flight_recorder import FlightRecorder, LoopLagSampler
        recorder = FlightRecorder(
            metrics, ring_size=settings.gw_flight_ring_size,
            slowest_size=settings.gw_flight_slowest_size,
            slow_request_s=settings.gw_slow_request_s,
            worker=ctx.worker_id)
        app["flight_recorder"] = recorder
        loop_sampler = LoopLagSampler(
            metrics, interval_s=settings.gw_loop_lag_interval_s,
            warn_s=settings.gw_loop_lag_warn_ms / 1e3, recorder=recorder)
        app["loop_lag_sampler"] = loop_sampler

    # fault-injection plane + graceful-degradation ladder
    # (observability/faults.py, observability/degradation.py,
    # docs/resilience.md). Configured BEFORE any component that grabs a
    # breaker (tier store, rollup, federation) so every breaker inherits
    # this app's thresholds and metrics sink. The plane stays a no-op
    # unless fault_injection_enabled is set.
    from ..observability.degradation import configure_degradation
    from ..observability.faults import configure_fault_plane
    fault_plane = configure_fault_plane(
        settings.fault_injection_enabled, metrics=metrics,
        rules_json=settings.fault_rules)
    degradation = configure_degradation(
        metrics=metrics,
        failure_threshold=settings.degradation_failure_threshold,
        cooldown_s=settings.degradation_cooldown_s)
    app["fault_plane"] = fault_plane
    app["degradation"] = degradation
    ctx.extras["degradation"] = degradation

    # per-tenant usage metering (observability/metering.py): the ledger
    # the engine feeds at retire time, its periodic DB rollup, and the
    # GET /admin/tenants/usage surface. Built before the engine so
    # every replica (and every reload-rebuilt engine) shares it.
    tenant_ledger = None
    tenant_rollup = None
    if settings.tenant_metering_enabled:
        from ..observability.metering import TenantLedger, TenantUsageRollup
        tenant_ledger = TenantLedger(
            clamp=tenant_clamp, metrics=metrics,
            max_tenants=settings.tenant_ledger_max_tenants,
            quota_tokens_per_window=settings.tenant_quota_tokens_per_window)
        tenant_rollup = TenantUsageRollup(
            db, tenant_ledger,
            interval_s=settings.tenant_usage_rollup_interval_s,
            pending_max=settings.tenant_rollup_pending_max)
        app["tenant_ledger"] = tenant_ledger
        app["tenant_usage_rollup"] = tenant_rollup
        ctx.extras["tenant_ledger"] = tenant_ledger

    # distributed tenant rate limiter (coordination/ratelimit.py,
    # docs/scaleout.md "Limiter math"): tenant quotas enforced against
    # ONE shared window counter so N workers admit quota + one burst,
    # never N x quota; charges are the ledger's conservation-gated
    # token counts, reconciled by a periodic sync task
    tenant_limiter = None
    if (settings.gw_distributed_limiter and tenant_ledger is not None
            and settings.tenant_quota_tokens_per_window > 0):
        from ..coordination.ratelimit import (DistributedTenantLimiter,
                                              make_rate_counter)
        tenant_limiter = DistributedTenantLimiter(
            make_rate_counter(settings.bus_backend, settings.bus_dir,
                              hub_client=hub_client),
            tenant_ledger,
            quota_tokens=settings.tenant_quota_tokens_per_window,
            window_s=(settings.tenant_quota_window_s
                      or settings.tenant_usage_rollup_interval_s),
            burst_tokens=settings.tenant_quota_burst_tokens,
            sync_interval_s=settings.tenant_limiter_sync_interval_s)
        app["tenant_limiter"] = tenant_limiter
        ctx.extras["tenant_limiter"] = tenant_limiter

    # SLO verdicts over the serving histograms at GET /admin/slo —
    # engine objectives (TTFT/TPOT/queue-wait) read empty without the
    # engine, but the gateway http_p95 objective holds for every
    # deployment, so the evaluator is unconditional. SLO classes map
    # tenants to named target bundles, evaluated per tenant label slice
    # at /admin/slo?tenant= (clamp peek: a probe never consumes a slot)
    from ..observability.slo import (SloEvaluator, default_objectives,
                                     parse_slo_classes,
                                     parse_tenant_classes)
    tenant_class_map = parse_tenant_classes(settings)
    app["slo_evaluator"] = SloEvaluator(
        metrics, default_objectives(settings),
        error_budget=settings.slo_error_budget,
        slo_classes=parse_slo_classes(settings),
        tenant_classes=tenant_class_map,
        tenant_label=tenant_clamp.peek)
    if fleet_metrics is not None:
        # fleet-scope twin: same objectives evaluated over the SUMMED
        # cross-worker histogram state (/admin/slo?scope=fleet) — fleet
        # p95, not this worker's p95
        from ..observability.fleet import FleetMetricsView
        app["slo_evaluator_fleet"] = SloEvaluator(
            FleetMetricsView(metrics, fleet_metrics),
            default_objectives(settings),
            error_budget=settings.slo_error_budget,
            slo_classes=parse_slo_classes(settings),
            tenant_classes=tenant_class_map,
            tenant_label=tenant_clamp.peek)

    # overload shedder (observability/degradation.py): admission-time
    # 429s on the LLM chat surface, lowest SLO class first, consuming
    # the engine-saturation gauge's source signal + the tenant quota
    # window — ROADMAP item 5's "429s driven from the saturation signal"
    if settings.gw_shed_enabled:
        from ..observability.degradation import OverloadShedder
        shed_order: list[str] = []
        if settings.gw_shed_class_order:
            try:
                shed_order = json.loads(settings.gw_shed_class_order)
            except json.JSONDecodeError as exc:
                raise ValueError(
                    f"invalid gw_shed_class_order JSON: {exc}") from exc
            if not isinstance(shed_order, list):
                raise ValueError("gw_shed_class_order must be a JSON "
                                 "array of class names, lowest first")
        app["overload_shedder"] = OverloadShedder(
            shed_at=settings.gw_shed_saturation_at,
            class_order=shed_order,
            tenant_classes=tenant_class_map,
            ledger=tenant_ledger,
            degradation=degradation,
            metrics=metrics,
            limiter=tenant_limiter)

    # live signal plane (observability/signals.py, docs/controller.md):
    # bounded per-replica aggregates PUSHED by engine retire, the flight
    # recorder and the SLO evaluator at their own cadence — the closed-
    # loop serving controller reads these at its tick, never scrapes.
    # Built unconditionally (publish is O(1) and the admin surfaces read
    # it); the controller itself is opt-in below.
    from ..observability.signals import SignalBus
    signal_bus = SignalBus(window=settings.signal_window,
                           ewma_alpha=settings.signal_ewma_alpha)
    app["signal_bus"] = signal_bus
    ctx.extras["signal_bus"] = signal_bus
    if loop_sampler is not None:
        loop_sampler.signals = signal_bus  # gw.loop_lag_ms onto the bus

    # operation-timing registry (reference performance_tracker.py): http /
    # db / tool / resource series feed /admin/performance and the bundle
    if settings.performance_tracking_enabled:
        from ..services.diagnostics_service import tracker_from_settings
        perf = tracker_from_settings(settings)
        ctx.extras["perf_tracker"] = perf
        db.on_query = lambda ms: perf.record("db.query", ms / 1e3)

    # services
    from ..services.a2a_service import A2AService
    from ..services.export_service import ExportService
    from ..services.llm_provider_service import LLMProviderService
    from ..services.sampling_service import CompletionService, SamplingHandler
    from ..services.upstream_sessions import UpstreamSessionRegistry
    upstream_sessions = UpstreamSessionRegistry(
        ctx, max_sessions=settings.upstream_max_sessions,
        idle_ttl=settings.upstream_idle_ttl)
    ctx.extras["upstream_sessions"] = upstream_sessions
    auth_service = AuthService(ctx)
    tool_service = ToolService(ctx)
    gateway_service = GatewayService(ctx)
    resource_service = ResourceService(ctx)
    prompt_service = PromptService(ctx)
    server_service = ServerService(ctx)
    a2a_service = A2AService(ctx)
    ctx.extras["a2a_service"] = a2a_service
    export_service = ExportService(ctx)
    llm_provider_service = LLMProviderService(ctx)
    ctx.extras["llm_provider_service"] = llm_provider_service
    completion_service = CompletionService(ctx)
    sampling_handler = SamplingHandler(ctx)
    app["auth_service"] = auth_service
    # membership/role writers bust the auth resolution cache through this
    # hook (services must not import each other for it)
    ctx.extras["auth_invalidate"] = auth_service.invalidate_user
    app["tool_service"] = tool_service
    app["gateway_service"] = gateway_service
    app["resource_service"] = resource_service
    app["prompt_service"] = prompt_service
    app["server_service"] = server_service
    app["a2a_service"] = a2a_service
    app["export_service"] = export_service
    app["llm_provider_service"] = llm_provider_service

    # tpu_local engine + LLM provider registry
    engine = None
    engine_pool = None
    engine_plane = None
    if settings.tpu_local_enabled and settings.tpu_local_pool_shared:
        # shared engine plane (tpu_local/pool_rpc.py, docs/scaleout.md):
        # the EnginePool is built by ONE leader-elected worker; every
        # other worker serves LLM traffic through the bus RPC seam.
        # HBM state exists once, whatever gw_workers says.
        from ..tpu_local.pool_rpc import SharedEnginePlane, SharedPoolProvider
        from ..tpu_local.provider import LLMProviderRegistry
        from ..tpu_local.server import setup_llm_routes

        async def _build_pool_provider():
            from ..tpu_local.engine import EngineConfig, TPUEngine
            from ..tpu_local.pool import EnginePool
            from ..tpu_local.tpu_provider import TPULocalProvider
            config = EngineConfig.from_settings(settings)
            if settings.tpu_local_replicas > 1:
                pool = EnginePool(
                    config, replicas=settings.tpu_local_replicas,
                    tracer=tracer, metrics=metrics,
                    affinity_routing=settings.tpu_local_pool_affinity_routing,
                    health_interval_s=settings.tpu_local_pool_health_interval_s,
                    heartbeat_timeout_s=(
                        settings.tpu_local_pool_heartbeat_timeout_s),
                    requeue_max=settings.tpu_local_pool_requeue_max,
                    ledger=tenant_ledger, signals=signal_bus,
                    roles=settings.tpu_local_pool_roles,
                    disagg_prompt_tokens=(
                        settings.tpu_local_disagg_prompt_tokens),
                    role_penalty_tokens=(
                        settings.tpu_local_pool_role_penalty_tokens))
                await pool.start()
                backend = pool
                ctx.extras["tpu_engine_pool"] = pool
                ctx.extras["tpu_engine"] = pool.replicas[0].engine
            else:
                local_engine = TPUEngine(config, tracer=tracer,
                                         metrics=metrics,
                                         ledger=tenant_ledger,
                                         signals=signal_bus)
                await local_engine.start()
                backend = local_engine
                ctx.extras["tpu_engine"] = local_engine
            return TPULocalProvider(
                "tpu_local", backend,
                embedding_model=settings.tpu_local_embedding_model,
                tracer=tracer, metrics=metrics,
                encoder_max_batch=settings.tpu_local_encoder_max_batch,
                encoder_max_wait_ms=settings.tpu_local_encoder_max_wait_ms,
                encoder_min_seq=settings.tpu_local_encoder_min_seq)

        engine_plane = SharedEnginePlane(
            bus_rpc, leases, ctx.worker_id, _build_pool_provider,
            lease_ttl=settings.leader_lease_ttl,
            rpc_timeout_s=settings.gw_rpc_timeout_s,
            stream_idle_timeout_s=settings.gw_stream_idle_timeout_s)
        app["engine_plane"] = engine_plane
        ctx.extras["engine_plane"] = engine_plane
        registry = LLMProviderRegistry()
        registry.register(
            SharedPoolProvider("tpu_local", engine_plane),
            [settings.tpu_local_model, "tpu_local"],
            default_chat=True, default_embed=True)
        ctx.llm_registry = registry
        app["llm_registry"] = registry
        setup_llm_routes(app, registry, prefix=settings.llm_api_prefix)
    elif settings.tpu_local_enabled:
        from ..tpu_local.engine import EngineConfig, TPUEngine
        from ..tpu_local.provider import LLMProviderRegistry
        from ..tpu_local.server import setup_llm_routes
        from ..tpu_local.tpu_provider import TPULocalProvider
        # telemetry handles ride into the engine so the dispatch thread can
        # emit llm.prefill/llm.decode spans + token-level SLO histograms
        engine_config = EngineConfig.from_settings(settings)
        if settings.tpu_local_replicas > 1:
            # replica pool: N engines on device-subset meshes behind the
            # affinity router + health monitor (docs/serving_pool.md).
            # The provider speaks to the POOL. app["tpu_engine"] is still
            # set (replica 0 at build time) for code that predates the
            # pool, but the single-engine admin surfaces resolve the
            # CURRENT engine through live_tpu_engine() — a pool reload
            # swaps the engine object, so a build-time reference goes
            # stale after the first hot-swap.
            from ..tpu_local.pool import EnginePool
            engine_pool = EnginePool(
                engine_config,
                replicas=settings.tpu_local_replicas,
                tracer=tracer, metrics=metrics,
                affinity_routing=settings.tpu_local_pool_affinity_routing,
                health_interval_s=settings.tpu_local_pool_health_interval_s,
                heartbeat_timeout_s=(
                    settings.tpu_local_pool_heartbeat_timeout_s),
                requeue_max=settings.tpu_local_pool_requeue_max,
                ledger=tenant_ledger, signals=signal_bus,
                roles=settings.tpu_local_pool_roles,
                disagg_prompt_tokens=settings.tpu_local_disagg_prompt_tokens,
                role_penalty_tokens=(
                    settings.tpu_local_pool_role_penalty_tokens))
            engine = engine_pool.replicas[0].engine
            app["tpu_engine_pool"] = engine_pool
            ctx.extras["tpu_engine_pool"] = engine_pool
        else:
            engine = TPUEngine(engine_config, tracer=tracer, metrics=metrics,
                               ledger=tenant_ledger, signals=signal_bus)
        from ..services.diagnostics_service import JaxProfilerCapture
        app["jax_profiler"] = JaxProfilerCapture(settings.jax_profile_dir)
        provider = TPULocalProvider(
            "tpu_local", engine_pool if engine_pool is not None else engine,
            embedding_model=settings.tpu_local_embedding_model,
            tracer=tracer, metrics=metrics,
            encoder_max_batch=settings.tpu_local_encoder_max_batch,
            encoder_max_wait_ms=settings.tpu_local_encoder_max_wait_ms,
            encoder_min_seq=settings.tpu_local_encoder_min_seq)
        provider.classify_window = settings.tpu_local_classify_window
        provider.classify_coverage = settings.tpu_local_classify_coverage
        provider.classify_max_windows = settings.tpu_local_classify_max_windows
        provider.classify_cache_size = settings.tpu_local_classify_cache_size
        registry = LLMProviderRegistry()
        registry.register(provider, [settings.tpu_local_model, "tpu_local"],
                          default_chat=True, default_embed=True)
        ctx.llm_registry = registry
        app["llm_registry"] = registry
        app["tpu_engine"] = engine
        ctx.extras["tpu_engine"] = engine
        app["tpu_provider"] = provider
        setup_llm_routes(app, registry, prefix=settings.llm_api_prefix)

    # closed-loop serving controller (tpu_local/controller.py,
    # docs/controller.md): reads the signal bus at a fixed tick and
    # steers superstep K / batch-width floor / spec-decode / shed bars.
    # Opt-in (controller_enabled) and fully auditable — every decision
    # lands in a bounded ring behind GET /admin/controller. Engines are
    # resolved lazily through ctx.extras so a pool hot-swap or shared-
    # plane leader build is always steering the CURRENT engines.
    serving_controller = None
    if settings.controller_enabled:
        from ..tpu_local.controller import ServingController

        def _live_engines():
            live_pool = ctx.extras.get("tpu_engine_pool")
            if live_pool is not None:
                return [r.engine for r in live_pool.replicas]
            eng = ctx.extras.get("tpu_engine")
            return [eng] if eng is not None else []

        serving_controller = ServingController(
            signal_bus, _live_engines,
            shedder=app.get("overload_shedder"),
            slo_evaluator=app["slo_evaluator"],
            metrics=metrics, tracer=tracer,
            enabled=True,
            safe_mode=settings.controller_safe_mode,
            tick_s=settings.controller_tick_s,
            cooldown_s=settings.controller_cooldown_s,
            eval_window_s=settings.controller_eval_window_s,
            hysteresis=settings.controller_hysteresis,
            ring_size=settings.controller_ring_size,
            queue_wait_high_ms=settings.controller_queue_wait_high_ms,
            queue_wait_low_ms=settings.controller_queue_wait_low_ms,
            idle_frac_high=settings.controller_idle_frac_high,
            spec_accept_off=settings.controller_spec_accept_off,
            spec_accept_on=settings.controller_spec_accept_on,
            burn_high=settings.controller_burn_high,
            burn_low=settings.controller_burn_low,
            shed_floor=settings.controller_shed_floor,
            shed_step=settings.controller_shed_step)
        app["serving_controller"] = serving_controller
        ctx.extras["serving_controller"] = serving_controller

    # plugins (optional, loaded if configured)
    if settings.plugins_enabled:
        from ..plugins.framework import PluginManager
        pm = await PluginManager.load(ctx)
        ctx.plugin_manager = pm
        app["plugin_manager"] = pm

    # dispatcher + transports
    dispatcher = RPCDispatcher(ctx, tool_service, resource_service, prompt_service,
                               server_service, completion_service=completion_service,
                               sampling_handler=sampling_handler)
    app["dispatcher"] = dispatcher
    transport = StreamableHTTPTransport(dispatcher, settings)
    transport.sessions.metrics = metrics  # mcpforge_sessions_active gauge

    # MCP listChanged notifications: catalog mutations fan out to every
    # connected stateful session (reference: notification_service +
    # notifications/*/list_changed)
    def _notify(method: str):
        async def handler(topic, message):
            await transport.sessions.broadcast(
                {"jsonrpc": "2.0", "method": method})
        return handler

    bus.subscribe("tools.changed", _notify("notifications/tools/list_changed"))
    bus.subscribe("resources.changed",
                  _notify("notifications/resources/list_changed"))
    bus.subscribe("prompts.changed",
                  _notify("notifications/prompts/list_changed"))
    app["streamable_transport"] = transport
    # swappable /mcp ingress (ADR 051) + runtime-mutable mode
    from .ingress import IngressMount
    ingress = IngressMount(ctx)
    ingress.register("python", {"post": transport.handle_post,
                                "get": transport.handle_get,
                                "delete": transport.handle_delete})
    ingress.subscribe()
    await ingress.load()  # adopt the cluster's persisted mode at boot
    app["ingress"] = ingress
    app.router.add_post("/mcp", ingress.handler("post"))
    app.router.add_get("/mcp", ingress.handler("get"))
    app.router.add_delete("/mcp", ingress.handler("delete"))
    app.router.add_post("/servers/{server_id}/mcp", ingress.handler("post"))
    app.router.add_get("/servers/{server_id}/mcp", ingress.handler("get"))

    async def ingress_status(request: web.Request) -> web.Response:
        request["auth"].require("observability.read")
        return web.json_response({"mode": ingress.mode,
                                  "version": ingress.version,
                                  "available": ingress.names(),
                                  "changed_at": ingress.changed_at})

    async def ingress_set(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        body = await request.json()
        if not isinstance(body, dict):
            return web.json_response({"detail": "body must be an object"},
                                     status=422)
        await ingress.set_mode(body.get("mode", ""))
        return web.json_response({"mode": ingress.mode,
                                  "version": ingress.version})

    app.router.add_get("/admin/ingress", ingress_status)
    app.router.add_post("/admin/ingress", ingress_set)

    from .transports.ws_sse import LegacySSETransport, WebSocketTransport
    ws_transport = WebSocketTransport(dispatcher, settings)
    sse_transport = LegacySSETransport(dispatcher, settings)
    app.router.add_get("/ws", ws_transport.handle)
    app.router.add_get("/servers/{server_id}/ws", ws_transport.handle)
    app.router.add_get("/sse", sse_transport.handle_stream)
    app.router.add_post("/messages", sse_transport.handle_message)

    # session affinity: forwarded requests run under the original caller's
    # identity, reconstructed from the bus payload
    from ..services.auth_service import AuthContext as _AuthCtx
    from ..services.session_affinity import SessionAffinityService

    async def _affinity_local_handler(message: dict, auth_info: dict):
        from ..jsonrpc import JSONRPCError as _JE, RPCRequest as _RR
        auth_ctx = _AuthCtx(user=auth_info.get("user", "anonymous"),
                            is_admin=bool(auth_info.get("is_admin")),
                            teams=list(auth_info.get("teams", [])),
                            permissions=set(auth_info.get("permissions", [])),
                            via="forwarded")
        # forwarded traffic keeps the owner's session + lease alive: an
        # always-misrouted-but-active client must not expire mid-conversation
        sid = auth_info.get("headers", {}).get("mcp-session-id")
        if sid:
            transport.sessions.get(sid)  # slides local last_seen
            await affinity.claim_session(sid)
        # forwarded RESPONSE messages (no method) are elicitation replies for
        # a session this worker owns — RPCRequest.parse would reject them
        from ..jsonrpc import is_response_message
        if is_response_message(message):
            if transport.elicitation is not None:
                transport.elicitation.resolve(message, session_id=sid)
            return None
        try:
            return await dispatcher.dispatch(_RR.parse(message), auth_ctx,
                                             headers=auth_info.get("headers", {}))
        except _JE as exc:
            return exc.to_dict(message.get("id") if isinstance(message, dict)
                               else None)

    affinity = SessionAffinityService(
        ctx, local_handler=_affinity_local_handler,
        rpc=bus_rpc if settings.gw_session_handoff else None)
    ctx.extras["session_affinity"] = affinity
    app["session_affinity"] = affinity
    transport.affinity = affinity

    from ..services.reverse_proxy import ReverseProxyHub
    reverse_hub = ReverseProxyHub(ctx)
    ctx.extras["reverse_proxy_hub"] = reverse_hub
    app["reverse_proxy_hub"] = reverse_hub
    app.router.add_get("/reverse-proxy", reverse_hub.handle_ws)

    async def handle_rpc(request: web.Request) -> web.Response:
        raw = await request.read()
        headers = {k.lower(): v for k, v in request.headers.items()}
        payload: object = None
        try:
            payload = parse_body(raw, settings.max_request_size_bytes)
            rpc_request = RPCRequest.parse(payload)
            # zero-copy envelope (gateway/serialize.py via the
            # dispatcher's byte seam): pre-encoded JSON-RPC fragments
            # around one compact result encode, charged to the flight
            # recorder's `serialize` bucket instead of the unattributed
            # `handler` residue (docs/observability.md)
            body = await request.app["dispatcher"].dispatch_bytes(
                rpc_request, request["auth"], headers=headers)
        except JSONRPCError as exc:
            rid = payload.get("id") if isinstance(payload, dict) else None
            return web.json_response(exc.to_dict(rid))
        if body is None:
            return web.Response(status=202)
        return web.Response(body=body, content_type="application/json")

    app.router.add_post("/rpc", handle_rpc)
    setup_routes(app)
    from .routers_extra import setup_extra_routes
    setup_extra_routes(app)
    from .routers_discovery import setup_discovery_routes
    setup_discovery_routes(app)
    from ..services.role_service import RoleService
    app["role_service"] = RoleService(ctx)
    from ..services.diagnostics_service import (SupportBundleService,
                                                SystemStatsService)
    app["system_stats_service"] = SystemStatsService(ctx)
    app["support_bundle_service"] = SupportBundleService(ctx)
    from ..services.email_service import EmailNotificationService
    email_service = EmailNotificationService(ctx)
    app["email_service"] = email_service
    ctx.extras["email_service"] = email_service
    if settings.hot_cold_classification_enabled:
        from ..services.classification_service import (
            ServerClassificationService)
        ctx.extras["server_classifier"] = ServerClassificationService(ctx)
    if settings.registry_cache_enabled:
        from .registry_cache import RegistryCache
        registry_cache = RegistryCache(ctx)
        registry_cache.wire()
        app["registry_cache"] = registry_cache
        ctx.extras["registry_cache"] = registry_cache
    from ..services.compliance_service import ComplianceService
    app["compliance_service"] = ComplianceService(ctx)
    # pre-create: request handlers may not add keys to a frozen
    # (started) aiohttp app
    app["_token_usage_tasks"] = set()
    app["_stats_cache"] = {}
    from .routers_rbac import setup_compliance_routes, setup_rbac_routes
    setup_rbac_routes(app)
    setup_compliance_routes(app)

    from ..services.audit_service import AuditService
    from ..services.cancellation_service import CancellationService
    from ..services.catalog_service import CatalogService
    from ..services.chat_service import ChatService
    from ..services.metrics_service import MetricsMaintenanceService
    from ..services.team_service import TeamService
    app["chat_service"] = ChatService(ctx, tool_service, server_service,
                                      kv=kv_store,
                                      session_ttl=settings.session_ttl)
    app["team_service"] = TeamService(ctx)
    app["catalog_service"] = CatalogService(ctx)
    audit_service = AuditService(ctx, siem_url=settings.siem_export_url)
    if settings.audit_enabled:
        app["audit_service"] = audit_service
    cancellation_service = CancellationService(ctx)
    ctx.extras["cancellation_service"] = cancellation_service
    app["cancellation_service"] = cancellation_service
    from ..services.oauth_service import OAuthManager, SSOService
    oauth_manager = OAuthManager(ctx)
    ctx.extras["oauth_manager"] = oauth_manager
    sso_service = SSOService(ctx, auth_service)
    app["sso_service"] = sso_service
    if settings.sso_providers:
        import json as _json
        for entry in _json.loads(settings.sso_providers):
            sso_service.register_provider(
                entry["name"], entry["issuer"], entry["client_id"],
                entry.get("client_secret", ""),
                authorization_endpoint=entry.get("authorization_endpoint", ""),
                token_endpoint=entry.get("token_endpoint", ""),
                dialect=entry.get("dialect", "oidc"),
                userinfo_endpoint=entry.get("userinfo_endpoint", ""),
                metadata=entry.get("metadata"))

    async def sso_providers_route(request: web.Request) -> web.Response:
        return web.json_response({"providers": sso_service.list_providers()})

    async def sso_login(request: web.Request) -> web.Response:
        name = request.match_info["provider"]
        redirect_uri = f"{settings.app_domain}/auth/sso/{name}/callback"
        raise web.HTTPFound(await sso_service.login_url(name, redirect_uri))

    async def sso_callback(request: web.Request) -> web.Response:
        name = request.match_info["provider"]
        redirect_uri = f"{settings.app_domain}/auth/sso/{name}/callback"
        result = await sso_service.handle_callback(
            request.query.get("state", ""), request.query.get("code", ""),
            redirect_uri)
        return web.json_response(result)

    app.router.add_get("/auth/sso/providers", sso_providers_route)
    app.router.add_get("/auth/sso/{provider}/login", sso_login)
    app.router.add_get("/auth/sso/{provider}/callback", sso_callback)

    # OAuth DCR + token exchange (reference dcr_service.py / oauth_manager
    # token-exchange validation at gateway_service.py:767)
    from ..services.oauth_service import DCRService, exchange_token
    dcr_service = DCRService(ctx)
    app["dcr_service"] = dcr_service
    ctx.extras["dcr_service"] = dcr_service

    async def dcr_register(request: web.Request) -> web.Response:
        request["auth"].require("gateways.update")
        body = await request.json()
        record = await dcr_service.get_or_register(
            body.get("gateway_id", ""), body.get("issuer", ""),
            body.get("redirect_uri", f"{settings.app_domain}/oauth/callback"),
            body.get("scopes"))
        return web.json_response(record, status=201)

    async def dcr_list(request: web.Request) -> web.Response:
        request["auth"].require("gateways.read")
        return web.json_response(await dcr_service.list_clients())

    async def dcr_delete(request: web.Request) -> web.Response:
        request["auth"].require("gateways.update")
        await dcr_service.delete_client(request.match_info["record_id"])
        return web.Response(status=204)

    async def oauth_exchange(request: web.Request) -> web.Response:
        request["auth"].require("gateways.update")
        body = await request.json()
        payload = await exchange_token(
            ctx, body.get("token_url", ""), body.get("subject_token", ""),
            client_id=body.get("client_id", ""),
            client_secret=body.get("client_secret", ""),
            audience=body.get("audience", ""))
        return web.json_response(payload)

    app.router.add_post("/oauth/dcr/register", dcr_register)
    app.router.add_get("/oauth/dcr/clients", dcr_list)
    app.router.add_delete("/oauth/dcr/clients/{record_id}", dcr_delete)
    app.router.add_post("/oauth/exchange", oauth_exchange)

    from ..services.grpc_service import GrpcService
    grpc_service = GrpcService(ctx, tool_service)
    ctx.extras["grpc_service"] = grpc_service
    app["grpc_service"] = grpc_service

    from ..services.elicitation_service import ElicitationService
    if settings.mcp_apps_enabled:
        from ..services.mcp_apps_service import MCPAppsService
        app["mcp_apps_service"] = MCPAppsService(ctx, transport.sessions,
                                                 resource_service)

    elicitation_service = ElicitationService(ctx, transport.sessions)
    transport.elicitation = elicitation_service
    ctx.extras["elicitation_service"] = elicitation_service
    app["elicitation_service"] = elicitation_service

    # cross-worker session handoff handlers (docs/scaleout.md): the
    # OWNING worker serves forwarded elicit calls and relays its session
    # SSE queue to whichever worker the client's connection landed on
    async def _rpc_session_elicit(params: dict) -> dict:
        session_id = params.get("session_id", "")
        if transport.sessions.get(session_id) is None:
            from ..services.base import NotFoundError as _NF
            raise _NF(f"session {session_id!r} not connected here")
        await affinity.claim_session(session_id)  # forwarded activity renews
        return await elicitation_service.elicit(
            session_id, params.get("message", ""),
            requested_schema=params.get("requestedSchema"),
            timeout=float(params.get("timeout") or 120.0))

    async def _rpc_session_stream(params: dict):
        """Relay generator: replay-from-Last-Event-ID, then live queue
        consumption; idle gaps yield keepalive chunks so the remote
        writer emits the same ': keepalive' comments a local stream
        would. The remote consumer IS the stream consumer — frames are
        byte-identical because the remote side renders them with the
        same _sse_frame."""
        import asyncio as _aio
        session_id = params.get("session_id", "")
        session = transport.sessions.get(session_id)
        if session is None:
            from ..services.base import NotFoundError as _NF
            raise _NF(f"session {session_id!r} not connected here")
        metrics.gw_session_handoffs.labels(kind="stream_served").inc()
        last_event_id = params.get("last_event_id")
        if last_event_id:
            for entry in transport.sessions.events.replay_after(
                    session_id, last_event_id):
                yield {"event_id": entry.event_id, "message": entry.message}
        keepalive = settings.sse_keepalive_interval
        while True:
            # forwarded consumption keeps ownership + the session alive
            transport.sessions.get(session_id)
            await affinity.claim_session(session_id)
            try:
                event_id, message = await _aio.wait_for(
                    session.queue.get(), timeout=keepalive)
                yield {"event_id": event_id, "message": message}
            except _aio.TimeoutError:
                yield {"keepalive": True}

    bus_rpc.register("session.elicit", _rpc_session_elicit)
    bus_rpc.register_stream("session.stream", _rpc_session_stream)

    # cross-host prefix-cache fabric (docs/cache_fabric.md): one
    # publisher per gateway host gossips the tier store's
    # object-resident chains — in-fleet workers over the fabric.advert
    # bus method, cross-supervisor peers over POST /admin/fabric/adverts
    # (routers_extra.py) — and merges what peers advertise back into the
    # store's fabric index. The store resolves lazily: under the
    # leader-elected shared plane it only exists after election.
    from ..tpu_local.kv.fabric.publisher import FabricIndexPublisher

    def _fabric_store():
        pool = ctx.extras.get("tpu_engine_pool") or engine_pool
        if pool is not None and getattr(pool, "tier_store", None) is not None:
            return pool.tier_store
        eng = ctx.extras.get("tpu_engine") or engine
        client = getattr(eng, "_tier_client", None) \
            if eng is not None else None
        return client.store if client is not None else None

    _fabric_http: list = []  # ClientSession, created lazily on the loop

    async def _fabric_post_json(url: str, payload: dict) -> dict | None:
        # peer URLs may embed basic credentials
        # ("http://admin:pw@hostb:4444") — split them out; aiohttp
        # refuses userinfo in the request URL itself
        import aiohttp
        from yarl import URL
        if not _fabric_http:
            _fabric_http.append(aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=5.0)))
        u = URL(url)
        auth = (aiohttp.BasicAuth(u.user, u.password or "")
                if u.user else None)
        async with _fabric_http[0].post(
                u.with_user(None), json=payload, auth=auth) as resp:
            resp.raise_for_status()
            return await resp.json()

    fabric_publisher = FabricIndexPublisher(
        _fabric_store, ctx.worker_id, rpc=bus_rpc,
        bus_peers=(lambda: fleet_metrics.live_peers().keys())
        if fleet_metrics is not None else None,
        http_peers=[u.strip() for u in
                    settings.tpu_local_fabric_peers.split(",")
                    if u.strip()],
        post_json=_fabric_post_json,
        interval_s=settings.tpu_local_fabric_advert_interval_s,
        ttl_s=settings.tpu_local_fabric_advert_ttl_s,
        rpc_timeout_s=settings.gw_rpc_timeout_s,
        metrics=metrics)
    app["fabric_publisher"] = fabric_publisher
    ctx.extras["fabric_publisher"] = fabric_publisher
    bus_rpc.register("fabric.advert", fabric_publisher.handle_advert)

    async def elicit_route(request: web.Request) -> web.Response:
        request["auth"].require("tools.invoke")
        body = await request.json()
        session_id = request.match_info["session_id"]
        import math
        try:
            timeout = float(body.get("timeout", 120.0))
        except (TypeError, ValueError):
            return web.json_response({"detail": "timeout must be a number"},
                                     status=422)
        if not math.isfinite(timeout):
            return web.json_response({"detail": "timeout must be finite"},
                                     status=422)
        # the stream lives on the owning worker: forward there first
        # (docs/scaleout.md); the 409 survives only as the fallback when
        # no live owner answers (handoff disabled, owner died mid-claim)
        if (transport.sessions.get(session_id) is None
                and not await affinity.is_local(session_id)):
            from ..coordination.rpc import RpcAppError
            try:
                result = await affinity.forward_elicit(session_id, {
                    "message": body.get("message", ""),
                    "requestedSchema": body.get("requestedSchema"),
                    "timeout": timeout}, timeout=timeout + 10.0)
            except RpcAppError as exc:
                # only the owner's "session not connected here" maps to
                # the 409 fallback; any OTHER remote failure must
                # surface as its own error, not an invitation to retry
                # against an owner that just failed
                if "NotFoundError" not in str(exc):
                    metrics.gw_session_handoffs.labels(
                        kind="remote_error").inc()
                    return web.json_response(
                        {"detail": f"elicit handoff failed on the owning "
                                   f"worker: {exc}"}, status=502)
                result = None
                logger.info("elicit handoff refused by owner: %s", exc)
            if result is not None:
                metrics.gw_session_handoffs.labels(kind="elicit").inc()
                return web.json_response(result)
            metrics.gw_session_handoffs.labels(kind="refused").inc()
            return web.json_response(
                {"detail": "Session is owned by another worker; "
                           "elicit on the owning worker"}, status=409)
        result = await elicitation_service.elicit(
            session_id, body.get("message", ""),
            requested_schema=body.get("requestedSchema"),
            timeout=timeout)
        return web.json_response(result)

    app.router.add_post("/sessions/{session_id}/elicit", elicit_route)

    from ..services.toolops_service import ToolOpsService
    toolops = ToolOpsService(ctx, tool_service)
    app["toolops_service"] = toolops

    async def toolops_generate(request: web.Request) -> web.Response:
        request["auth"].require("tools.read")
        cases = await toolops.generate(
            request.match_info["name"],
            use_llm=request.query.get("use_llm") == "true")
        return web.json_response({"cases": cases})

    async def toolops_run(request: web.Request) -> web.Response:
        request["auth"].require("tools.invoke")
        try:
            body = await request.json()
        except Exception:
            body = {}
        if not isinstance(body, dict):
            return web.json_response({"detail": "body must be a JSON object"},
                                     status=422)
        report = await toolops.run(request.match_info["name"],
                                   cases=body.get("cases"),
                                   user=request["auth"].user)
        return web.json_response(report)

    app.router.add_get("/toolops/{name}/cases", toolops_generate)
    app.router.add_post("/toolops/{name}/run", toolops_run)

    async def register_grpc(request: web.Request) -> web.Response:
        request["auth"].require("tools.create")
        body = await request.json()
        try:
            created = await grpc_service.register_target(
                body.get("target", ""), prefix=body.get("prefix", ""),
                tls=bool(body.get("tls")), ca_pem=body.get("ca_pem"),
                cert_pem=body.get("cert_pem"), key_pem=body.get("key_pem"),
                authority=body.get("authority"))
        except Exception as exc:
            return web.json_response(
                {"detail": f"gRPC discovery failed: {type(exc).__name__}"},
                status=502)
        return web.json_response({"registered": created}, status=201)

    app.router.add_post("/grpc/register", register_grpc)
    if engine is not None:
        ctx.extras["tpu_engine"] = engine

    async def admin_audit(request: web.Request) -> web.Response:
        request["auth"].require("observability.read")
        raw_limit = request.query.get("limit", "200")
        if not raw_limit.isdigit():
            return web.json_response({"detail": "limit must be an integer"},
                                     status=400)
        return web.json_response(await audit_service.search(
            actor=request.query.get("actor"),
            action=request.query.get("action"),
            limit=min(int(raw_limit), 1000)))

    app.router.add_get("/admin/audit", admin_audit)
    metrics_maintenance = MetricsMaintenanceService(
        ctx, rollup_interval=settings.metrics_rollup_interval_minutes * 60,
        retention_hours=settings.metrics_retention_hours)
    app["metrics_maintenance"] = metrics_maintenance
    metrics_buffer = None
    if settings.metrics_buffer_enabled:
        from ..services.metrics_service import MetricsBuffer
        metrics_buffer = MetricsBuffer(
            ctx, max_size=settings.metrics_buffer_max_size,
            flush_interval=settings.metrics_buffer_flush_interval_s)
        ctx.extras["metrics_buffer"] = metrics_buffer
    from .routers_chat import setup_chat_routes
    setup_chat_routes(app)
    if settings.admin_ui_enabled:
        from .admin_ui import setup_admin_ui
        setup_admin_ui(app)

    async def lifecycle(app: web.Application) -> AsyncIterator[None]:
        await bus.start()
        import asyncio as _asyncio

        await bus_rpc.start()  # the cross-worker call seam rides the bus
        from ..utils.masking import native_available
        await _asyncio.to_thread(native_available)  # prebuild off the loop
        await transport.sessions.start_sweeper()
        await upstream_sessions.start()
        await auth_service.bootstrap_admin()
        await app["role_service"].bootstrap_system_roles()
        if engine_plane is not None:
            await engine_plane.start()  # leader-elected shared pool
        elif engine_pool is not None:
            await engine_pool.start()  # replicas + health monitor
        elif engine is not None:
            await engine.start()
        await llm_provider_service.rewire()  # external providers from DB
        if serving_controller is not None:
            await serving_controller.start()  # closed loop over the bus
        if ctx.plugin_manager is not None:
            await ctx.plugin_manager.load_bindings()
        elector = LeaderElector(leases, "gateway-leader", ctx.worker_id,
                                ttl=settings.leader_lease_ttl)
        ctx.extras["leader_elector"] = elector
        await elector.start()
        await gateway_service.start_health_loop()
        if loop_sampler is not None:
            await loop_sampler.start()
        if tenant_rollup is not None:
            await tenant_rollup.start()  # ledger window -> tenant_usage
        if tenant_limiter is not None:
            await tenant_limiter.start()  # ledger -> shared quota counter
        if fleet_metrics is not None:
            await fleet_metrics.start()
        if settings.tpu_local_tier_object_url:
            await fabric_publisher.start()  # T3 advert gossip loop
        await metrics_maintenance.start()
        if metrics_buffer is not None:
            await metrics_buffer.start()

        async def _chat_sweeper() -> None:
            # chat sessions expire via KV ttl; the purge drops entries no
            # one will ever get() again (abandoned sessions)
            while True:
                await _asyncio.sleep(600)
                try:
                    await kv_store.purge_expired()
                except Exception:
                    logger.exception("kv purge failed")
                apps_service = app.get("mcp_apps_service")
                if apps_service is not None:
                    try:  # expired AppBridge rows must not accumulate
                        await apps_service.sweep()
                    except Exception:
                        logger.exception("mcp_apps sweep failed")

        chat_sweeper = _asyncio.create_task(_chat_sweeper())
        await affinity.start()
        await audit_service.start()
        if otlp_exporter is not None:
            await otlp_exporter.start()
        logger.info("%s started (worker %s)", settings.app_name, ctx.worker_id)
        yield
        # drain in-flight token-usage accounting writes BEFORE the db
        # closes: the last requests' rows (incl. blocked security
        # denials the compliance reports count) must not be lost
        pending_usage = app.get("_token_usage_tasks")
        if pending_usage:
            await _asyncio.gather(*list(pending_usage),
                                  return_exceptions=True)
        if otlp_exporter is not None:
            await otlp_exporter.stop()
        await audit_service.stop()
        await affinity.stop()
        chat_sweeper.cancel()
        try:
            await chat_sweeper
        except _asyncio.CancelledError:
            pass
        if metrics_buffer is not None:
            await metrics_buffer.stop()
        if loop_sampler is not None:
            await loop_sampler.stop()
        await fabric_publisher.stop()
        if _fabric_http:
            await _fabric_http[0].close()
        if fleet_metrics is not None:
            await fleet_metrics.stop()
        if tenant_limiter is not None:
            await tenant_limiter.stop()
        await metrics_maintenance.stop()
        await transport.sessions.stop_sweeper()
        await gateway_service.stop_health_loop()
        await elector.stop()
        if serving_controller is not None:
            # BEFORE engine shutdown: no knob request may land on a
            # stopping dispatch loop
            await serving_controller.stop()
        if ctx.llm_registry is not None:
            await ctx.llm_registry.shutdown()
        await bus_rpc.stop()
        if tenant_rollup is not None:
            # AFTER engine shutdown (the last retires have landed in the
            # ledger) and before db.close(): the final window's usage
            # rows must not be lost at shutdown
            await tenant_rollup.stop()
        await upstream_sessions.stop()
        await grpc_service.shutdown()
        await ctx.close_http_client()
        await bus.stop()
        if hub is not None:
            await hub.stop()
        await db.close()

    app.cleanup_ctx.append(lifecycle)
    return app


def install_event_loop(policy_name: str) -> str:
    """Install the configured event-loop policy (gw_event_loop).

    Returns the loop actually installed: ``uvloop`` only when requested
    AND importable — the serving image does not ship it, so the knob
    degrades to asyncio with a warning instead of failing boot."""
    if policy_name != "uvloop":
        return "asyncio"
    try:
        import uvloop  # type: ignore[import-not-found]
    except ImportError:
        logging.getLogger(__name__).warning(
            "gw_event_loop=uvloop but uvloop is not installed; "
            "falling back to asyncio")
        return "asyncio"
    asyncio.set_event_loop_policy(uvloop.EventLoopPolicy())
    return "uvloop"


def run(settings: Settings | None = None) -> None:
    settings = settings or get_settings()
    install_event_loop(settings.gw_event_loop)

    async def _factory() -> web.Application:
        return await build_app(settings)

    from ..utils.sslctx import serving_ssl

    # gw_reuse_port: every supervised worker binds the SAME port with
    # SO_REUSEPORT and the kernel spreads accepted connections across
    # them — the one-socket multi-worker layout (docs/scaleout.md)
    web.run_app(_factory(), host=settings.host, port=settings.port,
                reuse_port=settings.gw_reuse_port or None,
                backlog=settings.gw_listen_backlog,
                ssl_context=serving_ssl(settings))
