"""RBAC routes: role CRUD, user-role assignment, permission inspection.

Reference surface: `/root/reference/mcpgateway/routers/rbac.py`
(`/rbac/roles` CRUD, `/rbac/users/{email}/roles` assign/list/revoke,
`/rbac/permissions/check`, `/rbac/permissions/user/{email}`). Guarded by
``admin.all`` (the reference's `admin.user_management` family maps onto
the single admin tier here); resolution itself happens in
`auth_service.resolve_*`, so an assignment changes `require()` outcomes
on the user's next request.
"""

from __future__ import annotations

from aiohttp import web

from ..services.role_service import RoleService
from .pagination import paginate


def setup_rbac_routes(app: web.Application) -> None:
    routes = web.RouteTableDef()
    service: RoleService = app["role_service"]

    # ------------------------------------------------------------ role CRUD
    @routes.get("/rbac/roles")
    async def list_roles(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        rows = await service.list_roles()
        return paginate(request, rows, lambda page: list(page),
                        key=lambda row: row["id"])

    @routes.post("/rbac/roles")
    async def create_role(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("admin.all")
        body = await request.json()
        role = await service.create_role(
            body.get("name", ""), body.get("permissions") or [],
            description=body.get("description", ""),
            scope=body.get("scope", "global"), created_by=auth.user)
        return web.json_response(role, status=201)

    @routes.get("/rbac/roles/{role_id}")
    async def get_role(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        return web.json_response(
            await service.get_role(request.match_info["role_id"]))

    @routes.put("/rbac/roles/{role_id}")
    async def update_role(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        body = await request.json()
        role = await service.update_role(
            request.match_info["role_id"], name=body.get("name"),
            description=body.get("description"),
            permissions=body.get("permissions"))
        return web.json_response(role)

    @routes.delete("/rbac/roles/{role_id}")
    async def delete_role(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        await service.delete_role(request.match_info["role_id"])
        return web.Response(status=204)

    # ----------------------------------------------------------- assignment
    @routes.get("/rbac/users/{email}/roles")
    async def user_roles(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        return web.json_response(
            await service.user_roles(request.match_info["email"]))

    @routes.post("/rbac/users/{email}/roles")
    async def assign_role(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("admin.all")
        body = await request.json()
        grant = await service.assign_role(
            request.match_info["email"], body.get("role_id", ""),
            scope_id=body.get("scope_id", ""), granted_by=auth.user)
        request.app["auth_service"].invalidate_user(
            request.match_info["email"])
        return web.json_response(grant, status=201)

    @routes.delete("/rbac/users/{email}/roles/{role_id}")
    async def revoke_role(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        await service.revoke_role(
            request.match_info["email"], request.match_info["role_id"],
            scope_id=request.query.get("scope_id", ""))
        request.app["auth_service"].invalidate_user(
            request.match_info["email"])
        return web.Response(status=204)

    # ----------------------------------------------------------- inspection
    @routes.get("/rbac/permissions/user/{email}")
    async def user_permissions(request: web.Request) -> web.Response:
        """Effective permission set via the SAME helper resolve_* uses —
        the inspector can never drift from enforcement. Team-scoped
        grants resolve against the user's memberships; per-assignment
        detail lives at /rbac/users/{email}/roles."""
        request["auth"].require("admin.all")
        email = request.match_info["email"]
        perms, is_admin, is_active = \
            await request.app["auth_service"].effective_permissions(email)
        return web.json_response(
            {"user_email": email, "is_admin": is_admin,
             "is_active": is_active, "permissions": sorted(perms)})

    @routes.post("/rbac/permissions/check")
    async def check_permission(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        body = await request.json()
        email = body.get("user_email", "")
        permission = body.get("permission", "")
        perms, is_admin, is_active = \
            await request.app["auth_service"].effective_permissions(email)
        # mirrors AuthContext.can for an unscoped identity — plus the
        # deactivation gate resolve_* applies before permissions matter
        granted = is_active and (is_admin or "admin.all" in perms
                                 or permission in perms)
        return web.json_response({"user_email": email,
                                  "permission": permission,
                                  "is_active": is_active,
                                  "granted": granted})

    app.add_routes(routes)


def setup_compliance_routes(app: web.Application) -> None:
    """Compliance report generator routes (reference
    `routers/compliance_router.py`): framework catalog, report
    generation over an assessment period, retrieval, and export."""
    from ..services.compliance_service import (CONTROLS, FRAMEWORK_TITLES,
                                               FRAMEWORKS,
                                               ComplianceService)

    routes = web.RouteTableDef()
    service: ComplianceService = app["compliance_service"]

    @routes.get("/compliance/frameworks")
    async def frameworks(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        return web.json_response([
            {"id": fw, "title": FRAMEWORK_TITLES[fw],
             "controls": [{"id": c.id, "title": c.title}
                          for c in CONTROLS[fw]]}
            for fw in FRAMEWORKS])

    @routes.post("/compliance/reports")
    async def generate(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("admin.all")
        from ..services.base import ValidationFailure
        body = await request.json()
        if not isinstance(body, dict):
            raise ValidationFailure("Body must be a JSON object")
        import time as _time

        import math

        def number(name: str, default: float) -> float:
            value = body.get(name)
            if value is None:
                return default
            if (not isinstance(value, (int, float))
                    or isinstance(value, bool)
                    or not math.isfinite(value)):
                # json.loads accepts NaN/Infinity literals; NaN bounds
                # would match no rows and serialize as non-standard JSON
                raise ValidationFailure(f"{name} must be a finite number")
            return float(value)

        days = number("period_days", 30.0)
        end = number("period_end", _time.time())
        start = number("period_start", end - days * 86400)
        report = await service.generate(body.get("framework", ""),
                                        start, end, generated_by=auth.user)
        return web.json_response(report, status=201)

    @routes.get("/compliance/reports")
    async def list_reports(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        return web.json_response(await service.list_reports())

    @routes.get("/compliance/reports/{report_id}")
    async def get_report(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        return web.json_response(
            await service.get_report(request.match_info["report_id"]))

    @routes.get("/compliance/reports/{report_id}/export")
    async def export_report(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        report_id = request.match_info["report_id"]
        if request.query.get("format", "json") == "markdown":
            text = await service.export_markdown(report_id)
            return web.Response(
                text=text, content_type="text/markdown",
                headers={"Content-Disposition":
                         f'attachment; filename="{report_id}.md"'})
        report = await service.get_report(report_id)
        return web.json_response(
            report, headers={"Content-Disposition":
                             f'attachment; filename="{report_id}.json"'})

    app.add_routes(routes)
