"""aiohttp middleware chain.

Reference stack (`/root/reference/mcpgateway/main.py:3259-3330`): CORS,
security headers, header-size guard, correlation id, compression, rate limit,
auth, RBAC, token scoping, request logging, OTel. Same capabilities here as
aiohttp middlewares, ordered outermost-first in ``MIDDLEWARES``.
"""

from __future__ import annotations

import asyncio
import base64
import time
import uuid
from typing import Awaitable, Callable

from aiohttp import web

from ..observability import phases as request_phases
from ..observability import tenant as tenant_ctx
from ..observability.tracing import current_span
from ..services.auth_service import AuthContext, AuthError, PermissionDenied
from ..services.base import ConflictError, NotFoundError, ValidationFailure
from .flight_recorder import backpressure_headers, queue_state

Handler = Callable[[web.Request], Awaitable[web.StreamResponse]]

PUBLIC_PATHS = {"/health", "/ready", "/version", "/auth/login", "/robots.txt",
                # reset flow is pre-auth by nature; both endpoints are
                # rate-limited + enumeration-hardened in the handlers
                "/auth/password/reset-request", "/auth/password/reset"}


@web.middleware
async def forwarded_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    """Honor X-Forwarded-For/Proto from a trusted edge (reference
    ProxyHeaders + ForwardedHostMiddleware). Off unless trust_proxy_headers
    — honoring client-supplied headers otherwise lets callers spoof their
    rate-limit identity."""
    settings = request.app["ctx"].settings
    client_ip = request.remote or "unknown"
    if settings.trust_proxy_headers:
        forwarded = request.headers.get("x-forwarded-for", "")
        if forwarded:
            # RIGHTMOST entry: the one the trusted edge appended — the
            # leftmost is client-supplied and would let callers mint a fresh
            # rate-limit identity per request
            client_ip = forwarded.split(",")[-1].strip() or client_ip
    request["client_ip"] = client_ip
    return await handler(request)


@web.middleware
async def header_size_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    """Reject oversized header blocks (reference HeaderSizeMiddleware) —
    431 before any downstream work."""
    settings = request.app["ctx"].settings
    limit = settings.max_header_bytes
    if limit:
        total = sum(len(k) + len(v) for k, v in request.raw_headers)
        if total > limit:
            return web.json_response(
                {"detail": f"Request headers exceed {limit} bytes"},
                status=431)
    if settings.max_header_count and \
            len(request.raw_headers) > settings.max_header_count:
        return web.json_response(
            {"detail": f"More than {settings.max_header_count} header fields"},
            status=431)
    if settings.max_header_field_bytes:
        for key, value in request.raw_headers:
            if len(key) + len(value) > settings.max_header_field_bytes:
                return web.json_response(
                    {"detail": "Header field exceeds "
                               f"{settings.max_header_field_bytes} bytes"},
                    status=431)
    return await handler(request)


@web.middleware
async def protocol_version_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    """Validate MCP-Protocol-Version when a client sends one (reference
    MCPProtocolVersionMiddleware): unsupported versions get a clear 400
    instead of undefined behavior deeper in the stack."""
    version = request.headers.get("mcp-protocol-version")
    if version and request.path.startswith(("/mcp", "/servers", "/rpc")):
        supported = request.app["ctx"].settings.supported_protocol_versions
        if version not in supported:
            return web.json_response(
                {"detail": f"Unsupported MCP protocol version {version!r};"
                           f" supported: {sorted(supported)}"}, status=400)
    return await handler(request)


@web.middleware
async def cors_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    """CORS for browser-based MCP clients (reference CORSMiddleware).
    Enabled by setting cors_allowed_origins; '*' allows any origin."""
    settings = request.app["ctx"].settings
    allowed = settings.cors_origins
    origin = request.headers.get("origin", "")
    grant = origin if (allowed and origin and
                       ("*" in allowed or origin in allowed)) else ""
    if request.method == "OPTIONS" and grant:
        headers = {
            "access-control-allow-origin": grant,
            "access-control-allow-methods": settings.cors_allowed_methods,
            "access-control-allow-headers": settings.cors_allowed_headers,
            "access-control-max-age": str(settings.cors_max_age_s),
            "vary": "origin",
        }
        if settings.cors_allow_credentials:
            headers["access-control-allow-credentials"] = "true"
        return web.Response(status=204, headers=headers)
    response = await handler(request)
    if grant:
        response.headers["access-control-allow-origin"] = grant
        response.headers.setdefault("vary", "origin")
        response.headers["access-control-expose-headers"] = \
            "mcp-session-id, x-correlation-id"
        if settings.cors_allow_credentials:
            response.headers["access-control-allow-credentials"] = "true"
    return response


@web.middleware
async def error_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    """Map domain errors to HTTP codes; never leak stack traces."""
    try:
        return await handler(request)
    except web.HTTPException:
        raise
    except NotFoundError as exc:
        return web.json_response({"detail": str(exc)}, status=404)
    except ConflictError as exc:
        return web.json_response({"detail": str(exc)}, status=409)
    except (ValidationFailure, ValueError) as exc:
        return web.json_response({"detail": str(exc)}, status=422)
    except AuthError as exc:
        return web.json_response({"detail": str(exc)}, status=401,
                                 headers={"www-authenticate": "Bearer"})
    except PermissionDenied as exc:
        return web.json_response({"detail": str(exc)}, status=403)
    except Exception as exc:  # pragma: no cover - last resort
        request.app.logger.exception("Unhandled error on %s", request.path)
        return web.json_response({"detail": f"Internal error: {type(exc).__name__}"},
                                 status=500)


def _extract_baggage(request: web.Request, settings) -> dict[str, str]:
    """W3C baggage from the inbound header plus configured header→key
    mappings (reference middleware/baggage_middleware.py +
    otel_baggage_* family). Values are percent-decoded per the W3C
    syntax, item count and TOTAL utf-8 size are bounded, and operator
    mappings are admitted BEFORE the untrusted inbound header so a
    padded baggage header cannot starve tenant attribution."""
    from urllib.parse import unquote

    entries: dict[str, str] = {}
    max_items = settings.otel_baggage_max_items
    budget = settings.otel_baggage_max_size_bytes

    def _add(key: str, value: str) -> None:
        nonlocal budget
        key = key.strip()
        value = unquote(value.strip()).replace(",", "").replace(";", "")[:256]
        cost = len(key.encode()) + len(value.encode())
        if key and value and len(entries) < max_items and cost <= budget:
            entries[key] = value
            budget -= cost

    for header, key in settings.otel_baggage_header_mappings:
        value = request.headers.get(header)
        if value:
            _add(key, value)
    raw = request.headers.get("baggage", "")
    for member in raw.split(","):
        if "=" in member:
            key, value = member.split("=", 1)
            _add(key, value.split(";", 1)[0])  # properties are dropped
    return entries


@web.middleware
async def observability_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    """Correlation id + span + Prometheus metrics per request."""
    ctx = request.app["ctx"]
    settings = ctx.settings
    inbound = (request.headers.get(settings.correlation_id_header, "")
               if settings.correlation_id_preserve else "")
    correlation_id = inbound or uuid.uuid4().hex[:16]
    request["correlation_id"] = correlation_id
    started = time.monotonic()
    route = request.match_info.route.resource
    path_label = route.canonical if route is not None else request.path
    attrs = {
        "http.method": request.method, "http.path": request.path,
        "correlation_id": correlation_id,
    }
    if settings.otel_baggage_enabled:
        baggage = _extract_baggage(request, settings)
        request["baggage"] = baggage
        attrs.update({f"baggage.{k}": v for k, v in baggage.items()})
    with ctx.tracer.span("http.request", attrs,
                         traceparent=request.headers.get("traceparent")) as span:
        # route TEMPLATE for bounded-cardinality consumers (the trace
        # store's slowest-per-route tables); unmatched paths are
        # client-controlled and collapse to one key
        span.set_attribute("http.route",
                           path_label if route is not None else "unmatched")
        response = await handler(request)
        span.set_attribute("http.status_code", response.status)
        elapsed = time.monotonic() - started
        ctx.metrics.http_requests.labels(request.method, path_label, str(response.status)).inc()
        # tenant resolved by the auth middleware (deeper in the chain —
        # set by the time the handler returns); requests rejected before
        # auth (rate limit, header size) read as anonymous. Clamped: the
        # label child set stays bounded at tenant_label_clamp + 1. The
        # span carries the EXACT tenant (bounded store, no cardinality
        # concern) so the trace store can slice slowest-N per tenant,
        # and the observe rides a trace-id exemplar: a p99 spike on the
        # http histogram clicks through to a retained trace
        span.set_attribute("gw.tenant",
                           request.get("tenant") or tenant_ctx.ANONYMOUS)
        tenant_label = ctx.metrics.tenant_clamp.label(
            request.get("tenant") or tenant_ctx.ANONYMOUS)
        ctx.metrics.http_duration.labels(
            request.method, path_label, tenant_label,
        ).observe(elapsed, exemplar=ctx.metrics.exemplar(
            "http_duration", elapsed, span.trace_id,
            (request.method, path_label, tenant_label)))
        perf = ctx.extras.get("perf_tracker")
        if perf is not None:
            # the flight recorder (one layer in) already attributed this
            # request; ride its phase vector on the tracker's slow-op
            # warning so "http.request: 3786 ms" is never a bare
            # duration again (r05 bench-tail satellite). Formatted only
            # when the record will actually WARN — record() reads
            # component on the slow branch alone, and stringifying a
            # dict per request is hot-path waste
            entry = request.get("flight_entry")
            slow = entry is not None and perf.will_warn("http.request",
                                                        elapsed)
            perf.record("http.request", elapsed,
                        component=(f"phases={entry['phases_ms']}"
                                   if slow else None))
        response.headers[settings.correlation_id_response_header] = \
            correlation_id
        return response


@web.middleware
async def flight_recorder_middleware(request: web.Request,
                                     handler: Handler) -> web.StreamResponse:
    """Gateway data-plane flight recorder (flight_recorder.py +
    observability/phases.py): open a PhaseClock for the request, let the
    instrumented layers (auth resolution, plugin hooks, DB statements,
    the engine handoff, serialization) charge their wall into named
    buckets, then record the completed request — phase vector, status,
    trace ids — into the bounded rings behind
    ``GET /admin/gateway/requests``, the per-route phase histograms, and
    a ``gw.phases`` event on the ``http.request`` span. The residue
    (wall minus every attributed phase) reports as ``handler`` — or
    ``error`` when an exception passed through — so the vector always
    sums to the measured wall (tolerance-gated in tests).

    Sits just inside observability_middleware: current_span() is the
    http.request span here, and client-disconnect CancelledErrors still
    propagate through (rows for aborted requests carry
    ``client_disconnected``). Also surfaces engine-pool admission depth
    as X-Queue-Depth / Retry-After backpressure headers on the LLM
    serving surface."""
    settings = request.app["ctx"].settings
    recorder = request.app.get("flight_recorder")
    if recorder is None:
        # recorder off is NOT backpressure off: the two are independent
        # knobs, and clients must keep their queue-depth signal
        response = await handler(request)
        _apply_backpressure(request, response, settings)
        return response
    clock = request_phases.PhaseClock()
    token = request_phases.set_phase_clock(clock)
    span = current_span()
    trace = span.context() if span is not None else None
    rid = recorder.start_request(request.path, trace)
    started = time.perf_counter()
    response: web.StreamResponse | None = None
    error: str | None = None
    disconnected = False
    try:
        response = await handler(request)
        return response
    except web.HTTPException as exc:
        response = exc  # an HTTPException IS its response
        raise
    except asyncio.CancelledError:
        error = "CancelledError"
        disconnected = True
        raise
    except Exception as exc:  # recorded, then translated upstream
        error = type(exc).__name__
        raise
    finally:
        recorder.finish_request(rid)
        request_phases.reset_phase_clock(token)
        wall = time.perf_counter() - started
        clock.add("error" if error else "handler",
                  max(0.0, wall - clock.total()))
        if response is not None:
            status = response.status
        elif disconnected:
            status = 499  # client closed request (nginx convention)
        else:
            status = 500
        route = request.match_info.route.resource
        # unmatched paths are client-controlled: one fixed label child,
        # never a per-path Prometheus series (the row keeps the raw path)
        route_label = route.canonical if route is not None else "unmatched"
        phases_ms = clock.vector_ms()
        if error is None and status >= 500:
            # the handler's exception was already translated to a 5xx
            # below us — the row must still say this request failed
            error = f"http_{status}"
        entry = recorder.record(
            method=request.method, path=request.path, route=route_label,
            status=status, duration_s=wall, phases_ms=phases_ms,
            trace_id=trace[0] if trace else None,
            span_id=trace[1] if trace else None,
            correlation_id=request.get("correlation_id"),
            tenant=request.get("tenant"),
            error=error,
            client_disconnected=(disconnected
                                 or bool(request.get("client_disconnected"))))
        request["flight_entry"] = entry
        if span is not None:
            span.add_event("gw.phases", {
                "duration_ms": entry["duration_ms"], **phases_ms})
        if response is not None:
            _apply_backpressure(request, response, settings)


def _apply_backpressure(request: web.Request,
                        response: web.StreamResponse, settings) -> None:
    """X-Queue-Depth / Retry-After on the LLM serving surface (unary
    responses; the SSE path sets them pre-prepare in tpu_local/server).
    queue_state() feeds the saturation gauge as a side effect."""
    if (not settings.gw_backpressure_headers or response.prepared
            or not request.path.startswith(
                (settings.llm_api_prefix + "/", "/llmchat"))):
        return
    response.headers.update(
        backpressure_headers(queue_state(request.app), settings))


@web.middleware
async def deprecation_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    """Sunset/Deprecation headers on configured legacy path prefixes
    (reference middleware/deprecation.py + legacy_api_* settings): lets
    an operator announce an endpoint's retirement machine-readably
    (RFC 8594) without touching handlers."""
    response = await handler(request)
    settings = request.app["ctx"].settings
    prefixes = settings.deprecated_path_prefixes
    if prefixes and any(request.path.startswith(p) for p in prefixes):
        response.headers["Deprecation"] = "true"
        response.headers["X-Deprecated-Endpoint"] = request.path
        if settings.legacy_api_sunset_date:
            response.headers["Sunset"] = settings.legacy_api_sunset_date
    return response


@web.middleware
async def security_headers_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    response = await handler(request)
    response.headers.setdefault("x-content-type-options", "nosniff")
    response.headers.setdefault("x-frame-options", "DENY")
    response.headers.setdefault("referrer-policy", "no-referrer")
    response.headers.setdefault("cache-control", "no-store")
    return response


class RateLimiter:
    """Per-client token bucket (reference RateLimitMiddleware).

    The bucket dict is kept in RECENCY order (allow() re-inserts the key,
    so dict iteration order == least-recently-seen first): overflow
    eviction pops from the front in O(evictions) instead of sorting the
    whole dict mid-flood (round-2 VERDICT weak #10 residual)."""

    # a bucket that would refill to full is state-free (recreating it at
    # full burst is identical), so it can be pruned losslessly; prune so IP
    # churn cannot grow the dict without bound
    _SWEEP_INTERVAL = 60.0

    def __init__(self, rps: int, burst: int, max_buckets: int = 100_000) -> None:
        self.rps = rps
        self.burst = burst
        self.max_buckets = max_buckets
        self._buckets: dict[str, tuple[float, float]] = {}  # key -> (tokens, last)
        self._next_sweep = time.monotonic() + self._SWEEP_INTERVAL

    def _sweep(self, now: float) -> None:
        self._buckets = {
            k: (tokens, last) for k, (tokens, last) in self._buckets.items()
            if tokens + (now - last) * self.rps < self.burst}
        self._next_sweep = now + self._SWEEP_INTERVAL

    def allow(self, key: str) -> bool:
        if self.rps <= 0:
            return True
        now = time.monotonic()
        if now >= self._next_sweep:
            self._sweep(now)
        entry = self._buckets.pop(key, None)  # re-insert -> recency order
        tokens, last = entry if entry is not None else (float(self.burst), now)
        tokens = min(self.burst, tokens + (now - last) * self.rps)
        allowed = tokens >= 1.0
        self._buckets[key] = (tokens - 1.0 if allowed else tokens, now)
        while len(self._buckets) > self.max_buckets:
            # oldest-first eviction, O(1) per surplus entry (dict iteration
            # order == insertion order == recency here; no key-list copy)
            del self._buckets[next(iter(self._buckets))]
        return allowed


@web.middleware
async def host_validation_middleware(request: web.Request,
                                     handler: Handler) -> web.StreamResponse:
    """Reject requests whose Host header isn't allowlisted (reference
    forwarded-host validation tier). '' (default) allows any host —
    deployments behind a proxy pin MCPFORGE_ALLOWED_HOSTS."""
    allowed = request.app["ctx"].settings.allowed_host_set
    if allowed:
        host = (request.host or "").split(":", 1)[0].lower()
        if host not in allowed:
            return web.json_response({"detail": f"Host {host!r} not allowed"},
                                     status=421)
    return await handler(request)


@web.middleware
async def compression_middleware(request: web.Request,
                                 handler: Handler) -> web.StreamResponse:
    """Negotiated response compression with SSE special-casing (reference
    SSEAwareCompressMiddleware): event streams and small bodies are never
    compressed — compressing an SSE response would buffer/break it."""
    response = await handler(request)
    settings = request.app["ctx"].settings
    if not settings.compression_enabled:
        return response
    if not isinstance(response, web.Response) or response.body is None:
        return response  # streaming (SSE/WS upgrade): leave untouched
    if response.content_type == "text/event-stream":
        return response
    if "content-encoding" in response.headers:
        return response
    if len(response.body) < settings.compression_min_bytes:
        return response
    response.enable_compression()  # negotiates via Accept-Encoding
    return response


@web.middleware
async def client_disconnect_middleware(request: web.Request,
                                       handler: Handler) -> web.StreamResponse:
    """Observe client disconnects (reference client-disconnect middleware):
    aiohttp cancels the handler task when the peer goes away mid-request;
    count it and mark the trace instead of logging a naked
    CancelledError."""
    try:
        return await handler(request)
    except asyncio.CancelledError:
        metrics = request.app["ctx"].metrics
        if metrics is not None:
            metrics.client_disconnects.inc()
        request["client_disconnected"] = True
        raise


@web.middleware
async def rate_limit_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    limiter: RateLimiter = request.app["rate_limiter"]
    key = request.get("client_ip") or request.remote or "unknown"
    if not limiter.allow(key):
        return web.json_response({"detail": "Rate limit exceeded"}, status=429,
                                 headers={"retry-after": "1"})
    return await handler(request)


async def _handle_as_tenant(request: web.Request,
                            handler: Handler) -> web.StreamResponse:
    """Run the rest of the chain under the principal's resolved tenant:
    ``request['tenant']`` for the observability/flight-recorder layers
    above, and the contextvar the LLM provider stamps onto GenRequests
    (team → API key → user resolution; docs/multitenancy.md)."""
    tenant = tenant_ctx.resolve_tenant(request.get("auth"))
    request["tenant"] = tenant
    token = tenant_ctx.set_current_tenant(tenant)
    try:
        return await handler(request)
    finally:
        tenant_ctx.reset_current_tenant(token)


@web.middleware
async def auth_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    """Resolve identity (Bearer JWT / Basic) into request['auth'].

    Plugin http_auth_resolve_user hooks may override resolution; the
    http_pre_request hook runs after auth (reference HttpAuthMiddleware +
    run_pre_request_hooks).
    """
    ctx = request.app["ctx"]
    auth_service = request.app["auth_service"]
    settings = ctx.settings

    if (request.method == "OPTIONS" or request.path in PUBLIC_PATHS
            or request.path.startswith("/auth/sso/")
            # well-known files are public discovery surface by definition
            # (gateway-level AND per-server; reference well_known +
            # server_well_known routers serve them unauthenticated)
            or request.path.startswith("/.well-known/")
            or (request.path.startswith("/servers/")
                and request.path.endswith("/.well-known/mcp"))):
        request["auth"] = AuthContext(user="anonymous", via="anonymous")
        return await _handle_as_tenant(request, handler)

    # flight-recorder attribution: identity resolution (header parse,
    # plugin resolve, DB-backed bearer/basic lookups) charges the "auth"
    # phase; the plugin hooks inside charge "plugins" via PluginManager
    # and self-time accounting keeps the two from double-counting
    with request_phases.phase("auth"):
        header = request.headers.get(settings.auth_header_name, "")
        auth_ctx: AuthContext | None = None
        pm = ctx.plugin_manager
        if pm is not None:
            auth_ctx = await pm.http_auth_resolve_user(dict(request.headers))
        if auth_ctx is None:
            if header.lower().startswith("bearer "):
                auth_ctx = await auth_service.resolve_bearer(header[7:].strip())
            elif header.lower().startswith("basic "):
                try:
                    decoded = base64.b64decode(header[6:].strip()).decode()
                    username, _, password = decoded.partition(":")
                except Exception as exc:
                    raise AuthError("Malformed basic credentials") from exc
                auth_ctx = await auth_service.resolve_basic(username, password)
            elif not settings.auth_required:
                auth_ctx = AuthContext(user="anonymous", is_admin=True, via="anonymous")
            else:
                raise AuthError("Authentication required")
        request["auth"] = auth_ctx
    if pm is not None:
        await pm.http_pre_request(request.method, request.path, dict(request.headers),
                                  user=auth_ctx.user)
    return await _handle_as_tenant(request, handler)


@web.middleware
async def csrf_middleware(request: web.Request, handler: Handler
                          ) -> web.StreamResponse:
    """CSRF protection for the ambient-credential surface (reference
    middleware/csrf_middleware.py + services/csrf_service.py).

    Runs AFTER auth (needs the resolved identity). Bearer-token requests
    are exempt — a cross-site page cannot set an Authorization header
    with a token it doesn't hold. Basic-auth and cookie-session requests
    ride credentials the BROWSER attaches automatically, so unsafe
    methods must prove same-origin provenance:

    - browser-declared cross-site (``Sec-Fetch-Site``/mismatched
      ``Origin``) → 403 (non-browser clients send neither header and are
      not CSRF-able);
    - when the admin page's ``csrf_token`` cookie is present, the
      ``X-CSRF-Token`` header must echo it and verify (double-submit:
      cross-site JS can make the browser SEND the cookie, not READ it).
    """
    from ..services import csrf_service

    settings = request.app["ctx"].settings
    if (not settings.csrf_enabled
            or request.method in csrf_service.SAFE_METHODS
            or request.path in PUBLIC_PATHS):
        return await handler(request)
    for exempt in settings.csrf_exempt_paths:
        if request.path == exempt or \
                request.path.startswith(exempt.rstrip("/") + "/"):
            return await handler(request)
    auth = request.get("auth")
    header = request.headers.get(settings.auth_header_name, "")
    if header.lower().startswith("bearer ") or auth is None \
            or auth.via == "anonymous":
        return await handler(request)
    host = request.headers.get("host", "")
    if csrf_service.browser_cross_site(request.headers, host,
                                       settings.csrf_trusted_origins):
        return web.json_response(
            {"detail": "CSRF validation failed", "code": "CSRF_CROSS_SITE"},
            status=403)
    if settings.csrf_check_referer and not (
            request.headers.get("origin")
            or request.headers.get("referer")
            or request.headers.get("sec-fetch-site")):
        # fail-closed posture: ambient-credential mutations must declare
        # provenance (rejects legacy browsers AND non-browser basic-auth
        # clients — that is the documented trade of enabling this knob)
        return web.json_response(
            {"detail": "CSRF validation failed",
             "code": "CSRF_NO_PROVENANCE"}, status=403)
    cookie = request.cookies.get(settings.csrf_cookie_name)
    if cookie:
        echoed = request.headers.get(settings.csrf_header_name, "")
        import hmac as _hmac
        if not echoed or not _hmac.compare_digest(echoed, cookie) \
                or not csrf_service.validate(echoed, auth.user,
                                             settings.jwt_secret_key):
            return web.json_response(
                {"detail": "CSRF validation failed",
                 "code": "CSRF_TOKEN_INVALID"}, status=403)
    return await handler(request)


@web.middleware
async def password_change_middleware(request: web.Request, handler: Handler
                                     ) -> web.StreamResponse:
    """Mandatory password-change enforcement (reference
    middleware/password_change_enforcement.py): an interactive identity
    whose ``password_change_required`` flag is set may only reach the
    password-change surface until it rotates. API tokens (programmatic)
    are exempt, as are the endpoints needed to perform the change; the
    REST shape is a 403 with a machine-readable code (the reference's
    browser tier 303-redirects to its change-password page)."""
    settings = request.app["ctx"].settings
    if not settings.password_change_enforcement_enabled:
        return await handler(request)
    auth = request.get("auth")
    if (auth is None or auth.via == "anonymous" or auth.token_jti
            or auth.scoped or request.path in PUBLIC_PATHS
            or request.path == "/auth/password"):
        return await handler(request)
    # the flag rides AuthContext (read in resolve_*'s existing users-row
    # fetch) — no extra hot-path query here
    if auth.password_change_required:
        return web.json_response(
            {"detail": "Password change required before further access",
             "code": "PASSWORD_CHANGE_REQUIRED",
             "change_url": "/auth/password"}, status=403)
    return await handler(request)


@web.middleware
async def token_usage_middleware(request: web.Request, handler: Handler
                                 ) -> web.StreamResponse:
    """API-token usage accounting (reference
    middleware/token_usage_middleware.py + TokenUsageLog, db.py:5565):
    every request that authenticates with an API token (jti-bearing JWT)
    is recorded — endpoint, status, latency, client — including 4xx
    outcomes (marked blocked) and 401 rejections of revoked/expired
    tokens, where the jti is recovered from the unverified payload and
    checked against the token catalog so forged tokens can't spam the
    log. Sits OUTSIDE error translation to see final statuses."""
    settings = request.app["ctx"].settings
    if not settings.token_usage_logging_enabled:
        return await handler(request)
    started = time.monotonic()
    response = await handler(request)
    auth = request.get("auth")
    jti = auth.token_jti if auth is not None else None
    user_email = auth.user if auth is not None else None
    if jti is None and response.status in (401, 403):
        # auth rejected before an identity existed: identify (not trust)
        # the token, then confirm the jti is a real catalog row
        header = request.headers.get(settings.auth_header_name, "")
        if header.lower().startswith("bearer "):
            from ..utils import jwt as jwt_utils
            payload = jwt_utils.decode_unverified(header[7:].strip())
            candidate = (payload or {}).get("jti")
            if candidate:
                row = await request.app["ctx"].db.fetchone(
                    "SELECT jti, user_email FROM api_tokens WHERE jti=?",
                    (candidate,))
                if row:
                    jti = row["jti"]
                    # catalog attribution ONLY: the unverified sub is
                    # attacker-chosen and must not spoof the trail
                    user_email = row["user_email"]
    if jti is not None:
        # "blocked" means a security denial (authn/authz/rate limit) —
        # routine 404s/validation 400s are normal traffic, and counting
        # them would poison the compliance evidence built on this table
        blocked = response.status in (401, 403, 429)
        row_values = (
            jti, user_email, time.time(), request.method, request.path,
            response.status,
            round((time.monotonic() - started) * 1000, 2),
            request.get("client_ip", request.remote),
            request.headers.get("user-agent", "")[:256],
            1 if blocked else 0,
            f"http_{response.status}" if blocked else None)

        async def _record() -> None:
            try:
                await request.app["ctx"].db.execute(
                    "INSERT INTO token_usage_logs (token_jti, user_email,"
                    " ts, method, path, status, response_ms, client_ip,"
                    " user_agent, blocked, block_reason)"
                    " VALUES (?,?,?,?,?,?,?,?,?,?,?)", row_values)
            except Exception:  # accounting must never break serving
                request.app.logger.debug("token usage write failed",
                                         exc_info=True)

        # off the critical path: the response must not wait on the
        # serialized DB executor for an accounting write. The task set
        # (created in build_app — a frozen aiohttp app rejects new keys)
        # holds strong references (the loop keeps only weak ones) and is
        # drained at shutdown so final-request rows aren't lost.
        tasks: set = request.app["_token_usage_tasks"]
        task = asyncio.ensure_future(_record())
        tasks.add(task)
        task.add_done_callback(tasks.discard)
    return response


@web.middleware
async def db_query_logging_middleware(request: web.Request, handler: Handler
                                      ) -> web.StreamResponse:
    """Per-request DB query telemetry (reference
    middleware/db_query_logging.py): when enabled, every query the
    handler runs is collected (innermost middleware — auth-layer queries
    are excluded by position), slow statements WARN, and N+1 patterns
    (the same normalized statement repeated >= threshold times) are
    called out. Response gains X-DB-Query-Count/-Time-MS headers so the
    signal is scriptable without log scraping."""
    settings = request.app["ctx"].settings
    if not settings.db_query_logging:
        return await handler(request)
    from ..db.core import query_log_capture
    with query_log_capture() as queries:
        response = await handler(request)
    if not queries:
        return response
    logger = request.app.logger
    total_ms = sum(ms for _, ms in queries)
    response.headers["X-DB-Query-Count"] = str(len(queries))
    response.headers["X-DB-Query-Time-MS"] = f"{total_ms:.2f}"
    for sql, ms in queries:
        if ms >= settings.db_query_logging_slow_ms:
            logger.warning("slow query (%.1f ms) on %s %s: %s",
                           ms, request.method, request.path, sql[:300])
    shapes: dict[str, int] = {}
    for sql, _ in queries:
        shapes[sql] = shapes.get(sql, 0) + 1
    suspects = {sql: n for sql, n in shapes.items()
                if n >= settings.db_query_n1_threshold}
    if suspects:
        logger.warning(
            "possible N+1 on %s %s: %s", request.method, request.path,
            "; ".join(f"{n}x {sql[:160]}" for sql, n in suspects.items()))
    else:
        logger.debug("%s %s ran %d queries in %.2f ms", request.method,
                     request.path, len(queries), total_ms)
    return response


@web.middleware
async def request_logging_middleware(request: web.Request, handler: Handler
                                     ) -> web.StreamResponse:
    """DEBUG-level request/response logging with sensitive-value masking via
    the native extension (reference: RequestLoggingMiddleware + the Rust
    masking crate)."""
    logger = request.app.logger
    if logger.isEnabledFor(10):  # DEBUG
        from ..utils.masking import mask_text
        body = await request.text() if request.can_read_body else ""
        logger.debug("req %s %s %s", request.method, request.path,
                     mask_text(body[:4096]) if body else "")
    response = await handler(request)
    if logger.isEnabledFor(10):
        logger.debug("resp %s %s -> %s", request.method, request.path,
                     response.status)
    # audit trail: record successful mutations (reference AuditTrail)
    audit = request.app.get("audit_service")
    if (audit is not None and request.method in ("POST", "PUT", "DELETE")
            and 200 <= response.status < 300
            and not request.path.startswith(("/rpc", "/mcp", "/messages",
                                             "/v1/", "/llmchat"))):
        auth = request.get("auth")
        await audit.record(auth.user if auth else None,
                           f"{request.method} {request.path}",
                           details={"status": response.status})
    return response


# Order matters: observability outermost so error responses still get
# metrics + correlation ids; error_middleware outside rate-limit/auth so
# AuthError and friends map to status codes.
MIDDLEWARES = [
    observability_middleware,
    # flight recorder just inside observability: current_span() is the
    # http.request span, and disconnect CancelledErrors (re-raised one
    # layer down) still pass through so aborted requests get rows too
    flight_recorder_middleware,
    client_disconnect_middleware,
    forwarded_middleware,
    host_validation_middleware,
    cors_middleware,
    compression_middleware,
    security_headers_middleware,
    deprecation_middleware,
    header_size_middleware,
    # token usage sits OUTSIDE error translation so 401/403 rejections of
    # revoked tokens surface here as statuses, not exceptions
    token_usage_middleware,
    error_middleware,
    protocol_version_middleware,
    rate_limit_middleware,
    auth_middleware,
    # csrf + password-change need the resolved identity (inside auth)
    csrf_middleware,
    password_change_middleware,
    request_logging_middleware,
    # innermost: captures only the HANDLER's queries (auth/limit-layer
    # queries run above and are excluded by position)
    db_query_logging_middleware,
]
