"""WebSocket + legacy-SSE inbound transports.

Reference: `transports/websocket_transport.py` (JSON-RPC over WS frames) and
`transports/sse_transport.py` (GET stream + POST /messages back-channel with
keepalives). Both feed the same RPCDispatcher as /mcp.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any

from aiohttp import WSMsgType, web

from ...jsonrpc import JSONRPCError, RPCRequest
from ...utils.ids import new_id
from .streamable_http import SessionManager, _sse_frame


class WebSocketTransport:
    def __init__(self, dispatcher, settings):
        self.dispatcher = dispatcher
        self.settings = settings

    async def handle(self, request: web.Request) -> web.WebSocketResponse:
        ws = web.WebSocketResponse(heartbeat=self.settings.websocket_ping_interval)
        await ws.prepare(request)
        auth = request["auth"]
        headers = {k.lower(): v for k, v in request.headers.items()}
        server_id = request.match_info.get("server_id")
        limiter = request.app.get("rate_limiter")
        client_key = request.remote or "unknown"
        async for msg in ws:
            if msg.type != WSMsgType.TEXT:
                continue
            # per-message rate limiting: the HTTP middleware only saw the
            # upgrade request, not the frames
            if limiter is not None and not limiter.allow(client_key):
                await ws.send_json({"jsonrpc": "2.0", "id": None,
                                    "error": {"code": -32000,
                                              "message": "Rate limit exceeded"}})
                continue
            try:
                payload = json.loads(msg.data)
            except json.JSONDecodeError:
                await ws.send_json({"jsonrpc": "2.0", "id": None,
                                    "error": {"code": -32700, "message": "Parse error"}})
                continue
            messages = payload if isinstance(payload, list) else [payload]
            for message in messages:
                try:
                    rpc_request = RPCRequest.parse(message)
                    response = await self.dispatcher.dispatch(
                        rpc_request, auth, headers=headers, server_id=server_id)
                except JSONRPCError as exc:
                    response = exc.to_dict(
                        message.get("id") if isinstance(message, dict) else None)
                if response is not None:
                    await ws.send_json(response)
        return ws


class LegacySSETransport:
    """GET /sse opens the stream; first event names the POST back-channel
    (/messages?session_id=...); responses ride the stream as message events."""

    def __init__(self, dispatcher, settings, session_manager: SessionManager | None = None):
        self.dispatcher = dispatcher
        self.settings = settings
        self.sessions = session_manager or SessionManager(ttl=settings.session_ttl)
        self._auth: dict[str, Any] = {}

    async def handle_stream(self, request: web.Request) -> web.StreamResponse:
        session = self.sessions.create()
        self._auth[session.id] = request["auth"]
        resp = web.StreamResponse(headers={
            "content-type": "text/event-stream", "cache-control": "no-store"})
        await resp.prepare(request)
        endpoint = f"/messages?session_id={session.id}"
        await resp.write(f"event: endpoint\ndata: {endpoint}\n\n".encode())
        keepalive = self.settings.sse_keepalive_interval
        try:
            while True:
                try:
                    event_id, message = await asyncio.wait_for(session.queue.get(),
                                                               timeout=keepalive)
                    await resp.write(_sse_frame(event_id, message))
                except asyncio.TimeoutError:
                    await resp.write(b": keepalive\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            self._auth.pop(session.id, None)
            self.sessions.drop(session.id)
        return resp

    async def handle_message(self, request: web.Request) -> web.Response:
        session_id = request.query.get("session_id", "")
        session = self.sessions.get(session_id)
        if session is None:
            return web.json_response({"detail": "Unknown session"}, status=404)
        # dispatch under the POSTER's auth, and only if the poster is the
        # stream owner — a leaked session_id must not grant the owner's
        # permissions to someone else
        auth = request["auth"]
        owner = self._auth.get(session_id)
        if owner is not None and owner.user != auth.user:
            return web.json_response({"detail": "Session belongs to another user"},
                                     status=403)
        headers = {k.lower(): v for k, v in request.headers.items()}
        headers["mcp-session-id"] = session_id
        try:
            payload = json.loads(await request.read())
        except json.JSONDecodeError:
            return web.json_response({"detail": "Parse error"}, status=400)
        try:
            rpc_request = RPCRequest.parse(payload)
            response = await self.dispatcher.dispatch(rpc_request, auth,
                                                      headers=headers)
        except JSONRPCError as exc:
            response = exc.to_dict(payload.get("id") if isinstance(payload, dict)
                                   else None)
        if response is not None:
            await self.sessions.send_to_session(session_id, response)
        return web.Response(status=202)
