"""Streamable-HTTP transport (MCP 2025-03-26+).

Reference: `/root/reference/mcpgateway/transports/streamablehttp_transport.py`
(5.6k LoC around the ``mcp`` SDK session manager; `InMemoryEventStore` :467).
In-tree implementation of the same wire behavior:

- ``POST``: JSON-RPC message(s) in, ``application/json`` (or SSE stream) out;
  notifications → 202.
- Stateful mode: ``initialize`` mints an ``Mcp-Session-Id``; ``GET`` opens the
  server→client SSE stream with ``Last-Event-ID`` resume from the event
  store; ``DELETE`` ends the session.
- Stateless mode (default): every POST is self-contained.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field
from typing import Any

from aiohttp import web

from ...jsonrpc import JSONRPCError, RPCRequest, error_response, INVALID_REQUEST, PARSE_ERROR
from ...utils.ids import new_id
from ..serialize import encode_json


@dataclass
class EventStoreEntry:
    event_id: str
    message: dict[str, Any]


class InMemoryEventStore:
    """Per-session replay buffer for SSE resume (Last-Event-ID)."""

    def __init__(self, max_events_per_session: int = 512) -> None:
        self._events: dict[str, list[EventStoreEntry]] = {}
        self._max = max_events_per_session
        self._counter = 0

    def append(self, session_id: str, message: dict[str, Any]) -> str:
        self._counter += 1
        event_id = f"{session_id}-{self._counter}"
        bucket = self._events.setdefault(session_id, [])
        bucket.append(EventStoreEntry(event_id, message))
        if len(bucket) > self._max:
            del bucket[: len(bucket) - self._max]
        return event_id

    def replay_after(self, session_id: str, last_event_id: str) -> list[EventStoreEntry]:
        bucket = self._events.get(session_id, [])
        out, seen = [], False
        for entry in bucket:
            if seen:
                out.append(entry)
            elif entry.event_id == last_event_id:
                seen = True
        return out if seen else list(bucket)

    def drop(self, session_id: str) -> None:
        self._events.pop(session_id, None)


@dataclass
class StreamSession:
    id: str
    created_at: float = field(default_factory=time.time)
    last_seen: float = field(default_factory=time.time)
    queue: asyncio.Queue = field(default_factory=lambda: asyncio.Queue(maxsize=256))
    initialized: bool = False


class SessionManager:
    SWEEP_INTERVAL = 60.0

    def __init__(self, ttl: float = 3600.0) -> None:
        self.sessions: dict[str, StreamSession] = {}
        self.events = InMemoryEventStore()
        self.ttl = ttl
        self._sweeper: asyncio.Task | None = None
        self.metrics = None  # PrometheusRegistry, set by app wiring

    def _sync_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.sessions_active.set(len(self.sessions))

    async def start_sweeper(self) -> None:
        if self._sweeper is None:
            async def _loop() -> None:
                while True:
                    await asyncio.sleep(self.SWEEP_INTERVAL)
                    self.sweep()
            self._sweeper = asyncio.create_task(_loop())

    async def stop_sweeper(self) -> None:
        if self._sweeper is not None:
            self._sweeper.cancel()
            try:
                await self._sweeper
            except asyncio.CancelledError:
                pass
            self._sweeper = None

    def create(self) -> StreamSession:
        session = StreamSession(id=new_id())
        self.sessions[session.id] = session
        self._sync_gauge()
        return session

    def get(self, session_id: str) -> StreamSession | None:
        session = self.sessions.get(session_id)
        if session is not None:
            session.last_seen = time.time()
        return session

    def drop(self, session_id: str) -> None:
        self.sessions.pop(session_id, None)
        self.events.drop(session_id)
        self._sync_gauge()

    def sweep(self) -> None:
        cutoff = time.time() - self.ttl
        for sid in [s for s, sess in self.sessions.items() if sess.last_seen < cutoff]:
            self.drop(sid)

    async def send_to_session(self, session_id: str, message: dict[str, Any]) -> bool:
        """Queue a server-initiated message (notifications fanout)."""
        session = self.sessions.get(session_id)
        if session is None:
            return False
        event_id = self.events.append(session_id, message)
        try:
            session.queue.put_nowait((event_id, message))
            return True
        except asyncio.QueueFull:
            return False

    async def broadcast(self, message: dict[str, Any]) -> int:
        """Send a notification to every live session (listChanged fanout)."""
        count = 0
        for session_id in list(self.sessions):
            if await self.send_to_session(session_id, message):
                count += 1
        return count


_FRAME_EVENT = b"event: message\ndata: "


def _sse_frame(event_id: str | None, data: Any) -> bytes:
    # shared compact encoder + pre-built framing (gateway/serialize.py);
    # resume/handoff byte-equality holds because every writer — owner,
    # replayer, cross-worker forwarder — goes through THIS function
    if event_id:
        return b"".join((b"id: ", event_id.encode(), b"\n",
                         _FRAME_EVENT, encode_json(data), b"\n\n"))
    return b"".join((_FRAME_EVENT, encode_json(data), b"\n\n"))


class StreamableHTTPTransport:
    """Bound to a dispatcher; mounted at /mcp and /servers/{id}/mcp."""

    def __init__(self, dispatcher, settings, session_manager: SessionManager | None = None):
        self.dispatcher = dispatcher
        self.settings = settings
        self.sessions = session_manager or SessionManager(ttl=settings.session_ttl)
        self.affinity = None  # SessionAffinityService (multi-worker), set by app
        self.elicitation = None  # ElicitationService, set by app

    # ------------------------------------------------------------------ POST

    async def handle_post(self, request: web.Request) -> web.StreamResponse:
        auth = request["auth"]
        server_id = request.match_info.get("server_id")
        stateful = self.settings.streamable_http_stateful
        try:
            raw = await request.read()
            payload = json.loads(raw) if raw else None
        except json.JSONDecodeError:
            return web.json_response(error_response(None, PARSE_ERROR, "Parse error"),
                                     status=400)
        if payload is None:
            return web.json_response(error_response(None, INVALID_REQUEST, "Empty body"),
                                     status=400)

        messages = payload if isinstance(payload, list) else [payload]
        if not messages:
            return web.json_response(error_response(None, INVALID_REQUEST, "Empty batch"),
                                     status=400)

        session: StreamSession | None = None
        session_id = request.headers.get("mcp-session-id")
        if stateful:
            is_initialize = any(
                isinstance(m, dict) and m.get("method") == "initialize" for m in messages)
            if session_id:
                session = self.sessions.get(session_id)
                if session is not None and self.affinity is not None:
                    # sliding ownership: renew the owner lease on activity so
                    # it tracks the local session's sliding TTL
                    await self.affinity.claim_session(session_id)
                if session is None:
                    # another worker may own it (ADR-052): forward over the bus
                    if self.affinity is not None and not \
                            await self.affinity.is_local(session_id):
                        replies = []
                        forwarded = True
                        auth_info = {"user": auth.user, "is_admin": auth.is_admin,
                                     "teams": auth.teams,
                                     "permissions": sorted(auth.permissions),
                                     "headers": {"mcp-session-id": session_id}}
                        from ...jsonrpc import is_response_message
                        for message in messages:
                            reply = await self.affinity.forward(
                                session_id, message, auth_info=auth_info)
                            expects_reply = (isinstance(message, dict)
                                             and "method" in message
                                             and "id" in message)
                            if reply is None and expects_reply:
                                # owner died mid-claim: no one can answer this
                                # request — 404 so the client re-initializes.
                                # (notifications and RESPONSE messages — e.g.
                                # elicitation replies — legitimately get None)
                                forwarded = False
                                break
                            if reply is not None:
                                replies.append(reply)
                        if forwarded:
                            if not replies:
                                return web.Response(status=202)
                            return web.json_response(
                                replies if isinstance(payload, list) else replies[0],
                                headers={"mcp-session-id": session_id})
                    return web.json_response(
                        error_response(None, INVALID_REQUEST, "Unknown session"), status=404)
            elif is_initialize:
                session = self.sessions.create()
                if self.affinity is not None:
                    await self.affinity.claim_session(session.id)
            else:
                return web.json_response(
                    error_response(None, INVALID_REQUEST, "Missing Mcp-Session-Id"),
                    status=400)

        headers = {k.lower(): v for k, v in request.headers.items()}
        if session is not None:
            headers["mcp-session-id"] = session.id

        responses: list[dict[str, Any]] = []
        for message in messages:
            # client→server RESPONSE messages (no method): elicitation replies
            from ...jsonrpc import is_response_message
            if is_response_message(message):
                elicitation = getattr(self, "elicitation", None)
                if elicitation is not None:
                    elicitation.resolve(message,
                                        session_id=headers.get("mcp-session-id"))
                continue
            try:
                rpc_request = RPCRequest.parse(message)
            except JSONRPCError as exc:
                responses.append(exc.to_dict(message.get("id") if isinstance(message, dict)
                                             else None))
                continue
            try:
                response = await self.dispatcher.dispatch(rpc_request, auth,
                                                          headers=headers,
                                                          server_id=server_id)
            except JSONRPCError as exc:
                response = exc.to_dict(rpc_request.id)
            if response is not None:
                responses.append(response)
            if session is not None and rpc_request.method == "initialize":
                session.initialized = True

        response_headers = {"mcp-protocol-version": self.settings.protocol_version}
        if session is not None:
            response_headers["mcp-session-id"] = session.id
        if not responses:  # notifications only
            return web.Response(status=202, headers=response_headers)

        accept = request.headers.get("accept", "application/json")
        body = responses if isinstance(payload, list) else responses[0]
        if "text/event-stream" in accept and "application/json" not in accept.split(",")[0]:
            # client prefers a stream: emit response(s) as SSE then close
            resp = web.StreamResponse(headers={
                **response_headers, "content-type": "text/event-stream",
                "cache-control": "no-store"})
            await resp.prepare(request)
            for item in responses:
                await resp.write(_sse_frame(None, item))
            await resp.write_eof()
            return resp
        return web.json_response(body, headers=response_headers)

    # ------------------------------------------------------------------- GET

    async def handle_get(self, request: web.Request) -> web.StreamResponse:
        """Server→client SSE stream (stateful mode) with resume. A
        session owned by ANOTHER worker is relayed from its owner over
        the bus RPC seam (docs/scaleout.md) — byte-identical frames,
        instead of the pre-scale-out 404/409 refusal."""
        if not self.settings.streamable_http_stateful:
            return web.json_response({"detail": "Stateless mode: no server stream"},
                                     status=405)
        session_id = request.headers.get("mcp-session-id")
        session = self.sessions.get(session_id) if session_id else None
        if session is None and session_id and self.affinity is not None \
                and self.affinity.rpc is not None \
                and self.settings.gw_session_handoff:
            owner = await self.affinity.remote_owner(session_id)
            if owner is not None:
                return await self._relay_stream(request, session_id, owner)
        if session is None:
            return web.json_response({"detail": "Unknown or missing session"}, status=404)
        resp = web.StreamResponse(headers={
            "content-type": "text/event-stream", "cache-control": "no-store",
            "mcp-session-id": session.id})
        await resp.prepare(request)
        last_event_id = request.headers.get("last-event-id")
        if last_event_id:
            for entry in self.sessions.events.replay_after(session.id, last_event_id):
                await resp.write(_sse_frame(entry.event_id, entry.message))
        keepalive = self.settings.sse_keepalive_interval
        try:
            while True:
                try:
                    event_id, message = await asyncio.wait_for(session.queue.get(),
                                                               timeout=keepalive)
                    await resp.write(_sse_frame(event_id, message))
                except asyncio.TimeoutError:
                    await resp.write(b": keepalive\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        return resp

    async def _relay_stream(self, request: web.Request, session_id: str,
                            owner: str) -> web.StreamResponse:
        """Serve another worker's session stream: the owner's relay
        handler consumes the session queue and pushes (event_id,
        message) chunks over the RPC stream; frames here are rendered
        with the SAME ``_sse_frame`` the owner would use, so the bytes
        on the wire are identical whichever worker the client hit. A
        keepalive chunk maps to the same ``: keepalive`` comment. The
        owner dying mid-relay terminates the stream CLEANLY with the
        loss counted (``mcpforge_gw_session_handoffs_total{stream_lost}``)
        — never a hang."""
        from ...coordination.rpc import RpcAppError, RpcPeerLost
        metrics = getattr(self.sessions, "metrics", None)

        def _count(kind: str) -> None:
            if metrics is not None:
                try:
                    metrics.gw_session_handoffs.labels(kind=kind).inc()
                except Exception:
                    pass

        resp = web.StreamResponse(headers={
            "content-type": "text/event-stream", "cache-control": "no-store",
            "mcp-session-id": session_id})
        await resp.prepare(request)
        _count("stream")
        chunks = self.affinity.rpc.call_stream(
            owner, "session.stream",
            {"session_id": session_id,
             "last_event_id": request.headers.get("last-event-id")},
            idle_timeout_s=max(self.settings.sse_keepalive_interval * 2,
                               self.settings.gw_stream_idle_timeout_s))
        try:
            async for chunk in chunks:
                if chunk.get("keepalive"):
                    await resp.write(b": keepalive\n\n")
                    continue
                await resp.write(_sse_frame(chunk.get("event_id"),
                                            chunk.get("message")))
        except RpcPeerLost:
            # owning worker died: the client gets a clean EOF (it can
            # reconnect with Last-Event-ID once a new worker claims the
            # session) and the loss is COUNTED
            _count("stream_lost")
        except RpcAppError:
            # owner answered but refused (session expired there between
            # the lease check and the attach): clean EOF, client re-inits
            _count("refused")
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            try:
                await chunks.aclose()
            except Exception:
                pass
        try:
            await resp.write_eof()
        except ConnectionResetError:
            pass
        return resp

    # ---------------------------------------------------------------- DELETE

    async def handle_delete(self, request: web.Request) -> web.StreamResponse:
        session_id = request.headers.get("mcp-session-id")
        if session_id:
            self.sessions.drop(session_id)
        return web.Response(status=204)
