"""Inbound transports: streamable-HTTP (primary), SSE (legacy), WebSocket."""
