"""Routers: llmchat (ReAct sessions with SSE streaming), teams, catalog,
metric rollups. Reference: routers/llmchat_router.py, routers/teams.py,
routers/catalog.py, routers/metrics_maintenance.py."""

from __future__ import annotations

from aiohttp import web

from ..observability import phases
from ..services.base import ValidationFailure
from .serialize import SSE_DONE, sse_event


def setup_chat_routes(app: web.Application) -> None:
    routes = web.RouteTableDef()

    # --------------------------------------------------------------- llmchat
    @routes.post("/llmchat/connect")
    async def connect(request: web.Request) -> web.Response:
        request["auth"].require("llm.chat")
        try:
            body = await request.json()
        except Exception:
            body = {}
        session = await request.app["chat_service"].connect(
            user=request["auth"].user, model=body.get("model"),
            server_id=body.get("server_id"),
            max_steps=(int(body["max_steps"])
                       if body.get("max_steps") is not None else None))
        return web.json_response({"session_id": session.id}, status=201)

    @routes.post("/llmchat/{session_id}/chat")
    async def chat(request: web.Request) -> web.StreamResponse:
        request["auth"].require("llm.chat")
        body = await request.json()
        text = body.get("message", "")
        stream = bool(body.get("stream", True))
        service = request.app["chat_service"]
        # validate BEFORE the SSE response starts — an async generator only
        # raises at first iteration, which would be after the 200 headers
        await service.get_session(request.match_info["session_id"],
                                  request["auth"].user)
        if request.app["ctx"].llm_registry is None:
            return web.json_response({"detail": "tpu_local engine disabled"},
                                     status=422)
        events = service.chat(request.match_info["session_id"],
                              request["auth"].user, text,
                              auth_teams=request["auth"].teams)
        if not stream:
            collected = [event async for event in events]
            return web.json_response({"events": collected})
        resp = web.StreamResponse(headers={"content-type": "text/event-stream",
                                           "cache-control": "no-store"})
        await resp.prepare(request)
        # shared zero-copy SSE path (gateway/serialize.py): one compact
        # encoder + pre-built framing instead of per-event dumps+concat
        async for event in events:
            with phases.phase("serialize"):
                await resp.write(sse_event(event))
        await resp.write(SSE_DONE)
        await resp.write_eof()
        return resp

    @routes.delete("/llmchat/{session_id}")
    async def disconnect(request: web.Request) -> web.Response:
        await request.app["chat_service"].disconnect(
            request.match_info["session_id"], request["auth"].user)
        return web.Response(status=204)

    # ----------------------------------------------------------------- teams
    @routes.get("/teams")
    async def list_teams(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("teams.read")
        user = None if auth.is_admin and request.query.get("all") == "true" \
            else auth.user
        return web.json_response(await request.app["team_service"].list_teams(user))

    @routes.post("/teams")
    async def create_team(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("teams.create")  # write permission: read-scoped tokens may not
        body = await request.json()
        team = await request.app["team_service"].create_team(
            name=body.get("name", ""), created_by=auth.user,
            description=body.get("description", ""),
            visibility=body.get("visibility", "private"),
            is_admin=auth.is_admin)
        return web.json_response(team, status=201)

    @routes.get("/teams/{team_id}")
    async def get_team(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("teams.read")
        return web.json_response(
            await request.app["team_service"].get_team(
                request.match_info["team_id"], actor=auth.user,
                is_admin=auth.is_admin))

    @routes.delete("/teams/{team_id}")
    async def delete_team(request: web.Request) -> web.Response:
        auth = request["auth"]
        await request.app["team_service"].delete_team(
            request.match_info["team_id"], auth.user, auth.is_admin)
        return web.Response(status=204)

    @routes.post("/teams/{team_id}/members")
    async def add_member(request: web.Request) -> web.Response:
        auth = request["auth"]
        body = await request.json()
        await request.app["team_service"].add_member(
            request.match_info["team_id"], auth.user, body.get("email", ""),
            role=body.get("role") or None, is_admin=auth.is_admin)
        return web.Response(status=204)

    @routes.delete("/teams/{team_id}/members/{email}")
    async def remove_member(request: web.Request) -> web.Response:
        auth = request["auth"]
        await request.app["team_service"].remove_member(
            request.match_info["team_id"], auth.user,
            request.match_info["email"], is_admin=auth.is_admin)
        return web.Response(status=204)

    @routes.post("/teams/{team_id}/invitations")
    async def invite(request: web.Request) -> web.Response:
        auth = request["auth"]
        body = await request.json()
        invitation = await request.app["team_service"].invite(
            request.match_info["team_id"], auth.user, body.get("email", ""),
            role=body.get("role") or None, is_admin=auth.is_admin)
        return web.json_response(invitation, status=201)

    @routes.post("/teams/invitations/accept")
    async def accept(request: web.Request) -> web.Response:
        body = await request.json()
        team = await request.app["team_service"].accept_invitation(
            body.get("token", ""), request["auth"].user)
        return web.json_response(team)

    # --------------------------------------------------------------- catalog
    @routes.get("/catalog")
    async def catalog(request: web.Request) -> web.Response:
        request["auth"].require("gateways.read")
        return web.json_response(await request.app["catalog_service"].list_entries())

    @routes.post("/catalog/{entry_id}/register")
    async def register_catalog(request: web.Request) -> web.Response:
        request["auth"].require("gateways.create")
        gateway = await request.app["catalog_service"].register_entry(
            request.match_info["entry_id"], request.app["gateway_service"])
        from .routers import _dump
        return web.json_response(_dump(gateway), status=201)

    # --------------------------------------------------------------- rollups
    @routes.get("/metrics/rollups")
    async def rollups(request: web.Request) -> web.Response:
        request["auth"].require("observability.read")
        service = request.app["metrics_maintenance"]
        return web.json_response(await service.hourly_summary(
            entity_id=request.query.get("entity_id"),
            hours=int(request.query.get("hours", "24"))))

    @routes.get("/metrics/timeseries")
    async def metrics_timeseries(request: web.Request) -> web.Response:
        """Hourly calls/errors/avg series: persisted rollups + the
        un-rolled raw tail (reference metrics_query_service.py)."""
        request["auth"].require("observability.read")
        service = request.app["metrics_maintenance"]
        try:
            hours = float(request.query.get("hours", "24"))
            if not (0 < hours <= 24 * 366):  # also rejects nan/inf
                raise ValueError(hours)
        except ValueError as exc:
            raise ValidationFailure(
                "hours must be a number in (0, 8784]") from exc
        return web.json_response(await service.timeseries(
            hours=hours,
            entity_type=request.query.get("entity_type")))

    @routes.post("/metrics/rollup")
    async def run_rollup(request: web.Request) -> web.Response:
        request["auth"].require("observability.read")
        service = request.app["metrics_maintenance"]
        count = await service.rollup()
        return web.json_response({"rolled_up": count})

    app.add_routes(routes)
