"""Swappable /mcp ingress mount + cluster-wide runtime mode.

Reference: ADR 051 (`transports/mcp_ingress_mount.py` — a registry of named
ASGI apps + a selector for the public /mcp) and `runtime_state.py` (ingress
mode switched at runtime, Redis-propagated, versioned). Here:

- ``IngressMount`` — named handler sets ("python" = in-tree streamable-HTTP
  transport; "drain" = 503 + Retry-After for rolling maintenance); the
  active name is runtime-mutable.
- Mode changes publish on the ``ingress.mode`` bus topic with a version
  counter, so every worker (memory/file/TCP-hub bus alike) converges on the
  same mode without restart — the reference's cluster-wide override.
- The C++ edge tier (native/mcp_edge.cpp) sits IN FRONT of whichever
  ingress is selected; "drain" therefore drains edge traffic too.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Awaitable, Callable

from aiohttp import web

logger = logging.getLogger(__name__)

Handler = Callable[[web.Request], Awaitable[web.StreamResponse]]


class IngressMount:
    def __init__(self, ctx) -> None:
        self.ctx = ctx
        self._ingresses: dict[str, dict[str, Handler]] = {}
        self.mode = "python"
        self.version = 0
        self.changed_at: float | None = None
        self._register_drain()

    # ------------------------------------------------------------- registry

    def register(self, name: str, handlers: dict[str, Handler]) -> None:
        """handlers: {"post": ..., "get": ..., "delete": ...}."""
        self._ingresses[name] = handlers

    def names(self) -> list[str]:
        return sorted(self._ingresses)

    def _register_drain(self) -> None:
        async def drain(request: web.Request) -> web.StreamResponse:
            return web.json_response(
                {"detail": "MCP ingress is draining for maintenance"},
                status=503, headers={"retry-after": "10"})

        self.register("drain", {"post": drain, "get": drain, "delete": drain})

    # ----------------------------------------------------------------- mode

    _DB_KEY = "ingress_mode"

    async def load(self) -> None:
        """Adopt the cluster's persisted mode at boot — a restarted worker
        must not silently un-drain, and its version counter must continue
        from the cluster's (the reference's Redis-backed runtime_state;
        here the shared DB is the source of truth, the bus the push path)."""
        import json

        row = await self.ctx.db.fetchone(
            "SELECT value FROM global_config WHERE key=?", (self._DB_KEY,))
        if not row or not row["value"]:
            return
        try:
            state = json.loads(row["value"])
        except json.JSONDecodeError:
            return
        mode = state.get("mode")
        # the version counter is adopted UNCONDITIONALLY: a worker that
        # doesn't register the persisted mode name must still continue the
        # cluster's counter or its own switches get dropped as stale
        self.version = int(state.get("version") or 0)
        self.changed_at = state.get("changed_at")
        if mode in self._ingresses:
            self.mode = mode

    async def set_mode(self, mode: str, publish: bool = True) -> None:
        import json

        if mode not in self._ingresses:
            raise ValueError(f"unknown ingress {mode!r}; have {self.names()}")
        changed_at = time.time()
        # version allocation is an atomic counter in the SHARED DB: two
        # concurrent switches on different workers get distinct versions, so
        # every peer converges on the higher one (no split brain); and we
        # persist BEFORE touching local state — a failed write must not
        # leave this worker switched alone with the admin seeing a 500
        await self.ctx.db.execute(
            "INSERT INTO global_config (key, value, updated_at)"
            " VALUES (?, '1', ?) ON CONFLICT(key) DO UPDATE SET"
            " value=CAST(CAST(value AS INTEGER)+1 AS TEXT),"
            " updated_at=excluded.updated_at",
            (self._DB_KEY + ":version", changed_at))
        # re-read instead of RETURNING (sqlite >= 3.35 only): a concurrent
        # switch may have advanced the counter further, which is fine —
        # peers converge on the higher version by design
        row = await self.ctx.db.fetchone(
            "SELECT value FROM global_config WHERE key=?",
            (self._DB_KEY + ":version",))
        version = int(row["value"]) if row else self.version + 1
        await self.ctx.db.execute(
            "INSERT INTO global_config (key, value, updated_at) VALUES (?,?,?)"
            " ON CONFLICT(key) DO UPDATE SET value=excluded.value,"
            " updated_at=excluded.updated_at",
            (self._DB_KEY, json.dumps({"mode": mode, "version": version,
                                       "changed_at": changed_at}),
             changed_at))
        self.mode = mode
        self.version = version
        self.changed_at = changed_at
        logger.info("mcp ingress mode -> %s (v%d)", mode, version)
        if publish:
            await self.ctx.bus.publish("ingress.mode",
                                       {"mode": mode, "version": version})

    def subscribe(self) -> None:
        async def _on_mode(topic: str, message: dict[str, Any]) -> None:
            mode = message.get("mode")
            version = int(message.get("version") or 0)
            # versioned: a late-delivered older change must not undo a newer
            # local one (reference runtime_state version counter)
            if mode not in self._ingresses or version < self.version:
                return
            # adopt the version even when the mode already matches — a
            # lagging counter would make this worker's future switches be
            # rejected as stale by every peer
            self.version = version
            if mode != self.mode:
                self.mode = mode
                self.changed_at = time.time()
                logger.info("mcp ingress mode <- bus: %s (v%d)", mode, version)

        self.ctx.bus.subscribe("ingress.mode", _on_mode)

    # ------------------------------------------------------------- dispatch

    def handler(self, kind: str) -> Handler:
        async def dispatch(request: web.Request) -> web.StreamResponse:
            handlers = self._ingresses.get(self.mode) \
                or self._ingresses["python"]
            handler = handlers.get(kind)
            if handler is None:
                raise web.HTTPMethodNotAllowed(kind.upper(), ["POST", "GET"])
            return await handler(request)

        return dispatch
