"""TTL cache for entity list endpoints.

Reference: the ``registry_cache_*`` settings family
(`/root/reference/mcpgateway/config.py` — registry_cache_enabled +
per-entity TTLs for tools/resources/prompts/servers/gateways).

Design: the cache subscribes to the SAME ``<entity>.changed`` bus topics
that drive cross-worker sync and listChanged notifications, so a write
on any worker flushes every worker's cache immediately — the TTL only
bounds staleness for changes the bus cannot see (direct DB edits).
Values are the service-layer lists, keyed by the query flags (and, for
the team-scoped tool list, the viewer's team set) that change the
result.

A per-entity generation counter closes the miss-load-put race: a load
that started before an invalidation must not re-cache its pre-write
snapshot after the event fired, so ``put`` drops the value unless the
generation captured at miss time is still current.
"""

from __future__ import annotations

import time
from typing import Any

ENTITIES = ("tools", "resources", "prompts", "servers", "gateways")


class RegistryCache:
    def __init__(self, ctx) -> None:
        self._ctx = ctx
        self._store: dict[tuple[str, str], tuple[Any, float]] = {}
        self._gen: dict[str, int] = {e: 0 for e in ENTITIES}
        self.hits = 0
        self.misses = 0

    def wire(self) -> None:
        """Subscribe invalidation to the per-entity change topics."""
        for entity in ENTITIES:
            async def _handler(_topic, _msg, entity=entity):
                self.invalidate(entity)
            self._ctx.bus.subscribe(f"{entity}.changed", _handler)

    def _ttl(self, entity: str) -> float:
        settings = self._ctx.settings
        return getattr(settings, f"registry_cache_{entity}_ttl_s",
                       settings.registry_cache_default_ttl_s)

    def generation(self, entity: str) -> int:
        return self._gen.get(entity, 0)

    def get(self, entity: str, key: str) -> Any | None:
        hit = self._store.get((entity, key))
        if hit is not None and hit[1] <= time.monotonic():
            # evict on expiry: team-scoped keys churn, and dead entries
            # would otherwise accumulate until the next change event
            del self._store[(entity, key)]
            hit = None
        if hit is None:
            self.misses += 1
            return None
        self.hits += 1
        return hit[0]

    def put(self, entity: str, key: str, value: Any,
            generation: int | None = None) -> None:
        if generation is not None and generation != self._gen.get(entity, 0):
            return  # invalidated while the loader ran: stale snapshot
        ttl = self._ttl(entity)
        if ttl > 0:
            self._store[(entity, key)] = (value, time.monotonic() + ttl)

    def invalidate(self, entity: str | None = None) -> None:
        for name in ([entity] if entity else list(ENTITIES)):
            self._gen[name] = self._gen.get(name, 0) + 1
        if entity is None:
            self._store.clear()
            return
        for k in [k for k in self._store if k[0] == entity]:
            del self._store[k]
