"""Server-rendered admin UI.

Reference: 20.5k-LoC admin.py + 34.8k-LoC JS admin_ui — intentionally
table-driven here (SURVEY.md §7.2 #5: the API surface must be generated,
not hand-grown). One page, vanilla JS over the existing REST API:

- entity tabs with client-side search + auto-refresh + cursor paging
- full CRUD where the API has it: create forms (per-entity field specs,
  typed fields ``name:int`` / ``name:csv`` / ``name:json``), JSON edit
  (PUT), delete, enable/disable toggles
- per-entity DETAIL views (key-value pane + related records: team
  members with add/remove/invite, token mint-once reveal, plugin mode
  dropdowns posting /plugins/{name}/mode)
- metrics dashboard: totals cards + hourly rollup bar chart (pure divs)
- export/import pane: download the config bundle, paste-to-import with
  overwrite toggle
- trace drill-down: span tree AND a gantt view; engine stat cards

The UI contract test (`tests/integration/test_admin_ui_contract.py` +
`test_admin_ui_coverage.py`) asserts every admin REST endpoint is
reachable from this page — the JS-free browser tier (no node/playwright
in the image; the reference uses `tests/playwright/`).
"""

from __future__ import annotations

from aiohttp import web

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>mcpforge admin</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f5f7;color:#1a1d21}
 header{background:#1a1d21;color:#fff;padding:10px 20px;display:flex;gap:16px;align-items:center;flex-wrap:wrap}
 header h1{font-size:16px;margin:0}
 nav button{background:none;border:none;color:#aab;cursor:pointer;font-size:14px;padding:6px 10px}
 nav button.active{color:#fff;border-bottom:2px solid #6cf}
 main{padding:20px;max-width:1200px;margin:0 auto}
 table{width:100%;border-collapse:collapse;background:#fff;box-shadow:0 1px 3px rgba(0,0,0,.08)}
 th,td{text-align:left;padding:8px 12px;border-bottom:1px solid #eceef1;font-size:13px}
 th{background:#fafbfc;font-weight:600}
 .pill{display:inline-block;padding:1px 8px;border-radius:10px;font-size:11px}
 .ok{background:#d9f2e4;color:#11734b}.bad{background:#fde2e1;color:#a12622}
 #bar{margin:10px 0;display:flex;gap:10px;align-items:center;flex-wrap:wrap}
 #status{color:#667}
 #q{padding:6px 10px;border:1px solid #ccd;border-radius:4px;min-width:220px}
 button.act{background:#eef;border:1px solid #ccd;border-radius:4px;cursor:pointer;padding:2px 8px;font-size:12px}
 button.danger{background:#fde2e1;border-color:#eab}
 a.trace{color:#26c;cursor:pointer;text-decoration:underline}
 #detail{background:#fff;margin-top:14px;padding:12px;box-shadow:0 1px 3px rgba(0,0,0,.08);display:none}
 .span-row{font-family:ui-monospace,monospace;font-size:12px;white-space:pre}
 .err{color:#a12622}
 #form{background:#fff;margin:10px 0;padding:12px;box-shadow:0 1px 3px rgba(0,0,0,.08);display:none}
 #form input,#detail input,#detail select{margin:3px 6px 3px 0;padding:5px 8px;border:1px solid #ccd;border-radius:4px}
 #edit-area,#import-area{width:100%;min-height:140px;font-family:ui-monospace,monospace;font-size:12px}
 .gantt{position:relative;height:18px;margin:1px 0;background:#fafbfc}
 .gantt .bar{position:absolute;top:2px;height:14px;background:#9cf;border-radius:2px;min-width:2px}
 .gantt .bar.err{background:#f99}
 .gantt .lbl{position:absolute;left:4px;top:1px;font-size:11px;font-family:ui-monospace,monospace;white-space:nowrap;z-index:1}
 .cards{display:flex;gap:12px;flex-wrap:wrap}
 .card{background:#fff;box-shadow:0 1px 3px rgba(0,0,0,.08);padding:12px 18px;min-width:130px}
 .card b{display:block;font-size:22px}.card span{color:#667;font-size:12px}
 .kv{font-family:ui-monospace,monospace;font-size:12px}
 .kv td{padding:3px 10px}
 .chart{display:flex;align-items:flex-end;gap:2px;height:120px;background:#fff;padding:10px;box-shadow:0 1px 3px rgba(0,0,0,.08);margin-top:10px}
 .chart .col{flex:1;display:flex;flex-direction:column;justify-content:flex-end;height:100%}
 .chart .v{background:#9cf;min-height:1px}
 .chart .e{background:#f99}
 .chart .t{font-size:9px;color:#889;text-align:center;overflow:hidden}
 select.mode{font-size:12px;padding:2px}
 .reveal{background:#fffbe6;border:1px solid #eda;padding:8px;margin:8px 0;font-family:ui-monospace,monospace;font-size:12px;word-break:break-all}
</style></head><body>
<header><h1>mcpforge</h1><nav id="nav"></nav></header>
<main>
 <div id="bar">
  <input id="q" placeholder="filter rows…" oninput="render()">
  <button class="act" onclick="show(current)">refresh</button>
  <button class="act" id="newbtn" onclick="openForm()" style="display:none">+ new</button>
  <button class="act" id="morebtn" onclick="nextPage()" style="display:none">next page ▸</button>
  <label style="font-size:12px;color:#667"><input type="checkbox" id="auto"
   onchange="autoRefresh()"> auto (5s)</label>
  <span id="status"></span>
 </div>
 <div id="form"></div>
 <div id="view"></div>
 <div id="detail"></div>
</main>
<script src="/admin/app.js"></script>
</body></html>"""

# The page's JavaScript, served as its own asset (/admin/app.js) so
# it is a TESTABLE MODULE: tests/integration/test_admin_js_render.py
# extracts and EXECUTES its pure render functions (no JS runtime in
# the CI image; a mechanical subset translator runs them in-process).
_JS = r"""// double-submit CSRF: echo the csrf_token cookie on every fetch — a
// cross-site page can make the browser SEND the cookie but cannot READ
// it, so the echo proves this same-origin script issued the request
const _fetch = window.fetch.bind(window);
window.fetch = (url, opts) => {
  opts = opts || {};
  const m = document.cookie.match(/(?:^|; )csrf_token=([^;]*)/);
  opts.headers = Object.assign({}, opts.headers,
                               m ? {"X-CSRF-Token": m[1]} : {});
  return _fetch(url, opts);
};
const TABS = {
  tools:    {paged:true, url: "/tools?include_inactive=true", cols: ["name","integration_type","url","enabled","reachable"], toggle: id => `/tools/${id}/toggle`, boolcols: ["enabled","reachable"],
             create: {url:"/tools", fields:["name","integration_type","url","description","tags:csv"]},
             edit: id => `/tools/${id}`, del: id => `/tools/${id}`,
             detail: id => `/tools/${id}`,
             rowacts: [{label:"gen cases", method:"GET", key:"name", show:true, url: n => `/toolops/${encodeURIComponent(n)}/cases`},
                       {label:"run cases", method:"POST", key:"name", show:true, url: n => `/toolops/${encodeURIComponent(n)}/run`}]},
  gateways: {paged:true, url: "/gateways?include_inactive=true", cols: ["name","url","transport","state","reachable"], boolcols: ["reachable"],
             create: {url:"/gateways", fields:["name","url","transport"],
                      testurl: "/gateways/test"},
             edit: id => `/gateways/${id}`, del: id => `/gateways/${id}`,
             detail: id => `/gateways/${id}`,
             rowacts: [{label:"resync", method:"POST", url: id => `/gateways/${id}/refresh`}]},
  servers:  {paged:true, url: "/servers?include_inactive=true", cols: ["name","description","associated_tools","enabled"], boolcols: ["enabled"],
             create: {url:"/servers", fields:["name","description","associated_tools:csv"]},
             edit: id => `/servers/${id}`, del: id => `/servers/${id}`,
             detail: id => `/servers/${id}`},
  resources:{paged:true, url: "/resources?include_inactive=true", cols: ["uri","name","mime_type","enabled"], boolcols: ["enabled"],
             create: {url:"/resources", fields:["uri","name","content","mime_type"]},
             edit: id => `/resources/${id}`, del: id => `/resources/${id}`},
  prompts:  {paged:true, url: "/prompts?include_inactive=true", cols: ["name","description","enabled"], boolcols: ["enabled"],
             create: {url:"/prompts", fields:["name","template","description"]},
             edit: id => `/prompts/${id}`, del: id => `/prompts/${id}`},
  agents:   {paged:true, url: "/a2a?include_inactive=true", cols: ["name","agent_type","endpoint_url","enabled","reachable"], boolcols: ["enabled","reachable"],
             create: {url:"/a2a", fields:["name","agent_type","endpoint_url"]},
             del: id => `/a2a/${id}`},
  plugins:  {url: "/plugins", cols: ["name","kind","mode","priority"], special: "plugins"},
  bindings: {url: "/plugins/bindings", cols: ["plugin_name","scope_type","scope_id","mode","enabled"], boolcols: ["enabled"],
             create: {url:"/plugins/bindings", fields:["plugin_name","scope_type","scope_id","mode","config:json"]},
             del: id => `/plugins/bindings/${id}`},
  users:    {paged:true, url: "/admin/users", cols: ["email","full_name","is_admin","is_active","auth_provider","last_login"], toggle: id => `/admin/users/${encodeURIComponent(id)}/toggle`, idcol: "email", boolcols: ["is_admin","is_active"],
             create: {url:"/admin/users", fields:["email","password","full_name"]},
             rowacts: [{label:"require pw change", method:"POST", key:"email", show:true, url: e => `/admin/users/${encodeURIComponent(e)}/require-password-change`}]},
  teams:    {url: "/teams", cols: ["name","slug","visibility","is_personal","created_by"], boolcols: ["is_personal"],
             create: {url:"/teams", fields:["name","visibility"]},
             del: id => `/teams/${id}`, detail: id => `/teams/${id}`, special: "teams"},
  config:   {url: "/admin/config", cols: ["name","value"]},
  compliance: {url: "/compliance/reports", cols: ["framework","generated_at","generated_by","summary"],
             create: {url:"/compliance/reports", fields:["framework","period_days:int"]},
             detail: id => `/compliance/reports/${id}`,
             rowacts: [{label:"export md", method:"GET", show:true, url: id => `/compliance/reports/${id}/export?format=markdown`},
                       {label:"frameworks", method:"GET", show:true, url: () => `/compliance/frameworks`}]},
  roles:    {paged:true, url: "/rbac/roles", cols: ["name","scope","description","is_system","assignment_count"], boolcols: ["is_system"],
             create: {url:"/rbac/roles", fields:["name","description","scope","permissions:csv"]},
             del: id => `/rbac/roles/${id}`, detail: id => `/rbac/roles/${id}`, special: "roles"},
  tokens:   {url: "/auth/tokens", cols: ["name","server_id","expires_at","last_used","revoked_at"],
             create: {url:"/auth/tokens", fields:["name","expires_minutes:int","permissions:csv","server_id"], reveal: "token"},
             del: id => `/auth/tokens/${id}`,
             rowacts: [{label:"usage", method:"GET", show:true, url: id => `/auth/tokens/${id}/usage`}]},
  providers:{url: "/llm/providers", cols: ["name","provider_type","api_base","enabled"], boolcols: ["enabled"],
             create: {url:"/llm/providers", fields:["name","provider_type","api_base","api_key"]},
             del: id => `/llm/providers/${id}`},
  models:   {url: "/v1/models", cols: ["id","owned_by"], path: "data"},
  llmmodels:{url: "/llm/models", cols: ["model_alias","provider_id","enabled"], boolcols: ["enabled"]},
  ingress:  {url: "/admin/ingress", special: "ingress"},
  dashboard:{special: "dashboard"},
  metrics:  {url: "/metrics", cols: ["name","calls","errors","avg_ms","min_ms","max_ms"], path: "tools"},
  rollups:  {url: "/metrics/rollups", cols: ["entity_type","entity_id","hour","calls","errors","avg_ms"]},
  traces:   {url: "/admin/traces?limit=100", cols: ["name","duration_ms","status","trace_id"], tracecol: "trace_id"},
  logs:     {url: "/admin/logs?limit=200", cols: ["ts","level","logger","message"]},
  audit:    {url: "/admin/audit?limit=100", cols: ["ts","actor","action","details"]},
  exportimport: {special: "exportimport"},
  chat:     {special: "chat"},
  engine:   {url: "/admin/engine/stats", special: "engine"},
  gateway:  {url: "/admin/gateway/requests?limit=24", special: "gwflight"},
  forensics:{url: "/admin/trace?limit=50", special: "forensics"},
  controller:{url: "/admin/controller?limit=32", special: "controller"},
  tenants:  {url: "/admin/tenants/usage?limit=32", special: "tenants"},
  diagnostics: {special: "diagnostics"},
};
let current = "tools", rows = [], shown = [], timer = null, cursor = null;
function esc(s){
  return String(s).replace(/[&<>"']/g, c => ({"&":"&amp;","<":"&lt;",">":"&gt;",
    '"':"&quot;","'":"&#39;"}[c]));
}
function cell(v, isBool){
  // booleanness is a per-COLUMN decision (sqlite int-bools), never by value
  if (isBool) return (v === true || v === 1)
    ? '<span class="pill ok">yes</span>' : '<span class="pill bad">no</span>';
  if (v === true) return '<span class="pill ok">yes</span>';
  if (v === false) return '<span class="pill bad">no</span>';
  if (Array.isArray(v)) return v.length;
  if (v === null || v === undefined) return "";
  if (typeof v === "number") return Math.round(v*100)/100;
  if (typeof v === "object") return esc(JSON.stringify(v).slice(0,80));
  return esc(String(v).slice(0,100));  // API data is attacker-influenced
}
function fnum(v){
  // roofline fractions live at 1e-2..1e-8 (MFU 0.00018 is the headline
  // production number) — cell()'s 2-decimal rounding would zero them
  if (v === null || v === undefined || typeof v !== "number") return cell(v);
  if (v !== 0 && Math.abs(v) < 0.01) return v.toExponential(2);
  return Math.round(v*10000)/10000;
}
async function renderEngine(stats){
  const order = ["requests","prompt_tokens","completion_tokens","decode_steps",
                 "decode_dispatches",
                 "prefill_batches","queue_depth","chunking","kv_pages_in_use",
                 "kv_bytes_in_use","kv_quant",
                 "prefix_hits","prefix_hit_tokens","tier_hits_host",
                 "tier_hits_disk","tier_hits_object",
                 "tier_hit_tokens_spilled",
                 "spec_steps","spec_tokens",
                 "overlap_steps","pipeline_drains","dispatch_gap_ms_total",
                 "prefill_ms_total","decode_ms_total","engine_restarts"];
  const cards = order.filter(k => k in stats).map(k =>
    `<div class="card"><b>${cell(stats[k])}</b><span>${k}</span></div>`).join("");
  const rest = Object.keys(stats).filter(k => !order.includes(k));
  const extra = rest.map(k =>
    `<div class="card"><b>${cell(stats[k])}</b><span>${k}</span></div>`).join("");
  // replica pool card (multi-replica serving tier; 404 when replicas=1)
  let pool = "";
  try {
    const pr = await fetch("/admin/engine/pool");
    if (pr.ok){
      const p = await pr.json();
      const pcols = ["id","state","role","occupancy","outstanding",
                     "outstanding_tokens","kv_pages_in_use","routed",
                     "requeued_off","migrations_out","migrations_in",
                     "reloads","failures","heartbeat_age_s"];
      const pbody = (p.replicas || []).map(rp =>
        "<tr>" + pcols.map(c => `<td>${cell(rp[c])}</td>`).join("")
        + `<td><button class="act" onclick="poolAct('${esc(rp.id)}','drain')">drain</button>
           <button class="act" onclick="poolAct('${esc(rp.id)}','undrain')">undrain</button>
           <button class="act" onclick="poolAct('${esc(rp.id)}','reload')">reload</button></td></tr>`
      ).join("");
      const mig = p.migrations || {};
      pool = `<br><h3>engine replica pool</h3>
        <div class="cards">
          <div class="card"><b>${cell((p.router||{}).routed)}</b><span>routed</span></div>
          <div class="card"><b>${cell((p.router||{}).affinity_hits)}</b><span>affinity_hits</span></div>
          <div class="card"><b>${cell((p.router||{}).role_routed)}</b><span>role_routed</span></div>
          <div class="card"><b>${cell((p.router||{}).role_spills)}</b><span>role_spills</span></div>
          <div class="card"><b>${cell(mig.ok)}</b><span>migrations_ok</span></div>
          <div class="card"><b>${cell(mig.degraded)}</b><span>migrations_degraded</span></div>
          <div class="card"><b>${cell(p.requeues)}</b><span>requeues</span></div>
          <div class="card"><b>${cell((p.health||{}).failures)}</b><span>replica_failures</span></div>
        </div>
        <table><tr>` + pcols.map(c => `<th>${esc(c)}</th>`).join("")
        + `<th>actions</th></tr>${pbody}</table>`;
    }
  } catch(e){}
  // prefix-cache fabric card (docs/cache_fabric.md; 404 when the T3
  // object tier is off — fabric stats only exist behind an object store)
  let fabric = "";
  try {
    const fr = await fetch("/admin/fabric/adverts");
    if (fr.ok){
      const f = await fr.json();
      const st = f.store || {};
      const fx = st.fabric || {};
      fabric = `<br><h3>prefix-cache fabric ${
          (st.object_breaker || {}).state === "open"
            ? '<span class="pill bad">tier.object open</span>'
            : '<span class="pill ok">serving</span>'}</h3>
        <div class="cards">
          <div class="card"><b>${cell(st.object_pages)}</b><span>object_pages</span></div>
          <div class="card"><b>${cell(st.object_bytes)}</b><span>object_bytes</span></div>
          <div class="card"><b>${cell(st.object_reads)}</b><span>object_reads</span></div>
          <div class="card"><b>${cell(st.object_writes)}</b><span>object_writes</span></div>
          <div class="card"><b>${cell(st.object_write_drops)}</b><span>object_write_drops</span></div>
          <div class="card"><b>${cell(fx.keys)}</b><span>fabric_keys</span></div>
          <div class="card"><b>${cell(fx.hosts)}</b><span>fabric_hosts</span></div>
          <div class="card"><b>${cell(fx.merged)}</b><span>adverts_merged</span></div>
          <div class="card"><b>${cell(f.sent)}</b><span>adverts_sent</span></div>
          <div class="card"><b>${cell(f.send_failures)}</b><span>advert_send_failures</span></div>
        </div>`;
    }
  } catch(e){}
  // serving SLO verdicts (percentiles + burn rate vs error budget)
  let slo = "";
  try {
    const sr = await fetch("/admin/slo?window=admin-ui");
    if (sr.ok){
      const s = await sr.json();
      const scols = ["name","target_ms","window_p_ms","cumulative_p_ms",
                     "window_samples","fraction_over_target","burn_rate","ok"];
      const sbody = (s.objectives || []).map(o =>
        "<tr>" + scols.map(c => `<td>${
          c === "fraction_over_target" || c === "burn_rate"
            ? fnum(o[c]) : cell(o[c])
        }</td>`).join("") + "</tr>"
      ).join("");
      if (sbody) slo = `<br><h3>serving SLOs ${s.ok
          ? '<span class="pill ok">within budget</span>'
          : '<span class="pill bad">burning</span>'}</h3><table><tr>`
        + scols.map(c => `<th>${esc(c)}</th>`).join("")
        + `</tr>${sbody}</table>`;
    }
  } catch(e){}
  // step introspection: what the scheduler dispatched last (newest first)
  let steps = "";
  try {
    const r = await fetch("/admin/engine/steps?limit=32");
    if (r.ok){
      const intro = await r.json();
      // compile tracking + live roofline summary cards (a serving-stage
      // XLA compile on a warmed engine is the mid-traffic catastrophe)
      const xc = intro.xla_compiles || {};
      const rf = intro.roofline || {};
      steps = `<br><h3>step attribution &amp; roofline</h3>
        <div class="cards">
          <div class="card"><b>${cell((xc.serving||{}).count)}</b><span>serving_xla_compiles</span></div>
          <div class="card"><b>${cell((xc.warmup||{}).count)}</b><span>warmup_xla_compiles</span></div>
          <div class="card"><b>${fnum(rf.mfu)}</b><span>live_mfu</span></div>
          <div class="card"><b>${fnum(rf.hbm_roofline_frac)}</b><span>live_hbm_roofline_frac</span></div>
          <div class="card"><b>${cell((intro.phase_sampling||{}).samples)}</b><span>phase_samples</span></div>
        </div>`;
      const cols = ["seq","kind","batch","width","bucket","ctx_pages",
                    "duration_ms","gap_ms","tokens","superstep","frozen",
                    "mfu","hbm_frac",
                    "phases","queue_depth","kv_pages_in_use"];
      const body = (intro.steps || []).slice().reverse().map(s =>
        "<tr>" + cols.map(c => `<td>${
          c === "mfu" || c === "hbm_frac" ? fnum(s[c]) : cell(s[c])
        }</td>`).join("") + "</tr>"
      ).join("");
      if (body) steps += `<br><h3>recent engine steps</h3><table><tr>`
        + cols.map(c => `<th>${esc(c)}</th>`).join("") + `</tr>${body}</table>`;
    }
  } catch(e){}
  document.getElementById("view").innerHTML =
    `<div class="cards">${cards}${extra}</div>${pool}${fabric}${slo}${steps}
     <br><button class="act" onclick="engineProfile()">capture jax profile</button>
     <button class="act" onclick="engineProfileCtl('start')">start profile</button>
     <button class="act" onclick="engineProfileCtl('stop')">stop profile</button>
     <button class="act" onclick="engineProfileStatus()">profile status</button>`;
  document.getElementById("status").textContent = "engine stats";
}
function gwFlightTable(title, rows){
  // phase vector rendered inline: the breakdown IS the payload here
  const cols = ["ts","method","path","status","tenant","duration_ms",
                "phases_ms","error","trace_id"];
  const body = (rows || []).map(r =>
    "<tr>" + cols.map(c => {
      if (c === "phases_ms")
        return `<td class="kv">${esc(JSON.stringify(r.phases_ms || {}))}</td>`;
      if (c === "ts") return `<td>${esc(new Date((r.ts||0)*1000)
        .toISOString().slice(11,23))}</td>`;
      return `<td>${cell(r[c])}</td>`;
    }).join("") + "</tr>").join("");
  if (!body) return "";
  return `<br><h3>${esc(title)}</h3><table><tr>`
    + cols.map(c => `<th>${esc(c)}</th>`).join("") + `</tr>${body}</table>`;
}
function renderGatewayFlight(snap){
  // request flight recorder: slowest-N + recent rings with per-phase
  // breakdowns, loop-lag health, engine backpressure — the HTTP-tier
  // twin of the engine tab's step attribution card
  const loop = snap.loop || {};
  const bp = snap.backpressure || {};
  const cards = `<div class="cards">
    <div class="card"><b>${cell(snap.recorded)}</b><span>requests_recorded</span></div>
    <div class="card"><b>${cell(snap.slow_requests)}</b><span>slow_requests (&gt;${cell(snap.slow_request_ms)}ms)</span></div>
    <div class="card"><b>${cell(snap.inflight)}</b><span>in_flight</span></div>
    <div class="card"><b>${cell(loop.last_lag_ms)}</b><span>loop_lag_last_ms</span></div>
    <div class="card"><b>${cell(loop.max_lag_ms)}</b><span>loop_lag_max_ms</span></div>
    <div class="card"><b>${cell(loop.long_callbacks)}</b><span>long_callbacks</span></div>
    <div class="card"><b>${cell(bp.depth)}</b><span>engine_queue_depth</span></div>
    <div class="card"><b>${fnum(bp.saturation)}</b><span>engine_saturation</span></div>
    <div class="card"><b>${cell(snap.shed_total)}</b><span>requests_shed</span></div>
   </div>`;
  // degradation ladder (docs/resilience.md): one pill per component —
  // closed = healthy, half_open = probing recovery, open = degraded
  // path active (full breaker detail at GET /admin/faults)
  const deg = snap.degradation || {};
  const degRow = Object.keys(deg).length
    ? "<br><h3>degradation ladder</h3><div class=\"cards\">"
      + Object.keys(deg).sort().map(c =>
        `<div class="card"><b>${esc(deg[c])}</b><span>${esc(c)}</span></div>`
      ).join("") + "</div>"
    : "";
  document.getElementById("view").innerHTML = cards + degRow
    + '<br><button class="act" onclick="faultsDetail()">fault plane / breakers</button>'
    + gwFlightTable("slowest requests", snap.slowest)
    + gwFlightTable("recent requests", snap.recent);
  document.getElementById("status").textContent = "gateway flight recorder";
}
async function faultsDetail(){
  // the resilience plane (docs/resilience.md): armed fault rules with
  // fired/call counts (disarmable per point), breaker snapshots +
  // transition history, rollup outage stats, shedder counters
  const r = await fetch("/admin/faults");
  const d = document.getElementById("detail");
  d.style.display = "block";
  if (!r.ok){ d.textContent = "faults fetch failed: " + r.status; return; }
  const f = await r.json();
  faultRules = f.rules || [];
  let html = `<b>fault plane ${f.enabled ? "(ARMED)" : "(disabled)"}</b>`;
  html += faultRules.length
    ? "<table><tr><th>point</th><th>kind</th><th>mode</th><th>scope</th>"
      + "<th>fired/calls</th><th></th></tr>"
      + faultRules.map((r2, i) =>
        `<tr><td>${esc(r2.point)}</td><td>${esc(r2.kind)}</td>`
        + `<td>${esc(r2.mode)}</td><td>${esc(r2.scope||"")}</td>`
        + `<td>${cell(r2.fired)}/${cell(r2.calls)}</td>`
        + `<td><button class="act" onclick="faultDisarm(${i})">disarm</button></td></tr>`
      ).join("") + "</table>"
    : "<div class=\"kv\">no rules armed</div>";
  const deg = f.degradation || {};
  html += "<br><b>breakers</b><table><tr><th>component</th><th>key</th>"
    + "<th>state</th><th>consec</th><th>fail/ok</th></tr>"
    + (deg.breakers||[]).map(b =>
      `<tr><td>${esc(b.component)}</td><td>${esc(b.key||"")}</td>`
      + `<td>${esc(b.state)}</td><td>${cell(b.consecutive_failures)}</td>`
      + `<td>${cell(b.failures)}/${cell(b.successes)}</td></tr>`).join("")
    + "</table>";
  if (deg.rollup)
    html += `<div class="kv">rollup outage: pending ${cell(deg.rollup.pending_windows)}`
      + `/${cell(deg.rollup.pending_max)}, dropped ${cell(deg.rollup.windows_dropped)}`
      + ` window(s) / ${cell(deg.rollup.tokens_dropped)} token(s)</div>`;
  if (f.shedder)
    html += `<div class="kv">shedder: shed_total ${cell(f.shedder.shed_total)},`
      + ` bar ${fnum(f.shedder.shed_at)}, order ${esc(JSON.stringify(f.shedder.class_order))}</div>`;
  html += "<div class=\"kv\">transitions: "
    + esc((deg.transitions||[]).map(t =>
      `${t.component}:${t.from}→${t.to}`).join(", ") || "none") + "</div>";
  d.innerHTML = html;
}
let faultRules = [];
async function faultDisarm(i){
  // index-based lookup: the point name is server data and must never
  // be interpolated into an onclick JS string (tenants-tab XSS rule)
  const rule = faultRules[i];
  if (!rule) return;
  await fetch(`/admin/faults/${encodeURIComponent(String(rule.point))}`,
              {method: "DELETE"});
  faultsDetail();
}
let forensicRows = [];
function renderForensics(snap){
  // tail-sampled trace store (observability/trace_store.py): what
  // survived retention and why, each row clicking through to its
  // stitched cross-layer waterfall at /admin/trace/{id}
  forensicRows = snap.traces || [];
  const cards = `<div class="cards">
    <div class="card"><b>${cell(snap.retained)}/${cell(snap.max_traces)}</b><span>retained (budget)</span></div>
    <div class="card"><b>${cell(snap.finalized)}</b><span>traces_finalized</span></div>
    <div class="card"><b>${cell(snap.dropped)}</b><span>dropped (boring)</span></div>
    <div class="card"><b>${cell(snap.evicted)}</b><span>evicted (budget)</span></div>
    <div class="card"><b>${cell(snap.open)}</b><span>open</span></div>
    <div class="card"><b>${cell((snap.exemplars||{}).pinned_traces)}</b><span>exemplar_pins</span></div>
   </div>`;
  const cols = ["ts","root","route","tenant","status","duration_ms",
                "span_count","reasons","breaches","trace_id"];
  const body = forensicRows.map((t, i) =>
    "<tr>" + cols.map(c => {
      if (c === "ts") return `<td>${esc(new Date((t.ts||0)*1000)
        .toISOString().slice(11,23))}</td>`;
      if (c === "reasons" || c === "breaches")
        return `<td>${esc((t[c]||[]).join(","))}</td>`;
      return `<td>${cell(t[c])}</td>`;
    }).join("")
    + `<td><button class="act" onclick="forensicWaterfall(${i})">waterfall</button></td></tr>`
  ).join("");
  document.getElementById("view").innerHTML = cards
    + (body ? `<br><h3>retained traces (newest first)</h3><table><tr>`
      + cols.map(c => `<th>${esc(c)}</th>`).join("")
      + `<th></th></tr>${body}</table>`
      : "<br>no retained traces yet — drive some traffic");
  document.getElementById("status").textContent = "request forensics";
}
async function forensicWaterfall(i){
  const row = forensicRows[i];
  if (!row) return;
  const id = encodeURIComponent(String(row.trace_id || ""));
  const r = await fetch(`/admin/trace/${id}`);
  const d = document.getElementById("detail");
  d.style.display = "block";
  if (!r.ok){ d.textContent = "waterfall fetch failed: " + r.status; return; }
  const w = await r.json();
  const inv = w.invariants || {};
  const pill = ok => ok ? '<span class="pill ok">ok</span>'
                        : '<span class="pill bad">violated</span>';
  let html = `<b>waterfall ${esc(String(row.trace_id||""))}</b>
    <div class="cards">
      <div class="card"><b>${cell((w.root||{}).duration_ms)}</b><span>wall_ms (${esc((w.root||{}).name||"?")})</span></div>
      <div class="card"><b>${cell(w.span_count)}</b><span>spans</span></div>
      <div class="card"><b>${esc((w.replica_hops||[]).join(" → ")||"-")}</b><span>replica_hops</span></div>
      <div class="card"><b>${cell(w.engine_steps_joined)}</b><span>engine_steps_joined</span></div>
      <div class="card">${pill(inv.children_within_parent)}<span>children_within_parent</span></div>
      <div class="card">${pill(inv.child_cover_le_wall)}<span>child_cover_le_wall</span></div>
    </div>`;
  if (w.gateway)
    html += `<div class="kv">gateway phases (sum ${cell(w.gateway.phase_sum_ms)}ms`
      + ` / wall ${cell(w.gateway.duration_ms)}ms): `
      + `${esc(JSON.stringify(w.gateway.phases_ms||{}))}</div>`;
  // indented span rows + gantt bars over the trace window
  const flat = [];
  const walk = (node, depth) => {
    flat.push([node, depth]);
    for (const c of node.children || []) walk(c, depth+1);
  };
  for (const root of w.tree || []) walk(root, 0);
  const starts = flat.map(([s]) => s.start_ts).filter(v => v != null);
  const t0 = starts.length ? Math.min(...starts) : 0;
  const t1 = Math.max(...flat.map(([s]) =>
    (s.start_ts||t0) + ((s.duration_ms||0)/1000)), t0 + 1e-6);
  const win = t1 - t0;
  html += flat.map(([s, depth]) => {
    const left = (((s.start_ts||t0)-t0)/win)*100;
    const width = Math.max((((s.duration_ms||0)/1000)/win)*100, 0.3);
    const cls = s.status === "ERROR" ? "bar err" : "bar";
    const steps = s.engine_steps ? ` [${s.engine_steps.length} engine steps]` : "";
    return `<div class="span-row${s.status==="ERROR"?" err":""}">`
      + `${"  ".repeat(depth)}${esc(s.name)} (${esc(s.layer||"")})`
      + `  ${s.duration_ms == null ? "" : Math.round(s.duration_ms*100)/100 + "ms"}`
      + `${esc(steps)}</div>`
      + `<div class="gantt"><div class="${cls}" style="left:${left.toFixed(2)}%;width:${width.toFixed(2)}%"></div></div>`;
  }).join("");
  d.innerHTML = html;
}
function renderController(snap){
  // closed-loop serving controller (tpu_local/controller.py): the
  // decision audit ring — signal snapshot in, knob delta out, observed
  // effect after the eval window — plus live per-replica knob state
  const cards = `<div class="cards">
    <div class="card"><b>${snap.enabled ? (snap.safe_mode ? "SAFE (observe-only)" : "ACTIVE") : "off"}</b><span>controller</span></div>
    <div class="card"><b>${cell(snap.ticks)}</b><span>ticks</span></div>
    <div class="card"><b>${cell(snap.tick_s)}s / ${cell(snap.cooldown_s)}s</b><span>tick / cooldown</span></div>
    <div class="card"><b>${fnum(snap.hysteresis)}</b><span>hysteresis</span></div>
    <div class="card"><b>${fnum(snap.shed_bar)}</b><span>shed_bar (floor ${fnum(snap.shed_floor)}, ceil ${fnum(snap.shed_ceiling)})</span></div>
   </div>`;
  // per-replica knob state: what the engines are ACTUALLY running now
  const knobs = snap.knobs || {};
  const knobRows = Object.keys(knobs).sort().map(rid => {
    const k = knobs[rid] || {};
    return `<tr><td>${esc(rid)}</td><td>${cell(k.superstep)}</td>`
      + `<td>${esc(JSON.stringify(k.warmed_k||[]))}</td>`
      + `<td>${cell(k.width_floor)}</td><td>${cell(k.batch_width)}</td>`
      + `<td>${k.spec_built ? (k.spec_enabled ? "on" : "off") : "-"}</td></tr>`;
  }).join("");
  const knobTable = knobRows
    ? `<br><h3>replica knobs</h3><table><tr><th>replica</th><th>K</th>`
      + `<th>warmed_k</th><th>width_floor</th><th>batch_width</th>`
      + `<th>spec</th></tr>${knobRows}</table>`
    : "<br>no engines wired";
  // decision ring, newest first: every row says what the controller
  // saw, what it moved, and what the signals did afterwards
  const cols = ["ts","replica","knob","direction","from","to","actuated",
                "signals","effect"];
  const body = (snap.decisions || []).map(d =>
    "<tr>" + cols.map(c => {
      if (c === "ts") return `<td>${esc(new Date((d.ts||0)*1000)
        .toISOString().slice(11,23))}</td>`;
      if (c === "signals" || c === "effect")
        return `<td class="kv">${esc(JSON.stringify(d[c]||{}))}</td>`;
      if (c === "actuated") return `<td>${cell(d.actuated === true)}</td>`;
      return `<td>${cell(d[c])}</td>`;
    }).join("") + "</tr>").join("");
  const ring = body
    ? `<br><h3>decisions (newest first)</h3><table><tr>`
      + cols.map(c => `<th>${esc(c)}</th>`).join("") + `</tr>${body}</table>`
    : "<br>no decisions yet — the loop holds until signals warrant a move";
  document.getElementById("view").innerHTML = cards + knobTable + ring;
  document.getElementById("status").textContent = "serving controller";
}
async function renderTenants(usage){
  // per-tenant metering (observability/metering.py): live ledger rows,
  // quota window, label clamp, and the recent DB rollups — plus each
  // tenant's SLO-class verdict fetched per row from /admin/slo?tenant=
  const clamp = usage.clamp || {};
  const cards = `<div class="cards">
    <div class="card"><b>${cell(usage.tenant_count)}</b><span>tenants</span></div>
    <div class="card"><b>${cell(usage.rollups_written)}</b><span>rollup_rows_written</span></div>
    <div class="card"><b>${cell(usage.rollup_interval_s)}</b><span>rollup_interval_s</span></div>
    <div class="card"><b>${cell(usage.quota_tokens_per_window) || "off"}</b><span>quota_tokens_per_window</span></div>
    <div class="card"><b>${cell((clamp.admitted||[]).length)}/${cell(clamp.max_tenants)}</b><span>label_clamp (top-N + other)</span></div>
   </div>`;
  const cols = ["tenant","label","requests","prompt_tokens","generated_tokens",
                "cache_hit_tokens","kv_page_seconds","window_tokens",
                "quota_used_ratio"];
  // index-based handler lookup: a tenant id is attacker-influenced
  // (user emails), and interpolating it into an onclick JS string would
  // let a quote in the id break out (the HTML parser decodes esc()'s
  // entities BEFORE the JS engine parses the attribute)
  tenantRows = usage.tenants || [];
  const body = tenantRows.map((t, i) =>
    "<tr>" + cols.map(c => `<td>${
      c === "quota_used_ratio" || c === "kv_page_seconds" ? fnum(t[c]) : cell(t[c])
    }</td>`).join("")
    + `<td><button class="act" onclick="tenantSlo(${i})">slo</button></td></tr>`
  ).join("");
  let table = body ? `<br><h3>ledger (cumulative since boot)</h3><table><tr>`
    + cols.map(c => `<th>${esc(c)}</th>`).join("") + `<th></th></tr>${body}</table>` : "";
  const rcols = ["tenant","window_start","window_end","requests",
                 "prompt_tokens","generated_tokens","cache_hit_tokens",
                 "kv_page_seconds"];
  const rbody = (usage.rollups || []).slice(0, 24).map(r =>
    "<tr>" + rcols.map(c => `<td>${
      c === "window_start" || c === "window_end"
        ? esc(new Date((r[c]||0)*1000).toISOString().slice(11,19)) : cell(r[c])
    }</td>`).join("") + "</tr>").join("");
  if (rbody) table += `<br><h3>recent rollups (tenant_usage table)</h3><table><tr>`
    + rcols.map(c => `<th>${esc(c)}</th>`).join("") + `</tr>${rbody}</table>`;
  document.getElementById("view").innerHTML = cards + table
    + `<pre id="tenant-slo" class="kv"></pre>`;
  document.getElementById("status").textContent = "tenant usage metering";
}
let tenantRows = [];
async function tenantSlo(i){
  // the tenant's assigned SLO class, evaluated over ITS label slice
  const row = tenantRows[i];
  if (!row) return;
  const r = await fetch("/admin/slo?window=admin-ui&tenant=" + encodeURIComponent(row.tenant));
  const el = document.getElementById("tenant-slo");
  if (!r.ok){ el.textContent = "slo fetch failed: " + r.status; return; }
  const s = await r.json();
  el.textContent = JSON.stringify({tenant: s.tenant, slo_class: s.slo_class,
    tenant_label: s.tenant_label, clamped: s.tenant_clamped, ok: s.ok,
    objectives: (s.objectives||[]).map(o => ({name: o.name, target_ms: o.target_ms,
      window_p_ms: o.window_p_ms, window_samples: o.window_samples,
      burn_rate: o.burn_rate, ok: o.ok}))}, null, 1);
}
async function poolAct(rid, action){
  const r = await fetch(`/admin/engine/pool/${rid}/${action}`, {method:"POST"});
  document.getElementById("status").textContent = r.ok
    ? `replica ${rid} ${action} ok` : `replica ${rid} ${action} failed: ${r.status}`;
  if (r.ok) show("engine");
}
async function engineProfileCtl(action){
  const url = action === "start" ? "/admin/engine/profile/start"
                                 : "/admin/engine/profile/stop";
  const r = await fetch(url, {method:"POST"});
  document.getElementById("status").textContent =
    r.ok ? "profile " + action + " ok" : "profile " + action + " failed: " + r.status;
}
async function engineProfileStatus(){
  const r = await fetch("/admin/engine/profile/status");
  document.getElementById("status").textContent = r.ok
    ? "profiler active: " + (await r.json()).active
    : "profile status failed: " + r.status;
}
async function renderDiagnostics(){
  // system-scale counters + operation timing + support-bundle download
  const v = document.getElementById("view");
  const [sr, pr, cr] = await Promise.all([
    fetch("/admin/system/stats"), fetch("/admin/performance"),
    fetch("/admin/classification")]);
  if (!sr.ok){ v.textContent = "system stats fetch failed: " + sr.status; return; }
  const stats = await sr.json();
  let html = "";
  for (const family of ["users","teams","tokens","metrics","security","workflows"]){
    const fam = stats[family];
    if (!fam || typeof fam !== "object") continue;
    const cards = Object.keys(fam).map(k =>
      `<div class="card"><b>${cell(fam[k])}</b><span>${esc(family+"."+k)}</span></div>`).join("");
    html += `<div class="cards">${cards}</div>`;
  }
  const ent = stats.entities || {};
  const entRows = Object.keys(ent).map(k => {
    const e = ent[k];
    const total = (e && typeof e === "object") ? e.total : e;
    const enabled = (e && typeof e === "object") ? e.enabled : "";
    return `<tr><td>${esc(k)}</td><td>${cell(total)}</td><td>${cell(enabled)}</td></tr>`;
  }).join("");
  html += `<table><tr><th>entity</th><th>total</th><th>enabled</th></tr>${entRows}</table>`;
  if (pr.ok){
    const perf = await pr.json();
    const ops = perf.operations || {};
    const perfRows = Object.keys(ops).map(k => {
      const o = ops[k];
      return `<tr><td>${esc(k)}</td><td>${cell(o.count)}</td><td>${cell(o.avg_ms)}</td>`
        + `<td>${cell(o.p50_ms)}</td><td>${cell(o.p95_ms)}</td><td>${cell(o.p99_ms)}</td>`
        + `<td>${cell(o.max_ms)}</td><td>${cell(o.slow)}</td></tr>`;
    }).join("");
    html += `<br><b>operation timings</b><table><tr><th>operation</th><th>count</th>`
      + `<th>avg ms</th><th>p50</th><th>p95</th><th>p99</th><th>max</th><th>slow</th></tr>`
      + `${perfRows}</table>`
      + `<button class="act danger" onclick="clearPerf()">reset timings</button> `;
  }
  if (cr.ok){  // 404 when hot/cold classification is disabled
    const cls = await cr.json();
    html += `<br><b>gateway polling</b><div class="cards">`
      + `<div class="card"><b>${cell((cls.hot||[]).length)}</b><span>hot peers</span></div>`
      + `<div class="card"><b>${cell((cls.cold||[]).length)}</b><span>cold peers</span></div>`
      + `<div class="card"><b>${cell((cls.metadata||{}).cycle)}</b><span>poll cycle</span></div></div>`;
  }
  html += `<br><a class="act" href="/admin/support-bundle" download>download support bundle</a>`;
  v.innerHTML = html;
  document.getElementById("status").textContent = "diagnostics";
}
async function clearPerf(){
  const r = await fetch("/admin/performance", {method:"DELETE"});
  await renderDiagnostics();  // re-render first: it overwrites the status
  document.getElementById("status").textContent =
    r.ok ? "timings cleared" : "clear failed: " + r.status;
}
async function engineProfile(){
  const r = await fetch("/admin/engine/profile", {method:"POST",
    headers:{"content-type":"application/json"}, body:"{}"});
  document.getElementById("status").textContent =
    r.ok ? "profile captured" : "profile failed: " + r.status;
}
async function renderDashboard(){
  // totals from /metrics + hourly bars from the combined series (rollups
  // + un-rolled raw tail, so the current hour is never missing)
  const v = document.getElementById("view");
  const [mr, rr] = await Promise.all([fetch("/metrics"), fetch("/metrics/timeseries?hours=24")]);
  if (!mr.ok || !rr.ok){ v.textContent = "dashboard fetch failed"; return; }
  const metrics = await mr.json(), roll = await rr.json();
  const tools = metrics.tools || [];
  const calls = tools.reduce((a,t)=>a+(t.calls||0),0);
  const errors = tools.reduce((a,t)=>a+(t.errors||0),0);
  const avg = tools.length ? tools.reduce((a,t)=>a+(t.avg_ms||0),0)/tools.length : 0;
  const byHour = {};
  for (const r of roll) {
    const h = r.hour;
    byHour[h] = byHour[h] || {calls:0, errors:0};
    byHour[h].calls += r.calls ?? r.count ?? 0;
    byHour[h].errors += r.errors || 0;
  }
  const hours = Object.keys(byHour).map(Number).sort((a,b)=>a-b);
  const peak = Math.max(1, ...hours.map(h=>byHour[h].calls));
  const chart = hours.map(h=>{
    const b = byHour[h];
    const hv = Math.round((b.calls/peak)*100);
    const he = Math.round((b.errors/peak)*100);
    const label = new Date(h*3600*1000).getUTCHours();
    return `<div class="col" title="${b.calls} calls / ${b.errors} errors">`
      + `<div class="e" style="height:${he}%"></div>`
      + `<div class="v" style="height:${Math.max(hv-he,0)}%"></div>`
      + `<div class="t">${label}</div></div>`;
  }).join("");
  v.innerHTML = `<div class="cards">
    <div class="card"><b>${calls}</b><span>tool calls</span></div>
    <div class="card"><b>${errors}</b><span>errors</span></div>
    <div class="card"><b>${Math.round(avg*100)/100}</b><span>avg ms</span></div>
    <div class="card"><b>${tools.length}</b><span>active tools</span></div>
   </div>
   <div class="chart">${chart || '<span style="color:#889">no rollup data — POST /metrics/rollup to aggregate</span>'}</div>
   <br><button class="act" onclick="runRollup()">run rollup now</button>
   <button class="act" onclick="pruneMetrics()">prune raw metrics</button>
   <button class="act danger" onclick="resetMetrics()">reset ALL metrics (/metrics/reset)</button>`;
  document.getElementById("status").textContent = "dashboard";
}
async function runRollup(){
  const r = await fetch("/metrics/rollup", {method:"POST"});
  document.getElementById("status").textContent = r.ok ? "rolled up" : "rollup failed";
  renderDashboard();
}
async function resetMetrics(){
  if (!confirm("drop ALL raw metrics and rollups?")) return;
  const r = await fetch("/metrics/reset", {method:"POST"});
  document.getElementById("status").textContent = r.ok ? "metrics reset" : "reset failed";
  renderDashboard();
}
async function pruneMetrics(){
  const r = await fetch("/metrics/prune", {method:"POST"});
  document.getElementById("status").textContent = r.ok ?
    "pruned " + (await r.json()).pruned + " rows" : "prune failed";
}
let chatSession = null;
function renderChat(){
  document.getElementById("view").innerHTML = `
   <div style="background:#fff;padding:14px;box-shadow:0 1px 3px rgba(0,0,0,.08)">
    <b>llmchat playground</b> (tpu_local agent + gateway tools, SSE streaming)<br>
    <div id="chat-log" style="min-height:160px;max-height:420px;overflow:auto;
      font-size:13px;margin:10px 0;border:1px solid #eceef1;padding:8px"></div>
    <input id="chat-input" style="width:70%;padding:6px 10px;border:1px solid #ccd;border-radius:4px"
      placeholder="message…" onkeydown="if(event.key==='Enter')sendChat()">
    <button class="act" onclick="sendChat()">send (/llmchat)</button>
    <button class="act danger" onclick="resetChat()">reset session</button>
   </div>`;
  document.getElementById("status").textContent =
    chatSession ? "session " + chatSession : "no session yet";
}
function chatLine(cls, text){
  const log = document.getElementById("chat-log");
  if (!log) return null;  // user left the chat tab mid-stream
  const div = document.createElement("div");
  div.style.whiteSpace = "pre-wrap";
  if (cls === "user") div.style.fontWeight = "600";
  if (cls === "tool") div.style.color = "#667";
  if (cls === "err") div.style.color = "#a12622";
  div.textContent = text;
  log.appendChild(div);
  log.scrollTop = log.scrollHeight;
  return div;
}
async function resetChat(){
  if (chatSession) await fetch(`/llmchat/${chatSession}`, {method:"DELETE"});
  chatSession = null;
  renderChat();
}
let chatBusy = false;
async function sendChat(){
  if (chatBusy) return;  // one in-flight turn per session: concurrent
                         // turns would interleave the stored history
  const input = document.getElementById("chat-input");
  const text = input.value.trim();
  if (!text) return;
  chatBusy = true;
  try {
    if (!chatSession){
      const r = await fetch("/llmchat/connect", {method:"POST",
        headers:{"content-type":"application/json"}, body:"{}"});
      if (!r.ok){ chatLine("err", "connect failed: " + r.status); return; }
      chatSession = (await r.json()).session_id;
      document.getElementById("status").textContent = "session " + chatSession;
    }
    chatLine("user", "you: " + text);
    const r = await fetch(`/llmchat/${chatSession}/chat`, {method:"POST",
      headers:{"content-type":"application/json"},
      body: JSON.stringify({message: text, stream: true})});
    if (!r.ok){ chatLine("err", "chat failed: " + r.status); return; }
    input.value = "";  // only a SENT message clears the box
    const reader = r.body.getReader();
    const decoder = new TextDecoder();
    let buffer = "", tokenDiv = null;
    while (true){
      const {done, value} = await reader.read();
      if (done) break;
      buffer += decoder.decode(value, {stream: true});
      let idx;
      while ((idx = buffer.indexOf("\n\n")) !== -1){
        const frame = buffer.slice(0, idx);
        buffer = buffer.slice(idx + 2);
        if (!frame.startsWith("data: ") || frame === "data: [DONE]") continue;
        let event;
        try { event = JSON.parse(frame.slice(6)); } catch(e){ continue; }
        if (event.type === "token"){
          if (!tokenDiv) tokenDiv = chatLine("", "assistant: ");
          if (tokenDiv) tokenDiv.textContent += event.text;
        } else if (event.type === "tool_call"){
          tokenDiv = null;  // next step's tokens open a NEW line (they
                            // must render BELOW the tool lines, in order)
          chatLine("tool", `→ tool ${event.tool}(${event.arguments || "{}"})`);
        } else if (event.type === "tool_result"){
          chatLine("tool", `← ${event.tool}: ${event.text}`);
        } else if (event.type === "answer"){
          if (tokenDiv) tokenDiv = null;
          else chatLine("", "assistant: " + event.text);
        } else if (event.type === "error"){
          chatLine("err", "error: " + event.message);
        }
      }
    }
  } finally {
    chatBusy = false;
  }
}
function renderExportImport(){
  document.getElementById("view").innerHTML = `
   <div style="background:#fff;padding:14px;box-shadow:0 1px 3px rgba(0,0,0,.08)">
    <b>export</b><br>
    <label style="font-size:12px"><input type="checkbox" id="exp-secrets"> include secrets (sealed)</label>
    <button class="act" onclick="doExport()">download bundle (/export)</button>
    <hr>
    <b>import</b> (paste a bundle)<br>
    <textarea id="import-area" placeholder='{"version":1,"entities":{...}}'></textarea><br>
    <label style="font-size:12px"><input type="checkbox" id="imp-overwrite"> overwrite existing</label>
    <button class="act" onclick="doImport()">import (/import)</button>
    <pre id="imp-result" class="kv"></pre>
   </div>`;
  document.getElementById("status").textContent = "export / import";
}
async function doExport(){
  const secrets = document.getElementById("exp-secrets").checked;
  const r = await fetch("/export" + (secrets ? "?include_secrets=true" : ""));
  if (!r.ok){ document.getElementById("status").textContent = "export failed: " + r.status; return; }
  const blob = new Blob([JSON.stringify(await r.json(), null, 1)], {type:"application/json"});
  const a = document.createElement("a");
  a.href = URL.createObjectURL(blob); a.download = "mcpforge-export.json"; a.click();
  URL.revokeObjectURL(a.href);
}
async function doImport(){
  let bundle;
  try { bundle = JSON.parse(document.getElementById("import-area").value); }
  catch(e){ document.getElementById("status").textContent = "bad JSON: " + esc(String(e)); return; }
  const overwrite = document.getElementById("imp-overwrite").checked;
  const r = await fetch("/import", {method:"POST",
    headers:{"content-type":"application/json"},
    body: JSON.stringify({bundle, overwrite})});
  const out = await r.text();
  document.getElementById("imp-result").textContent = out.slice(0, 2000);
  document.getElementById("status").textContent = r.ok ? "imported" : "import failed: " + r.status;
}
function render(){
  const t = TABS[current];
  if (!t.cols) return;  // special tabs (engine/dashboard/chat/diagnostics/
                        // ingress/exportimport) render at fetch time
  const q = document.getElementById("q").value.toLowerCase();
  // `shown` is the single source of truth for row indices: click handlers
  // index into it, so a filter edit between render and click cannot
  // misresolve, and attacker data never lands inside a JS string
  shown = rows.filter(d => !q || JSON.stringify(d).toLowerCase().includes(q));
  document.getElementById("status").textContent = shown.length + " rows";
  const hasActs = t.toggle || t.edit || t.del || t.detail || t.rowacts
    || t.special === "plugins";
  const head = "<tr>" + t.cols.map(c=>`<th>${c}</th>`).join("")
    + (hasActs ? "<th></th>" : "") + "</tr>";
  const bools = new Set(t.boolcols || []);
  const body = shown.map((d,i)=>{
    const cells = t.cols.map(c=>{
      if (t.tracecol === c) return `<td><a class="trace" onclick="trace(${i})">${cell(d[c])}</a></td>`;
      if (t.special === "plugins" && c === "mode")
        return `<td><select class="mode" onchange="setMode(${i}, this.value)">`
          + ["enforce","enforce_ignore_error","permissive","audit","disabled"].map(m =>
            `<option${m===d.mode?" selected":""}>${m}</option>`).join("") + "</select></td>";
      return `<td>${cell(d[c], bools.has(c))}</td>`;
    }).join("");
    let act = "";
    if (t.detail) act += `<button class="act" onclick="detailRow(${i})">view</button> `;
    if (t.toggle) act += `<button class="act" onclick="toggleRow(${i})">toggle</button> `;
    if (t.edit)   act += `<button class="act" onclick="editRow(${i})">edit</button> `;
    for (const [j, ra] of (t.rowacts || []).entries())
      act += `<button class="act" onclick="rowAct(${i},${j})">${ra.label}</button> `;
    if (t.del)    act += `<button class="act danger" onclick="delRow(${i})">delete</button>`;
    return "<tr>"+cells+(hasActs?`<td>${act}</td>`:"")+"</tr>";
  }).join("");
  document.getElementById("view").innerHTML = `<table>${head}${body}</table>`;
}
async function show(name, keepCursor){
  current = name;
  if (!keepCursor) cursor = null;
  document.getElementById("detail").style.display = "none";
  document.getElementById("form").style.display = "none";
  document.getElementById("newbtn").style.display = TABS[name].create ? "" : "none";
  document.getElementById("morebtn").style.display = "none";
  document.querySelectorAll("nav button").forEach(b=>b.classList.toggle("active", b.textContent===name));
  const t = TABS[name];
  const s = document.getElementById("status");
  s.textContent = "loading…";
  if (t.special === "dashboard") return renderDashboard();
  if (t.special === "exportimport") return renderExportImport();
  if (t.special === "chat") return renderChat();
  if (t.special === "diagnostics") return renderDiagnostics();
  try {
    let url = t.url;
    if (t.paged) {
      url += (url.includes("?") ? "&" : "?") + "limit=100";
      if (cursor) url += "&cursor=" + encodeURIComponent(cursor);
    }
    const r = await fetch(url, {headers: {accept: "application/json"}});
    if (!r.ok) { s.textContent = r.status + " " + esc(await r.text()); return; }
    let data = await r.json();
    if (t.special === "engine") return renderEngine(data);
    if (t.special === "gwflight") return renderGatewayFlight(data);
    if (t.special === "forensics") return renderForensics(data);
    if (t.special === "controller") return renderController(data);
    if (t.special === "tenants") return renderTenants(data);
    if (t.special === "ingress") return renderIngress(data);
    if (t.path) data = data[t.path] || [];
    if (data && !Array.isArray(data) && Array.isArray(data.items)){
      cursor = data.next_cursor;   // cursor-paged shape (pagination.py)
      document.getElementById("morebtn").style.display = cursor ? "" : "none";
      data = data.items;
    }
    rows = Array.isArray(data) ? data : [];
    render();
  } catch(e){ s.textContent = "error: " + esc(String(e)); }
}
function nextPage(){ if (cursor) show(current, true); }
function openForm(){
  const t = TABS[current];
  if (!t.create) return;
  const f = document.getElementById("form");
  f.style.display = "block";
  f.innerHTML = `<b>new ${esc(current)}</b><br>` + t.create.fields.map(x =>
    `<input id="f-${x.split(":")[0]}" placeholder="${x}">`).join("")
    + (t.create.testurl
       ? `<button class="act" onclick="testForm()">test connection</button>`
       : "")
    + `<button class="act" onclick="submitForm()">create</button>`
    + `<span id="f-probe"></span>`;
}
async function testForm(){
  // wizard step: dry-run the connectivity probe before committing
  const t = TABS[current];
  const body = {};
  for (const spec of t.create.fields){
    const x = spec.split(":")[0];
    const el = document.getElementById("f-" + x);
    if (el && el.value) body[x] = el.value;
  }
  const probe = document.getElementById("f-probe");
  probe.textContent = "probing…";
  const r = await fetch(t.create.testurl, {method: "POST",
    headers: {"content-type": "application/json"},
    body: JSON.stringify(body)});
  if (!r.ok){ probe.textContent = "probe failed: " + r.status; return; }
  const d = await r.json();
  probe.innerHTML = d.ok
    ? `<span class="pill ok">reachable</span> ${cell(d.latency_ms)}ms, `
      + `${cell(d.tool_count)} tools, caps: ${esc((d.capabilities||[]).join(", "))}`
    : `<span class="pill bad">unreachable</span> ${esc(d.error||"")}`;
}
async function submitForm(){
  const t = TABS[current];
  const body = {};
  for (const spec of t.create.fields){
    const [x, kind] = spec.split(":");
    const v = document.getElementById("f-"+x).value;
    if (!v) continue;
    if (kind === "int") body[x] = parseInt(v, 10);
    else if (kind === "csv") body[x] = v.split(",").map(s=>s.trim()).filter(Boolean);
    else if (kind === "json") { try { body[x] = JSON.parse(v); } catch(e) { body[x] = v; } }
    else body[x] = v;
  }
  const r = await fetch(t.create.url, {method:"POST",
    headers:{"content-type":"application/json"}, body: JSON.stringify(body)});
  document.getElementById("status").textContent = r.ok ? "created" :
    `create failed: ${r.status} ` + esc(await r.text());
  if (r.ok && t.create.reveal){
    // mint-once secrets (API tokens): shown a single time, never stored
    const out = await r.json();
    const d = document.getElementById("detail");
    d.style.display = "block";
    d.innerHTML = `<b>copy it now — it is not retrievable later</b>
      <div class="reveal">${esc(String(out[t.create.reveal] || ""))}</div>`;
  }
  if (r.ok) show(current, true);
}
async function setMode(i, mode){
  const row = shown[i];
  if (!row) return;
  const r = await fetch(`/plugins/${encodeURIComponent(row.name)}/mode`, {
    method:"POST", headers:{"content-type":"application/json"},
    body: JSON.stringify({mode})});
  document.getElementById("status").textContent = r.ok
    ? `mode of ${row.name} → ${mode}` : "mode change failed: " + r.status;
  if (!r.ok) show(current);
}
async function rowAct(i, j){
  const t = TABS[current], row = shown[i];
  if (!row) return;
  const ra = t.rowacts[j];
  const r = await fetch(ra.url(row[ra.key || t.idcol || "id"]), {method: ra.method});
  document.getElementById("status").textContent =
    `${ra.label}: ` + (r.ok ? "ok" : "failed " + r.status);
  if (ra.show && r.ok){
    const d = document.getElementById("detail");
    d.style.display = "block";
    d.innerHTML = `<b>${esc(ra.label)}</b><pre class="kv">`
      + esc(JSON.stringify(await r.json(), null, 1).slice(0, 4000)) + `</pre>`;
    return;
  }
  show(current);
}
async function renderIngress(data){
  const opts = (data.available || []).map(m =>
    `<option${m===data.mode?" selected":""}>${esc(m)}</option>`).join("");
  document.getElementById("view").innerHTML = `
   <div class="cards">
    <div class="card"><b>${esc(String(data.mode))}</b><span>active ingress</span></div>
    <div class="card"><b>${cell(data.version)}</b><span>version</span></div>
   </div><br>
   <select id="ingress-mode">${opts}</select>
   <button class="act" onclick="setIngress()">switch mode (POST /admin/ingress)</button>`;
  document.getElementById("status").textContent = "ingress mount";
}
async function setIngress(){
  const mode = document.getElementById("ingress-mode").value;
  const r = await fetch("/admin/ingress", {method:"POST",
    headers:{"content-type":"application/json"}, body: JSON.stringify({mode})});
  document.getElementById("status").textContent = r.ok ? "switched" : "switch failed: " + r.status;
  show(current);
}
async function toggleRow(i){
  const t = TABS[current];
  const row = shown[i];
  if (!row) return;
  const id = row[t.idcol || "id"];
  const r = await fetch(t.toggle(id), {method: "POST"});
  if (!r.ok) document.getElementById("status").textContent = "toggle failed: " + r.status;
  show(current);
}
async function detailRow(i){
  const t = TABS[current];
  const row = shown[i];
  if (!row) return;
  const id = row[t.idcol || "id"];
  const r = await fetch(t.detail(id));
  const d = document.getElementById("detail");
  d.style.display = "block";
  if (!r.ok){ d.textContent = "detail fetch failed: " + r.status; return; }
  const full = await r.json();
  const kv = Object.entries(full).map(([k,v]) =>
    `<tr><td><b>${esc(k)}</b></td><td>${cell(v)}</td></tr>`).join("");
  let extra = "";
  if (t.special === "teams"){
    // server data never lands inside a JS string literal: handlers take
    // indices and resolve id/email from detailTeam at click time
    detailTeam = {id: String(id), members: full.members || []};
    const members = detailTeam.members.map((m, midx) =>
      `<tr><td>${esc(m.user_email||"")}</td><td>${esc(m.role||"")}</td>
       <td><button class="act danger" onclick="removeMemberAt(${midx})">remove</button></td></tr>`).join("");
    extra = `<br><b>members</b><table class="kv">${members}</table>
      <input id="m-email" placeholder="email"><input id="m-role" placeholder="role (member)">
      <button class="act" onclick="addMember(detailTeam.id)">add member (/teams/{id}/members)</button>
      <button class="act" onclick="inviteMember(detailTeam.id)">invite (/teams/{id}/invitations)</button>
      <span id="invite-out" class="kv"></span>`;
  }
  if (t.special === "roles"){
    // same index-based pattern as teams: no server data in JS literals
    detailRole = {id: String(id), assignments: full.assignments || []};
    const rows = detailRole.assignments.map((a, aidx) =>
      `<tr><td>${esc(a.user_email||"")}</td><td>${esc(a.scope_id||"")}</td>
       <td><button class="act danger" onclick="revokeRoleAt(${aidx})">revoke</button></td></tr>`).join("");
    extra = `<br><b>assignments</b><table class="kv">${rows}</table>
      <input id="r-email" placeholder="user email"><input id="r-scope" placeholder="scope_id (team-scoped only)">
      <button class="act" onclick="assignRole()">assign (/rbac/users/{email}/roles)</button>
      <br><b>permission inspector</b><br>
      <input id="p-email" placeholder="user email"><input id="p-perm" placeholder="permission">
      <button class="act" onclick="checkPermission()">check (/rbac/permissions/check)</button>
      <button class="act" onclick="userPermissions()">effective set</button>
      <span id="perm-out" class="kv"></span>`;
  }
  d.innerHTML = `<b>${esc(current)} ${esc(String(id))}</b>
    <table class="kv">${kv}</table>${extra}`;
}
let detailRole = null;  // {id, assignments[]} of the open roles detail pane
async function assignRole(){
  if (!detailRole) return;
  const email = document.getElementById("r-email").value;
  const scope = document.getElementById("r-scope").value;
  const r = await fetch(`/rbac/users/${encodeURIComponent(email)}/roles`, {
    method:"POST", headers:{"content-type":"application/json"},
    body: JSON.stringify({role_id: detailRole.id, scope_id: scope})});
  document.getElementById("status").textContent = r.ok ? "role assigned" :
    "assign failed: " + r.status + " " + esc(await r.text());
  show(current);
}
async function revokeRoleAt(aidx){
  if (!detailRole || !detailRole.assignments[aidx]) return;
  const a = detailRole.assignments[aidx];
  const email = String(a.user_email || "");
  const qs = a.scope_id ? `?scope_id=${encodeURIComponent(String(a.scope_id))}` : "";
  const r = await fetch(`/rbac/users/${encodeURIComponent(email)}/roles/${encodeURIComponent(detailRole.id)}` + qs,
    {method:"DELETE"});
  document.getElementById("status").textContent = r.ok ? "role revoked" :
    "revoke failed: " + r.status;
  show(current);
}
async function checkPermission(){
  const email = document.getElementById("p-email").value;
  const perm = document.getElementById("p-perm").value;
  const r = await fetch("/rbac/permissions/check", {method:"POST",
    headers:{"content-type":"application/json"},
    body: JSON.stringify({user_email: email, permission: perm})});
  const out = r.ok ? await r.json() : {error: r.status};
  document.getElementById("perm-out").textContent = JSON.stringify(out);
}
async function userPermissions(){
  const email = document.getElementById("p-email").value;
  const r = await fetch(`/rbac/permissions/user/${encodeURIComponent(email)}`);
  const out = r.ok ? await r.json() : {error: r.status};
  document.getElementById("perm-out").textContent = JSON.stringify(out);
}
async function addMember(teamId){
  const email = document.getElementById("m-email").value;
  const role = document.getElementById("m-role").value || "member";
  const r = await fetch(`/teams/${encodeURIComponent(teamId)}/members`, {
    method:"POST", headers:{"content-type":"application/json"},
    body: JSON.stringify({email, role})});
  document.getElementById("status").textContent = r.ok ? "member added" :
    "add failed: " + r.status + " " + esc(await r.text());
}
async function inviteMember(teamId){
  const email = document.getElementById("m-email").value;
  const r = await fetch(`/teams/${encodeURIComponent(teamId)}/invitations`, {
    method:"POST", headers:{"content-type":"application/json"},
    body: JSON.stringify({email})});
  if (r.ok){
    const out = await r.json();
    document.getElementById("invite-out").textContent =
      "invitation token: " + (out.token || "");
  } else document.getElementById("status").textContent = "invite failed: " + r.status;
}
let detailTeam = null;  // {id, members[]} of the open teams detail pane
async function removeMemberAt(midx){
  if (!detailTeam || !detailTeam.members[midx]) return;
  await removeMember(detailTeam.id, String(detailTeam.members[midx].user_email||""));
}
async function removeMember(teamId, email){
  const r = await fetch(`/teams/${encodeURIComponent(teamId)}/members/${encodeURIComponent(email)}`,
    {method:"DELETE"});
  document.getElementById("status").textContent = r.ok ? "member removed" :
    "remove failed: " + r.status;
}
let editTarget = null;  // id captured at OPEN time: a filter edit must not
                        // re-point the save at a different row
function editRow(i){
  const t = TABS[current];
  const row = shown[i];
  if (!row) return;
  editTarget = row[t.idcol || "id"];
  const d = document.getElementById("detail");
  d.style.display = "block";
  d.innerHTML = `<b>edit ${esc(String(editTarget))}</b><br>`
    + `<textarea id="edit-area"></textarea><br>`
    + `<button class="act" onclick="saveEdit()">save (PUT)</button>`;
  document.getElementById("edit-area").value = JSON.stringify(row, null, 1);
}
async function saveEdit(){
  const t = TABS[current];
  if (editTarget == null) return;
  let body;
  try { body = JSON.parse(document.getElementById("edit-area").value); }
  catch(e){ document.getElementById("status").textContent = "bad JSON: " + esc(String(e)); return; }
  const r = await fetch(t.edit(editTarget), {method:"PUT",
    headers:{"content-type":"application/json"}, body: JSON.stringify(body)});
  document.getElementById("status").textContent = r.ok ? "saved" :
    `save failed: ${r.status} ` + esc(await r.text());
  if (r.ok) show(current);
}
async function delRow(i){
  const t = TABS[current];
  const row = shown[i];
  if (!row || !confirm("delete " + (row.name || row[t.idcol || "id"]) + "?")) return;
  const r = await fetch(t.del(row[t.idcol || "id"]), {method:"DELETE"});
  if (!r.ok) document.getElementById("status").textContent = "delete failed: " + r.status;
  show(current);
}
async function trace(i){
  const t = TABS[current];
  const row = shown[i];
  if (!row) return;
  const id = encodeURIComponent(String(row[t.tracecol] || ""));
  const r = await fetch(`/admin/traces/${id}`);
  const d = document.getElementById("detail");
  d.style.display = "block";
  if (!r.ok) { d.textContent = "trace fetch failed: " + r.status; return; }
  const tree = await r.json();
  const spans = tree.spans;
  const byParent = {};
  for (const s of spans) (byParent[s.parent_span_id || ""] ??= []).push(s);
  const lines = [];
  const walk = (pid, depth) => {
    for (const s of byParent[pid] || []) {
      const cls = s.status === "ERROR" ? " err" : "";
      lines.push(`<div class="span-row${cls}">${"  ".repeat(depth)}${esc(s.name)}`
        + `  ${s.duration_ms == null ? "" : Math.round(s.duration_ms*100)/100 + "ms"}`
        + `  ${esc(JSON.stringify(s.attributes||{})).slice(0,160)}</div>`);
      walk(s.span_id, depth+1);
    }
  };
  walk("", 0);
  // orphan spans (parent outside the stored window) still render
  const seen = new Set(spans.map(s=>s.span_id));
  for (const s of spans)
    if (s.parent_span_id && !seen.has(s.parent_span_id))
      lines.push(`<div class="span-row">${esc(s.name)} (orphan)</div>`);
  // gantt: bars positioned over the trace window (reference trace timeline)
  let gantt = "";
  const starts = spans.map(s=>s.start_ts).filter(v=>v!=null);
  if (starts.length){
    const t0 = Math.min(...starts);
    const t1 = Math.max(...spans.map(s=>(s.start_ts||t0)+((s.duration_ms||0)/1000)));
    const window_s = Math.max(t1 - t0, 1e-6);
    gantt = "<br><b>timeline</b>" + spans.map(s=>{
      const left = (((s.start_ts||t0)-t0)/window_s)*100;
      const width = Math.max((((s.duration_ms||0)/1000)/window_s)*100, 0.3);
      const cls = s.status === "ERROR" ? "bar err" : "bar";
      return `<div class="gantt"><span class="lbl">${esc(s.name)}</span>`
        + `<div class="${cls}" style="left:${left.toFixed(2)}%;width:${width.toFixed(2)}%"></div></div>`;
    }).join("");
  }
  d.innerHTML = `<b>trace ${esc(id)}</b> — ${spans.length} spans` + lines.join("") + gantt;
}
function autoRefresh(){
  if (timer) { clearInterval(timer); timer = null; }
  if (document.getElementById("auto").checked) timer = setInterval(()=>show(current), 5000);
}
const nav = document.getElementById("nav");
for (const name of Object.keys(TABS)){
  const b = document.createElement("button");
  b.textContent = name; b.onclick = ()=>show(name); nav.appendChild(b);
}
show("tools");
"""



def setup_admin_ui(app: web.Application) -> None:
    async def admin_page(request: web.Request) -> web.Response:
        request["auth"].require("observability.read")
        response = web.Response(text=_PAGE, content_type="text/html")
        # double-submit CSRF: the page JS echoes this cookie's value in
        # X-CSRF-Token on every mutating fetch (csrf_middleware validates)
        settings = request.app["ctx"].settings
        if settings.csrf_enabled:
            from ..services import csrf_service
            token = csrf_service.mint(request["auth"].user,
                                      settings.jwt_secret_key,
                                      ttl_s=settings.csrf_token_ttl_s)
            response.set_cookie(settings.csrf_cookie_name, token,
                                httponly=False,  # JS must read to echo
                                secure=settings.csrf_cookie_secure,
                                samesite="Strict", path="/")
        return response

    # substitute the configured CSRF names ONCE (settings are fixed for
    # the app's lifetime; the cookie name lands inside a JS regex literal,
    # so regex metacharacters in it must be escaped — 'csrf.token' is a
    # valid RFC 6265 name that would otherwise change the pattern)
    import re as _re
    settings = app["ctx"].settings
    _served_js = _JS.replace(
        "csrf_token=", _re.escape(settings.csrf_cookie_name) + "=").replace(
        '"X-CSRF-Token"', '"' + settings.csrf_header_name + '"')

    async def admin_js(request: web.Request) -> web.Response:
        request["auth"].require("observability.read")
        return web.Response(text=_served_js,
                            content_type="application/javascript")

    app.router.add_get("/admin", admin_page)
    app.router.add_get("/admin/", admin_page)
    app.router.add_get("/admin/app.js", admin_js)


def admin_page_source() -> str:
    """HTML + JS combined, for the UI contract/coverage test tier (the
    gates scan every URL the page's JS can build)."""
    return _PAGE + _JS


def admin_js_source() -> str:
    """The JS module alone, for the execution test tier
    (tests/integration/test_admin_js_render.py)."""
    return _JS
