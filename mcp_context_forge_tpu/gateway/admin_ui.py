"""Minimal server-rendered admin UI.

Reference: 20.5k-LoC admin.py + 34.8k-LoC JS admin_ui — intentionally
table-driven and tiny here (SURVEY.md §7.2 #5: the API surface must be
generated, not hand-grown). One page, vanilla JS over the existing REST API.
"""

from __future__ import annotations

from aiohttp import web

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>mcpforge admin</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f5f7;color:#1a1d21}
 header{background:#1a1d21;color:#fff;padding:10px 20px;display:flex;gap:16px;align-items:center}
 header h1{font-size:16px;margin:0}
 nav button{background:none;border:none;color:#aab;cursor:pointer;font-size:14px;padding:6px 10px}
 nav button.active{color:#fff;border-bottom:2px solid #6cf}
 main{padding:20px;max-width:1100px;margin:0 auto}
 table{width:100%;border-collapse:collapse;background:#fff;box-shadow:0 1px 3px rgba(0,0,0,.08)}
 th,td{text-align:left;padding:8px 12px;border-bottom:1px solid #eceef1;font-size:13px}
 th{background:#fafbfc;font-weight:600}
 .pill{display:inline-block;padding:1px 8px;border-radius:10px;font-size:11px}
 .ok{background:#d9f2e4;color:#11734b}.bad{background:#fde2e1;color:#a12622}
 #status{margin:10px 0;color:#667}
 pre{background:#fff;padding:12px;overflow:auto;font-size:12px}
</style></head><body>
<header><h1>mcpforge</h1><nav id="nav"></nav></header>
<main><div id="status"></div><div id="view"></div></main>
<script>
const TABS = {
  tools:    {url: "/tools?include_inactive=true", cols: ["name","integration_type","url","enabled","reachable"]},
  gateways: {url: "/gateways?include_inactive=true", cols: ["name","url","transport","state","reachable"]},
  servers:  {url: "/servers?include_inactive=true", cols: ["name","description","associated_tools","enabled"]},
  resources:{url: "/resources?include_inactive=true", cols: ["uri","name","mime_type","enabled"]},
  prompts:  {url: "/prompts?include_inactive=true", cols: ["name","description","enabled"]},
  agents:   {url: "/a2a?include_inactive=true", cols: ["name","agent_type","endpoint_url","enabled","reachable"]},
  models:   {url: "/v1/models", cols: ["id","owned_by"], path: "data"},
  metrics:  {url: "/metrics", cols: ["name","calls","errors","avg_ms","min_ms","max_ms"], path: "tools"},
  traces:   {url: "/admin/traces?limit=50", cols: ["name","duration_ms","status","trace_id"]},
  logs:     {url: "/admin/logs?limit=100", cols: ["ts","level","logger","message"]},
};
function esc(s){
  return String(s).replace(/[&<>"']/g, c => ({"&":"&amp;","<":"&lt;",">":"&gt;",
    '"':"&quot;","'":"&#39;"}[c]));
}
function cell(v){
  if (v === true) return '<span class="pill ok">yes</span>';
  if (v === false) return '<span class="pill bad">no</span>';
  if (Array.isArray(v)) return v.length;
  if (v === null || v === undefined) return "";
  if (typeof v === "number") return Math.round(v*100)/100;
  return esc(String(v).slice(0,80));  // API data is attacker-influenced
}
async function show(name){
  document.querySelectorAll("nav button").forEach(b=>b.classList.toggle("active", b.textContent===name));
  const t = TABS[name];
  const s = document.getElementById("status");
  s.textContent = "loading…";
  try {
    const r = await fetch(t.url, {headers: {accept: "application/json"}});
    if (!r.ok) { s.textContent = r.status + " " + await r.text(); return; }
    let data = await r.json();
    if (t.path) data = data[t.path] || [];
    s.textContent = data.length + " rows";
    const head = "<tr>" + t.cols.map(c=>`<th>${c}</th>`).join("") + "</tr>";
    const rows = data.map(d=>"<tr>"+t.cols.map(c=>`<td>${cell(d[c])}</td>`).join("")+"</tr>").join("");
    document.getElementById("view").innerHTML = `<table>${head}${rows}</table>`;
  } catch(e){ s.textContent = "error: " + e; }
}
const nav = document.getElementById("nav");
for (const name of Object.keys(TABS)){
  const b = document.createElement("button");
  b.textContent = name; b.onclick = ()=>show(name); nav.appendChild(b);
}
show("tools");
</script></body></html>"""


def setup_admin_ui(app: web.Application) -> None:
    async def admin_page(request: web.Request) -> web.Response:
        request["auth"].require("observability.read")
        return web.Response(text=_PAGE, content_type="text/html")

    app.router.add_get("/admin", admin_page)
    app.router.add_get("/admin/", admin_page)
