"""Server-rendered admin UI.

Reference: 20.5k-LoC admin.py + 34.8k-LoC JS admin_ui — intentionally
table-driven here (SURVEY.md §7.2 #5: the API surface must be generated,
not hand-grown). One page, vanilla JS over the existing REST API: entity
tabs with client-side search, enable/disable row actions, trace drill-down
(span tree), users/teams/plugins views, auto-refresh.
"""

from __future__ import annotations

from aiohttp import web

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>mcpforge admin</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f5f7;color:#1a1d21}
 header{background:#1a1d21;color:#fff;padding:10px 20px;display:flex;gap:16px;align-items:center;flex-wrap:wrap}
 header h1{font-size:16px;margin:0}
 nav button{background:none;border:none;color:#aab;cursor:pointer;font-size:14px;padding:6px 10px}
 nav button.active{color:#fff;border-bottom:2px solid #6cf}
 main{padding:20px;max-width:1200px;margin:0 auto}
 table{width:100%;border-collapse:collapse;background:#fff;box-shadow:0 1px 3px rgba(0,0,0,.08)}
 th,td{text-align:left;padding:8px 12px;border-bottom:1px solid #eceef1;font-size:13px}
 th{background:#fafbfc;font-weight:600}
 .pill{display:inline-block;padding:1px 8px;border-radius:10px;font-size:11px}
 .ok{background:#d9f2e4;color:#11734b}.bad{background:#fde2e1;color:#a12622}
 #bar{margin:10px 0;display:flex;gap:10px;align-items:center}
 #status{color:#667}
 #q{padding:6px 10px;border:1px solid #ccd;border-radius:4px;min-width:220px}
 button.act{background:#eef;border:1px solid #ccd;border-radius:4px;cursor:pointer;padding:2px 8px;font-size:12px}
 a.trace{color:#26c;cursor:pointer;text-decoration:underline}
 #detail{background:#fff;margin-top:14px;padding:12px;box-shadow:0 1px 3px rgba(0,0,0,.08);display:none}
 .span-row{font-family:ui-monospace,monospace;font-size:12px;white-space:pre}
 .err{color:#a12622}
</style></head><body>
<header><h1>mcpforge</h1><nav id="nav"></nav></header>
<main>
 <div id="bar">
  <input id="q" placeholder="filter rows…" oninput="render()">
  <button class="act" onclick="show(current)">refresh</button>
  <label style="font-size:12px;color:#667"><input type="checkbox" id="auto"
   onchange="autoRefresh()"> auto (5s)</label>
  <span id="status"></span>
 </div>
 <div id="view"></div>
 <div id="detail"></div>
</main>
<script>
const TABS = {
  tools:    {url: "/tools?include_inactive=true", cols: ["name","integration_type","url","enabled","reachable"], toggle: id => `/tools/${id}/toggle`, boolcols: ["enabled","reachable"]},
  gateways: {url: "/gateways?include_inactive=true", cols: ["name","url","transport","state","reachable"], boolcols: ["reachable"]},
  servers:  {url: "/servers?include_inactive=true", cols: ["name","description","associated_tools","enabled"], boolcols: ["enabled"]},
  resources:{url: "/resources?include_inactive=true", cols: ["uri","name","mime_type","enabled"], boolcols: ["enabled"]},
  prompts:  {url: "/prompts?include_inactive=true", cols: ["name","description","enabled"], boolcols: ["enabled"]},
  agents:   {url: "/a2a?include_inactive=true", cols: ["name","agent_type","endpoint_url","enabled","reachable"], boolcols: ["enabled","reachable"]},
  plugins:  {url: "/plugins", cols: ["name","kind","mode","priority"]},
  users:    {url: "/admin/users", cols: ["email","full_name","is_admin","is_active","auth_provider","last_login"], toggle: id => `/admin/users/${encodeURIComponent(id)}/toggle`, idcol: "email", boolcols: ["is_admin","is_active"]},
  teams:    {url: "/teams", cols: ["name","slug","visibility","is_personal","created_by"], boolcols: ["is_personal"]},
  tokens:   {url: "/auth/tokens", cols: ["name","server_id","expires_at","last_used","revoked_at"]},
  models:   {url: "/v1/models", cols: ["id","owned_by"], path: "data"},
  metrics:  {url: "/metrics", cols: ["name","calls","errors","avg_ms","min_ms","max_ms"], path: "tools"},
  rollups:  {url: "/metrics/rollups", cols: ["entity_type","entity_id","hour","calls","errors","avg_ms"]},
  traces:   {url: "/admin/traces?limit=100", cols: ["name","duration_ms","status","trace_id"], tracecol: "trace_id"},
  logs:     {url: "/admin/logs?limit=200", cols: ["ts","level","logger","message"]},
  audit:    {url: "/admin/audit?limit=100", cols: ["ts","actor","action","details"]},
};
let current = "tools", rows = [], shown = [], timer = null;
function esc(s){
  return String(s).replace(/[&<>"']/g, c => ({"&":"&amp;","<":"&lt;",">":"&gt;",
    '"':"&quot;","'":"&#39;"}[c]));
}
function cell(v, isBool){
  // booleanness is a per-COLUMN decision (sqlite int-bools), never by value
  if (isBool) return (v === true || v === 1)
    ? '<span class="pill ok">yes</span>' : '<span class="pill bad">no</span>';
  if (v === true) return '<span class="pill ok">yes</span>';
  if (v === false) return '<span class="pill bad">no</span>';
  if (Array.isArray(v)) return v.length;
  if (v === null || v === undefined) return "";
  if (typeof v === "number") return Math.round(v*100)/100;
  if (typeof v === "object") return esc(JSON.stringify(v).slice(0,80));
  return esc(String(v).slice(0,100));  // API data is attacker-influenced
}
function render(){
  const t = TABS[current];
  const q = document.getElementById("q").value.toLowerCase();
  // `shown` is the single source of truth for row indices: click handlers
  // index into it, so a filter edit between render and click cannot
  // misresolve, and attacker data never lands inside a JS string
  shown = rows.filter(d => !q || JSON.stringify(d).toLowerCase().includes(q));
  document.getElementById("status").textContent = shown.length + " rows";
  const actions = t.toggle ? "<th></th>" : "";
  const head = "<tr>" + t.cols.map(c=>`<th>${c}</th>`).join("") + actions + "</tr>";
  const bools = new Set(t.boolcols || []);
  const body = shown.map((d,i)=>{
    const cells = t.cols.map(c=>{
      if (t.tracecol === c) return `<td><a class="trace" onclick="trace(${i})">${cell(d[c])}</a></td>`;
      return `<td>${cell(d[c], bools.has(c))}</td>`;
    }).join("");
    const act = t.toggle ? `<td><button class="act" onclick="toggleRow(${i})">toggle</button></td>` : "";
    return "<tr>"+cells+act+"</tr>";
  }).join("");
  document.getElementById("view").innerHTML = `<table>${head}${body}</table>`;
}
async function show(name){
  current = name;
  document.getElementById("detail").style.display = "none";
  document.querySelectorAll("nav button").forEach(b=>b.classList.toggle("active", b.textContent===name));
  const t = TABS[name];
  const s = document.getElementById("status");
  s.textContent = "loading…";
  try {
    const r = await fetch(t.url, {headers: {accept: "application/json"}});
    if (!r.ok) { s.textContent = r.status + " " + esc(await r.text()); return; }
    let data = await r.json();
    if (t.path) data = data[t.path] || [];
    rows = Array.isArray(data) ? data : [];
    render();
  } catch(e){ s.textContent = "error: " + esc(String(e)); }
}
async function toggleRow(i){
  const t = TABS[current];
  const row = shown[i];
  if (!row) return;
  const id = row[t.idcol || "id"];
  const r = await fetch(t.toggle(id), {method: "POST"});
  if (!r.ok) document.getElementById("status").textContent = "toggle failed: " + r.status;
  show(current);
}
async function trace(i){
  const t = TABS[current];
  const row = shown[i];
  if (!row) return;
  const id = encodeURIComponent(String(row[t.tracecol] || ""));
  const r = await fetch(`/admin/traces/${id}`);
  const d = document.getElementById("detail");
  d.style.display = "block";
  if (!r.ok) { d.textContent = "trace fetch failed: " + r.status; return; }
  const tree = await r.json();
  const byParent = {};
  for (const s of tree.spans) (byParent[s.parent_span_id || ""] ??= []).push(s);
  const lines = [];
  const walk = (pid, depth) => {
    for (const s of byParent[pid] || []) {
      const cls = s.status === "ERROR" ? " err" : "";
      lines.push(`<div class="span-row${cls}">${"  ".repeat(depth)}${esc(s.name)}`
        + `  ${s.duration_ms == null ? "" : Math.round(s.duration_ms*100)/100 + "ms"}`
        + `  ${esc(JSON.stringify(s.attributes||{})).slice(0,160)}</div>`);
      walk(s.span_id, depth+1);
    }
  };
  walk("", 0);
  // orphan spans (parent outside the stored window) still render
  const seen = new Set(tree.spans.map(s=>s.span_id));
  for (const s of tree.spans)
    if (s.parent_span_id && !seen.has(s.parent_span_id))
      lines.push(`<div class="span-row">${esc(s.name)} (orphan)</div>`);
  d.innerHTML = `<b>trace ${esc(id)}</b> — ${tree.spans.length} spans` + lines.join("");
}
function autoRefresh(){
  if (timer) { clearInterval(timer); timer = null; }
  if (document.getElementById("auto").checked) timer = setInterval(()=>show(current), 5000);
}
const nav = document.getElementById("nav");
for (const name of Object.keys(TABS)){
  const b = document.createElement("button");
  b.textContent = name; b.onclick = ()=>show(name); nav.appendChild(b);
}
show("tools");
</script></body></html>"""


def setup_admin_ui(app: web.Application) -> None:
    async def admin_page(request: web.Request) -> web.Response:
        request["auth"].require("observability.read")
        return web.Response(text=_PAGE, content_type="text/html")

    app.router.add_get("/admin", admin_page)
    app.router.add_get("/admin/", admin_page)
