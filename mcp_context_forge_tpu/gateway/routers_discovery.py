"""Discovery routers: tags, cross-entity search, OpenAPI schema,
per-server well-known, metrics maintenance.

Reference: `routers/tags_router` + `routers/search` + `openapi_schema` +
`server_well_known` + `metrics_maintenance` in the main router list
(`/root/reference/mcpgateway/main.py:3575-3586`).
"""

from __future__ import annotations

import json
from typing import Any

from aiohttp import web

from .. import __version__
from ..services.base import NotFoundError

_ENTITY_SOURCES = ("tools", "resources", "prompts", "servers", "gateways",
                   "a2a_agents")


async def _all_entities(app: web.Application, teams: list[str],
                        types: list[str] | None = None
                        ) -> dict[str, list[Any]]:
    """Taggable/searchable entities keyed by type — only the requested
    ``types`` are fetched, concurrently (a narrowed /tags?entity_types=
    must not pay five unrelated DB round-trips)."""
    import asyncio

    loaders = {
        "tools": lambda: app["tool_service"].list_tools(team_ids=teams),
        "resources": lambda: app["resource_service"].list_resources(),
        "prompts": lambda: app["prompt_service"].list_prompts(),
        "servers": lambda: app["server_service"].list_servers(),
        "gateways": lambda: app["gateway_service"].list_gateways(),
        "a2a_agents": lambda: app["a2a_service"].list_agents(),
    }
    wanted = [t for t in (types or _ENTITY_SOURCES) if t in loaders]
    results = await asyncio.gather(*[loaders[t]() for t in wanted])
    return dict(zip(wanted, results))


def setup_discovery_routes(app: web.Application) -> None:
    routes = web.RouteTableDef()

    # ------------------------------------------------------------------ tags
    @routes.get("/tags")
    async def list_tags(request: web.Request) -> web.Response:
        """Aggregated tag census across entity types (reference tags
        router: names + per-type counts, optional entity_types filter)."""
        request["auth"].require("tools.read")
        wanted = request.query.get("entity_types")
        types = ([t.strip() for t in wanted.split(",") if t.strip()]
                 if wanted else list(_ENTITY_SOURCES))
        entities = await _all_entities(request.app, request["auth"].teams,
                                       types)
        census: dict[str, dict[str, Any]] = {}
        for etype in types:
            for entity in entities.get(etype, []):
                for tag in getattr(entity, "tags", None) or []:
                    stats = census.setdefault(
                        tag, {"name": tag, "total": 0,
                              "by_type": {}})
                    stats["total"] += 1
                    stats["by_type"][etype] = stats["by_type"].get(etype, 0) + 1
        return web.json_response(
            sorted(census.values(), key=lambda s: (-s["total"], s["name"])))

    @routes.get("/tags/{tag}/entities")
    async def tag_entities(request: web.Request) -> web.Response:
        request["auth"].require("tools.read")
        tag = request.match_info["tag"]
        entities = await _all_entities(request.app, request["auth"].teams)
        out = []
        for etype, items in entities.items():
            for entity in items:
                if tag in (getattr(entity, "tags", None) or []):
                    out.append({"type": etype,
                                "id": getattr(entity, "id", None),
                                "name": getattr(entity, "name", ""),
                                "description": getattr(entity, "description",
                                                       None)})
        return web.json_response({"tag": tag, "entities": out})

    # ---------------------------------------------------------------- search
    @routes.get("/search")
    async def search(request: web.Request) -> web.Response:
        """Case-insensitive substring search over name/description/tags of
        every entity type (reference routers/search.py), grouped by type.
        ``?q=`` required; ``?types=tools,prompts`` narrows; ``?limit=``
        caps per-type results."""
        request["auth"].require("tools.read")
        query = request.query.get("q", "").strip().lower()
        if not query:
            return web.json_response(
                {"detail": "query parameter 'q' is required"}, status=422)
        wanted = request.query.get("types")
        types = ([t.strip() for t in wanted.split(",") if t.strip()]
                 if wanted else list(_ENTITY_SOURCES))
        limit = max(1, min(int(request.query.get("limit", "25")), 200))
        entities = await _all_entities(request.app, request["auth"].teams,
                                       types)
        results: dict[str, list[dict[str, Any]]] = {}
        for etype in types:
            hits = []
            for entity in entities.get(etype, []):
                name = str(getattr(entity, "name", ""))
                desc = str(getattr(entity, "description", None) or "")
                tags = getattr(entity, "tags", None) or []
                haystacks = (name.lower(), desc.lower(),
                             " ".join(tags).lower())
                if any(query in hay for hay in haystacks):
                    hits.append({"id": getattr(entity, "id", None),
                                 "name": name, "description": desc or None,
                                 "tags": tags})
                    if len(hits) >= limit:
                        break
            if hits:
                results[etype] = hits
        return web.json_response({
            "query": query,
            "results": results,
            "total": sum(len(v) for v in results.values())})

    # ----------------------------------------------------------- openapi.json
    @routes.get("/openapi.json")
    async def openapi_schema(request: web.Request) -> web.Response:
        """OpenAPI 3.1 document generated from the live route table
        (reference routers/openapi_schema.py serves the FastAPI schema;
        aiohttp has none built in, so the gateway derives one)."""
        request["auth"].require("tools.read")
        paths: dict[str, dict[str, Any]] = {}
        for route in request.app.router.routes():
            method = route.method.lower()
            if method in ("head", "options", "*"):
                continue
            info = route.resource.get_info() if route.resource else {}
            path = info.get("path") or info.get("formatter")
            if not path or path.startswith("/admin/ui"):
                continue
            handler_doc = (route.handler.__doc__ or "").strip()
            op: dict[str, Any] = {
                "operationId": f"{method}_{route.handler.__name__}",
                "summary": handler_doc.split("\n", 1)[0][:120]
                or route.handler.__name__,
                "responses": {"200": {"description": "Success"}},
            }
            params = [seg[1:-1] for seg in path.split("/")
                      if seg.startswith("{") and seg.endswith("}")]
            if params:
                op["parameters"] = [{"name": p, "in": "path",
                                     "required": True,
                                     "schema": {"type": "string"}}
                                    for p in params]
            paths.setdefault(path, {})[method] = op
        from ..schemas import (GatewayRead, PromptRead, ResourceRead,
                               ServerRead, ToolRead)

        components = {
            name: model.model_json_schema(ref_template=
                                          "#/components/schemas/{model}")
            for name, model in (("ToolRead", ToolRead),
                                ("ResourceRead", ResourceRead),
                                ("PromptRead", PromptRead),
                                ("ServerRead", ServerRead),
                                ("GatewayRead", GatewayRead))}
        # hoist nested $defs so every $ref resolves at components/schemas
        hoisted: dict[str, Any] = {}
        for schema in components.values():
            for def_name, def_schema in schema.pop("$defs", {}).items():
                hoisted.setdefault(def_name, def_schema)
        components.update(hoisted)
        return web.json_response({
            "openapi": "3.1.0",
            "info": {"title": request.app["ctx"].settings.app_name,
                     "version": __version__},
            "paths": dict(sorted(paths.items())),
            "components": {"schemas": components},
        })

    # ------------------------------------------- per-server well-known (public)
    @routes.get("/servers/{server_id}/.well-known/mcp")
    async def server_well_known(request: web.Request) -> web.Response:
        """Public discovery metadata for ONE virtual server (reference
        routers/server_well_known.py): name + protocol + endpoint, no
        catalog contents (those stay behind auth)."""
        try:
            server = await request.app["server_service"].get_server(
                request.match_info["server_id"])
        except NotFoundError:
            return web.json_response({"detail": "Server not found"},
                                     status=404)
        settings = request.app["ctx"].settings
        base = settings.app_domain.rstrip("/")
        return web.json_response({
            "name": server.name,
            "description": server.description,
            "protocol_version": settings.protocol_version,
            "endpoint": f"{base}/servers/{server.id}/mcp",
            "transport": ["streamable-http"],
        })

    # --------------------------------------------- well-known files (public)
    @routes.get("/robots.txt")
    async def robots_txt(request: web.Request) -> web.Response:
        """reference well_known_robots_txt (crawler exclusion by default)."""
        settings = request.app["ctx"].settings
        return web.Response(
            text=settings.well_known_robots_txt, content_type="text/plain",
            headers={"cache-control":
                     f"max-age={settings.well_known_cache_max_age}"})

    @routes.get("/.well-known/{file}")
    async def well_known_file(request: web.Request) -> web.Response:
        """security.txt + operator-defined custom well-known files
        (reference routers/well_known.py; JSON map in settings)."""
        settings = request.app["ctx"].settings
        name = request.match_info["file"]
        content: str | None = None
        if name == "security.txt" and settings.well_known_security_txt:
            content = settings.well_known_security_txt
        elif settings.well_known_custom_files:
            try:
                custom = json.loads(settings.well_known_custom_files)
            except json.JSONDecodeError:
                custom = {}
            value = custom.get(name)
            content = value if isinstance(value, str) else None
        if content is None:
            return web.json_response({"detail": "Not found"}, status=404)
        return web.Response(
            text=content, content_type="text/plain",
            headers={"cache-control":
                     f"max-age={settings.well_known_cache_max_age}"})

    # ------------------------------------------------------ metrics maintenance
    @routes.post("/metrics/prune")
    async def prune_metrics(request: web.Request) -> web.Response:
        """Retention cleanup now (reference metrics_maintenance router):
        raw metric rows past retention are deleted; rollups keep history."""
        request["auth"].require("admin.all")
        pruned = await request.app["metrics_maintenance"].cleanup()
        return web.json_response({"pruned": pruned})

    @routes.post("/metrics/reset")
    async def reset_metrics(request: web.Request) -> web.Response:
        """Drop ALL raw metric rows + rollups (reference /metrics DELETE)."""
        request["auth"].require("admin.all")
        db = request.app["ctx"].db
        buffer = request.app["ctx"].extras.get("metrics_buffer")
        if buffer is not None:
            await buffer.flush()  # buffered rows must die with the reset
        raw = await db.fetchone("SELECT COUNT(*) AS n FROM tool_metrics")
        await db.execute("DELETE FROM tool_metrics")
        await db.execute("DELETE FROM metrics_rollups")
        return web.json_response({"deleted_raw": int(raw["n"]) if raw else 0})

    app.add_routes(routes)
