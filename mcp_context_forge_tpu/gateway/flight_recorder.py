"""Gateway data-plane flight recorder + event-loop health.

The serving engine is legible (step attribution, live roofline,
``/admin/engine/steps``); the gateway tier in front of it was not — the
r05 bench tail shows ``http.request: 3786 ms`` warnings with no
breakdown, and gateway RPS has been flat at ~900–1200 req/s across five
rounds while the engine got 4–60× faster. This module is the gateway's
instrument panel:

- :class:`FlightRecorder` — a bounded per-worker ring of completed
  requests (recent window + slowest-N retained by duration) with the
  phase vector each request's :class:`~..observability.phases.PhaseClock`
  accumulated, served at ``GET /admin/gateway/requests`` and mirrored
  into ``mcpforge_gw_request_phase_seconds{route,phase}``;
- an in-flight registry, so the loop-lag sampler can name the probable
  culprit request (longest-running in-flight) when the loop stalls;
- :class:`LoopLagSampler` — the runtime complement of mcpforge-lint's
  static ``async-blocking-call`` rule: a scheduled-callback delta
  sampler that measures how late the event loop runs a timer that asked
  for ``interval`` seconds. Sustained lag means a callback is blocking
  the loop (sync I/O, a long JSON encode, GC) — exactly the class of
  bug the linter catches statically, now measured in production;
- :func:`queue_state` — engine/pool admission depth and saturation, the
  pool→HTTP backpressure signal the middleware surfaces as
  ``X-Queue-Depth`` / ``Retry-After`` response headers.

Everything here runs on the gateway's asyncio loop; nothing is touched
from engine dispatch threads.
"""

from __future__ import annotations

import asyncio
import bisect
import itertools
import logging
import math
import time
from collections import deque
from typing import Any

from ..observability.logging import trace_extra

logger = logging.getLogger(__name__)


class FlightRecorder:
    """Bounded request-attribution rings + in-flight registry.

    ``recent`` keeps the last ``ring_size`` completed requests in
    arrival order; ``slowest`` retains the ``slowest_size`` worst by
    wall duration across the worker's lifetime (an operator chasing the
    p99.9 tail needs the outliers to SURVIVE churn — a recency ring
    alone forgets them within seconds at 1k rps). Both are plain lists
    of dicts, mutated only on the event loop."""

    def __init__(self, metrics: Any = None, ring_size: int = 256,
                 slowest_size: int = 32,
                 slow_request_s: float = 1.0,
                 worker: str = "") -> None:
        self.metrics = metrics
        # multi-worker attribution (docs/scaleout.md): every row carries
        # the serving worker's id so a merged fleet view can say WHICH
        # process served the outlier
        self.worker = worker
        self.ring_size = max(1, int(ring_size))
        self.slowest_size = max(1, int(slowest_size))
        self.slow_request_s = max(0.0, float(slow_request_s))
        self.recent: deque[dict[str, Any]] = deque(maxlen=self.ring_size)
        self._slowest: list[tuple[float, int, dict[str, Any]]] = []
        self._seq = itertools.count()
        self.recorded = 0
        self.slow_requests = 0
        # request_id -> {started, path, trace} of requests mid-handling
        self.inflight: dict[int, dict[str, Any]] = {}

    # ------------------------------------------------------------- in-flight

    def start_request(self, path: str,
                      trace: tuple[str, str] | None) -> int:
        rid = next(self._seq)
        self.inflight[rid] = {"started": time.monotonic(), "path": path,
                              "trace": trace}
        return rid

    def finish_request(self, rid: int) -> None:
        self.inflight.pop(rid, None)

    def longest_inflight(self) -> dict[str, Any] | None:
        """The oldest request still being handled — the loop-lag
        sampler's best guess at "who blocked the loop"."""
        if not self.inflight:
            return None
        entry = min(self.inflight.values(), key=lambda e: e["started"])
        return {"path": entry["path"], "trace": entry["trace"],
                "age_s": round(time.monotonic() - entry["started"], 3)}

    # ------------------------------------------------------------- recording

    def record(self, *, method: str, path: str, route: str, status: int,
               duration_s: float, phases_ms: dict[str, float],
               trace_id: str | None = None, span_id: str | None = None,
               correlation_id: str | None = None,
               tenant: str | None = None,
               error: str | None = None,
               client_disconnected: bool = False) -> dict[str, Any]:
        """Append one completed request to the rings + Prometheus."""
        entry = {
            "ts": time.time(),
            "method": method,
            "path": path,
            "route": route,
            "status": status,
            "duration_ms": round(duration_s * 1e3, 3),
            "phases_ms": phases_ms,
        }
        if self.worker:
            entry["worker"] = self.worker
        if tenant:
            # rows keep the EXACT tenant (bounded ring, no cardinality
            # concern); only the Prometheus label below is clamped
            entry["tenant"] = tenant
        if trace_id:
            entry["trace_id"] = trace_id
            if span_id:
                entry["span_id"] = span_id
        if correlation_id:
            entry["correlation_id"] = correlation_id
        if error:
            entry["error"] = error
        if client_disconnected:
            entry["client_disconnected"] = True
        self.recorded += 1
        self.recent.append(entry)
        # slowest-N: keep sorted ascending by duration, evict the fastest
        key = (entry["duration_ms"], next(self._seq))
        if (len(self._slowest) < self.slowest_size
                or key[0] > self._slowest[0][0]):
            bisect.insort(self._slowest, (key[0], key[1], entry))
            if len(self._slowest) > self.slowest_size:
                self._slowest.pop(0)
        metrics = self.metrics
        if metrics is not None:
            tenant_label = metrics.tenant_clamp.label(tenant or "anonymous")
            for phase_name, ms in phases_ms.items():
                metrics.gw_request_phase.labels(
                    route=route, phase=phase_name,
                    tenant=tenant_label).observe(ms / 1e3)
        # strictly-greater, matching PerformanceTracker.record's slow
        # branch — the two consumers of gw_slow_request_s must agree on
        # one bar (the walls differ by the recorder's own µs overhead;
        # the operator at least must not add a systematic disagreement)
        slow = self.slow_request_s and duration_s > self.slow_request_s
        if slow:
            self.slow_requests += 1
            if metrics is not None:
                metrics.gw_slow_requests.labels(route=route).inc()
            # the r05 tail's "http.request: 3786 ms" line, upgraded: the
            # phase vector says WHERE the milliseconds went, and the
            # explicit trace ctx joins the line to its OTel trace even
            # from producers off the contextvar chain
            logger.warning(
                "slow request %s %s -> %s: %.1f ms (threshold %.1f ms) "
                "phases=%s", method, path, status, duration_s * 1e3,
                self.slow_request_s * 1e3, phases_ms,
                extra=trace_extra((trace_id, span_id or "")
                                  if trace_id else None))
        return entry

    # ------------------------------------------------------------- reporting

    def slowest(self) -> list[dict[str, Any]]:
        """Worst-duration-first."""
        return [entry for _, _, entry in reversed(self._slowest)]

    def find_trace(self, trace_id: str) -> dict[str, Any] | None:
        """The recorder row for one trace id (slowest ring first — the
        waterfall endpoint's gateway-side join; a row present in both
        rings is the same dict object)."""
        for entry in self.slowest():
            if entry.get("trace_id") == trace_id:
                return entry
        for entry in reversed(self.recent):
            if entry.get("trace_id") == trace_id:
                return entry
        return None

    def snapshot(self, limit: int = 64,
                 tenant: str | None = None) -> dict[str, Any]:
        """Ring contents; ``tenant`` filters both rings to one tenant's
        rows (exact match on the row's unclamped tenant)."""
        limit = max(1, limit)
        slowest = self.slowest()
        recent = list(self.recent)[::-1]  # newest first
        if tenant:
            slowest = [r for r in slowest if r.get("tenant") == tenant]
            recent = [r for r in recent if r.get("tenant") == tenant]
        out = {
            "worker": self.worker or None,
            "recorded": self.recorded,
            "slow_requests": self.slow_requests,
            "slow_request_ms": round(self.slow_request_s * 1e3, 1),
            "ring_size": self.ring_size,
            "inflight": len(self.inflight),
            "slowest": slowest[:limit],
            "recent": recent[:limit],
        }
        if tenant:
            out["tenant"] = tenant
        return out


class LoopLagSampler:
    """Asyncio event-loop health: scheduled-callback delta sampling.

    Each tick asks the loop for ``interval`` seconds of sleep and
    measures how much LATER it actually ran; that delta is the time the
    loop spent unable to service timers — i.e. blocked in somebody's
    callback. Observed into ``mcpforge_gw_loop_lag_seconds`` and kept as
    a max-lag high-water mark; a tick beyond ``warn_s`` logs a
    long-callback warning naming the longest in-flight request (the
    probable culprit) with its trace ids, so the line joins the same
    OTel trace the flight-recorder row is in."""

    def __init__(self, metrics: Any = None, interval_s: float = 0.25,
                 warn_s: float = 0.25,
                 recorder: FlightRecorder | None = None) -> None:
        self.metrics = metrics
        self.interval_s = max(0.01, float(interval_s))
        self.warn_s = max(0.0, float(warn_s))
        self.recorder = recorder
        # optional live-signal bus (observability/signals.py): every
        # sample is also pushed as gw.loop_lag_ms so the serving
        # controller sees gateway loop health at its own tick
        self.signals = None
        self.samples = 0
        self.long_callbacks = 0
        self.max_lag_s = 0.0
        self.last_lag_s = 0.0
        self._task: asyncio.Task | None = None
        self._warn_bucket = 0.0  # rate limit: at most 1 warn / 5 s

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="gw-loop-lag-sampler")

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            before = loop.time()
            await asyncio.sleep(self.interval_s)
            lag = max(0.0, loop.time() - before - self.interval_s)
            self._observe(lag)

    def _observe(self, lag: float) -> None:
        self.samples += 1
        self.last_lag_s = lag
        self.max_lag_s = max(self.max_lag_s, lag)
        if self.metrics is not None:
            self.metrics.gw_loop_lag.observe(lag)
        if self.signals is not None:
            self.signals.publish("gw.loop_lag_ms", lag * 1e3)  # lint: allow[signal-name-conformance] dashboard-only export via the /signals snapshot; no steering consumer
        if self.warn_s and lag >= self.warn_s:
            self.long_callbacks += 1
            now = time.monotonic()
            if now >= self._warn_bucket:
                self._warn_bucket = now + 5.0
                culprit = (self.recorder.longest_inflight()
                           if self.recorder is not None else None)
                logger.warning(
                    "event loop lagged %.1f ms (bar %.1f ms) — a callback "
                    "blocked the loop%s", lag * 1e3, self.warn_s * 1e3,
                    (f"; longest in-flight: {culprit['path']} "
                     f"({culprit['age_s']} s)" if culprit else ""),
                    extra=trace_extra(culprit["trace"] if culprit else None))

    def snapshot(self) -> dict[str, Any]:
        return {
            "interval_s": self.interval_s,
            "warn_ms": round(self.warn_s * 1e3, 1),
            "samples": self.samples,
            "last_lag_ms": round(self.last_lag_s * 1e3, 3),
            "max_lag_ms": round(self.max_lag_s * 1e3, 3),
            "long_callbacks": self.long_callbacks,
        }


def compute_queue_state(pool: Any, engine: Any) -> dict[str, Any] | None:
    """Depth/capacity/saturation from a replica pool or single engine —
    the pure half of ``queue_state`` (no app, no metrics side effect),
    shared with the shared-engine-plane's ``pool.queue_state`` RPC so
    every worker reports the SAME arithmetic."""
    no_replicas = False
    if pool is not None:
        ready = [r for r in pool.replicas if r.state == "ready"]
        depth = sum(r.engine.stats.queue_depth for r in ready)
        capacity = sum(r.engine.config.max_queue for r in ready)
        no_replicas = not ready  # every replica dead/draining
    elif engine is not None:
        depth = engine.stats.queue_depth
        capacity = engine.config.max_queue
    else:
        return None
    if no_replicas:
        saturation = 1.0  # nothing routable: saturated by definition
    elif capacity > 0:
        saturation = min(1.0, depth / capacity)
    else:
        # max_queue<=0 means an UNBOUNDED admission queue (queue.Queue
        # maxsize semantics) — never "full", not permanently saturated
        saturation = 0.0
    return {"depth": int(depth), "capacity": int(capacity),
            "saturation": round(saturation, 4)}


def queue_state(app: Any) -> dict[str, Any] | None:
    """Engine/pool admission state as the HTTP tier's backpressure
    signal: queued work summed over ROUTABLE replicas, capacity from the
    per-engine admission bound, saturation = depth/capacity. None when
    no engine is wired (nothing to backpressure against). Every
    computation refreshes the ``mcpforge_gw_engine_saturation`` gauge —
    here rather than in the header-writing branch, so SSE responses
    (headers set pre-prepare) and header-disabled deployments still
    feed the metric.

    Shared-engine-plane topology (tpu_local/pool_rpc.py): only the
    leader-elected owner has local engine objects; every other worker
    reads the LEADER's admission state through the plane's short-TTL
    bus-RPC cache — a non-owner must never report a worker-local zero
    while the owner's queue is drowning (the in-process bench masked
    this; the real-process arm exposed it)."""
    state = compute_queue_state(app.get("tpu_engine_pool"),
                                app.get("tpu_engine"))
    if state is None:
        plane = app.get("engine_plane")
        if plane is not None:
            state = plane.queue_state_sync()
    if state is None:
        return None
    ctx = app.get("ctx")
    metrics = getattr(ctx, "metrics", None) if ctx is not None else None
    if metrics is not None:
        metrics.gw_engine_saturation.set(state["saturation"])
    return state


def retry_after_s(saturation: float, advisory_at: float = 0.8) -> int:
    """Suggested client backoff once saturation crosses the advisory
    bar: scales 1 s at the bar → 8 s at full saturation (a fixed
    punitive value would just synchronize retries)."""
    at = min(advisory_at, 1.0 - 1e-6)  # a bar AT 1.0 still ramps
    frac = max(0.0, saturation - at) / (1.0 - at)
    return max(1, min(8, math.ceil(frac * 8.0)))


def backpressure_headers(state: dict[str, Any] | None,
                         settings: Any) -> dict[str, str]:
    """THE header contract for engine-admission backpressure, shared by
    the unary middleware path and the SSE pre-prepare path (a change to
    the contract must land in both at once)."""
    if state is None:
        return {}
    headers = {"X-Queue-Depth": str(state["depth"])}
    advisory_at = settings.gw_backpressure_retry_after_at
    if state["saturation"] >= advisory_at:
        headers["Retry-After"] = str(
            retry_after_s(state["saturation"], advisory_at))
    return headers
