"""HTTP gateway: aiohttp app, middleware, JSON-RPC dispatch, transports."""
