"""REST API routers (reference: mcpgateway/main.py protocol routers +
mcpgateway/routers/ — 28 routers). Table-driven CRUD over the services plus
auth, metrics, admin observability endpoints."""

from __future__ import annotations

import json
from typing import Any

from aiohttp import web
from pydantic import ValidationError

from ..observability.logging import ring_buffer
from ..schemas import (
    A2AAgentCreate,
    GatewayCreate,
    GatewayUpdate,
    PromptCreate,
    PromptUpdate,
    ResourceCreate,
    ResourceUpdate,
    ServerCreate,
    ServerUpdate,
    ToolCreate,
    ToolUpdate,
)
from ..services.auth_service import AuthError, PermissionDenied
from ..services.base import NotFoundError, ValidationFailure
from .pagination import paginate


def _dump(model) -> Any:
    if isinstance(model, list):
        return [_dump(m) for m in model]
    return json.loads(model.model_dump_json())


async def _cached_list(request: web.Request, entity: str, key: str, loader):
    """List-endpoint TTL cache (reference registry_cache_* family); the
    loader runs on miss and the result is bus-invalidated on change."""
    cache = request.app.get("registry_cache")
    if cache is None:
        return await loader()
    items = cache.get(entity, key)
    if items is None:
        # capture the generation BEFORE loading: an invalidation that
        # fires while the db read runs makes this snapshot stale, and
        # put() must then drop it instead of caching pre-write state
        generation = cache.generation(entity)
        items = await loader()
        cache.put(entity, key, items, generation)
    return items


async def _body(request: web.Request, schema):
    try:
        model = schema.model_validate(await request.json())
    except json.JSONDecodeError as exc:
        raise ValidationFailure(f"Invalid JSON body: {exc}") from exc
    except ValidationError as exc:
        raise ValidationFailure(str(exc)) from exc
    _check_field_limits(model, request.app["ctx"].settings)
    return model


def _check_field_limits(model, settings) -> None:
    """Central create/update field limits (reference validation_* family,
    `/root/reference/mcpgateway/config.py` validation_max_name_length ..
    validation_max_tag_length): one enforcement point for every entity
    schema instead of per-model validators that can drift."""
    checks = (("name", settings.validation_max_name_length),
              ("description", settings.validation_max_description_length),
              ("url", settings.validation_max_url_length))
    for field_name, limit in checks:
        value = getattr(model, field_name, None)
        if isinstance(value, str) and limit and len(value) > limit:
            raise ValidationFailure(
                f"{field_name} exceeds {limit} characters")
    tags = getattr(model, "tags", None)
    if tags:
        if settings.validation_max_tags and \
                len(tags) > settings.validation_max_tags:
            raise ValidationFailure(
                f"More than {settings.validation_max_tags} tags")
        for tag in tags:
            if settings.validation_max_tag_length and \
                    len(tag) > settings.validation_max_tag_length:
                raise ValidationFailure(
                    f"Tag exceeds {settings.validation_max_tag_length}"
                    " characters")


def setup_routes(app: web.Application) -> None:
    routes = web.RouteTableDef()

    # ----------------------------------------------------------- health/meta
    @routes.get("/health")
    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "healthy"})

    @routes.get("/ready")
    async def ready(request: web.Request) -> web.Response:
        try:
            ctx = request.app["ctx"]
            await ctx.db.execute("SELECT 1")
            elector = ctx.extras.get("leader_elector")
            return web.json_response({
                "status": "ready", "worker_id": ctx.worker_id,
                "leader": bool(elector and elector.is_leader)})
        except Exception as exc:
            return web.json_response({"status": "not ready", "detail": str(exc)}, status=503)

    @routes.get("/.well-known/mcp")
    async def well_known(request: web.Request) -> web.Response:
        settings = request.app["ctx"].settings
        return web.json_response({
            "name": settings.app_name,
            "protocolVersion": settings.protocol_version,
            "endpoints": {"mcp": "/mcp", "rpc": "/rpc"},
        })

    @routes.get("/version")
    async def version(request: web.Request) -> web.Response:
        from .. import __version__
        return web.json_response({"version": __version__})

    # ----------------------------------------------------------------- auth
    @routes.post("/auth/login")
    async def login(request: web.Request) -> web.Response:
        body = await request.json()
        auth_service = request.app["auth_service"]
        email = body.get("email") or body.get("username") or ""
        password = body.get("password") or ""
        if not await auth_service.verify_password(email, password):
            raise AuthError("Invalid credentials")
        token = auth_service.issue_jwt(email)
        return web.json_response({"access_token": token, "token_type": "bearer"})

    @routes.post("/auth/tokens")
    async def create_token(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("tokens.manage")
        body = await request.json()
        token, token_id = await request.app["auth_service"].create_api_token(
            auth.user, body.get("name", "api-token"),
            server_id=body.get("server_id"),
            permissions=body.get("permissions"),
            expires_minutes=body.get("expires_minutes"), grantor=auth)
        return web.json_response({"token": token, "id": token_id}, status=201)

    @routes.get("/auth/tokens")
    async def list_tokens(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("tokens.manage")
        return web.json_response(await request.app["auth_service"].list_api_tokens(auth.user))

    @routes.delete("/auth/tokens/{token_id}")
    async def revoke_token(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("tokens.manage")
        await request.app["auth_service"].revoke_token(request.match_info["token_id"])
        return web.Response(status=204)

    @routes.get("/auth/tokens/{token_id}/usage")
    async def token_usage(request: web.Request) -> web.Response:
        """Usage trail of one API token (reference TokenUsageLog +
        token_usage_middleware): endpoint, status, latency, client,
        blocked attempts — owner or admin only."""
        auth = request["auth"]
        auth.require("tokens.manage")
        row = await request.app["ctx"].db.fetchone(
            "SELECT jti, user_email FROM api_tokens WHERE id=?",
            (request.match_info["token_id"],))
        if row is None:
            raise NotFoundError("Token not found")
        if row["user_email"] != auth.user and not auth.can("admin.all"):
            raise PermissionDenied("Not your token")
        logs = await request.app["ctx"].db.fetchall(
            "SELECT ts, method, path, status, response_ms, client_ip,"
            " user_agent, blocked, block_reason FROM token_usage_logs"
            " WHERE token_jti=? ORDER BY ts DESC LIMIT 500", (row["jti"],))
        return web.json_response({"token_id": request.match_info["token_id"],
                                  "entries": logs})

    @routes.post("/auth/password")
    async def change_password(request: web.Request) -> web.Response:
        auth = request["auth"]
        body = await request.json()
        await request.app["auth_service"].change_password(
            auth.user, body.get("old_password", ""),
            body.get("new_password", ""))
        return web.json_response({"status": "changed"})

    @routes.post("/auth/password/reset-request")
    async def password_reset_request(request: web.Request) -> web.Response:
        """Start a reset: always 202 with the same body and a minimum
        response time, whether or not the account exists (reference
        password_reset_min_response_ms user-enumeration guard)."""
        import asyncio as _asyncio
        import time as _time
        settings = request.app["ctx"].settings
        if not settings.password_reset_enabled:
            raise NotFoundError("password reset is disabled")
        started = _time.monotonic()

        async def _floor() -> None:
            # the enumeration guard must hold on EVERY exit path — a
            # malformed-body fast 400 vs a padded 202 would itself be a
            # timing side channel on the parse branch
            remaining = (settings.password_reset_min_response_ms / 1e3
                         - (_time.monotonic() - started))
            if remaining > 0:
                await _asyncio.sleep(remaining)

        try:
            body = await request.json()
        except Exception:
            # malformed JSON is a client error (400), not a 500
            await _floor()
            return web.json_response({"detail": "Invalid JSON body"},
                                     status=400)
        if not isinstance(body, dict):
            await _floor()
            return web.json_response({"detail": "body must be a JSON object"},
                                     status=400)
        email = str(body.get("email", "")).strip().lower()
        if email:
            token = await request.app["auth_service"].request_password_reset(
                email)
            if token:
                email_service = request.app.get("email_service")
                if email_service is not None:
                    # background send: awaiting SMTP inline would make
                    # existing accounts answer SLOWER than unknown ones
                    # (up to smtp_timeout_seconds) — the floor below only
                    # pads short responses, it cannot cap long ones
                    tasks = request.app["_token_usage_tasks"]
                    task = _asyncio.get_running_loop().create_task(
                        email_service.send_password_reset(
                            email, token,
                            settings.password_reset_token_expiry_minutes))
                    tasks.add(task)
                    task.add_done_callback(tasks.discard)
        await _floor()
        return web.json_response(
            {"status": "accepted",
             "detail": "If the account exists, a reset link was sent."},
            status=202)

    @routes.get("/auth/password/reset")
    async def password_reset_page(request: web.Request) -> web.Response:
        """The page the emailed reset link lands on: a minimal form that
        POSTs the token + new password back to this path. Without it the
        link in the mail would hit a POST-only JSON endpoint (405)."""
        if not request.app["ctx"].settings.password_reset_enabled:
            raise NotFoundError("password reset is disabled")
        # the token is NEVER interpolated into the page (reflected-XSS
        # surface); the script reads it from location.search client-side
        return web.Response(content_type="text/html", text="""<!doctype html>
<title>Password reset</title>
<h3>Choose a new password</h3>
<form id="f"><input type="password" id="p" placeholder="new password"
  autocomplete="new-password" minlength="8" required>
<button>Reset</button></form><p id="out"></p>
<script>
document.getElementById("f").onsubmit = async (e) => {
  e.preventDefault();
  const token = new URLSearchParams(location.search).get("token") || "";
  const r = await fetch("/auth/password/reset", {method: "POST",
    headers: {"content-type": "application/json"},
    body: JSON.stringify({token, new_password:
      document.getElementById("p").value})});
  document.getElementById("out").textContent = r.ok
    ? "Password reset. You can sign in now."
    : "Reset failed: " + (await r.json()).detail;
};
</script>""")

    @routes.post("/auth/password/reset")
    async def password_reset(request: web.Request) -> web.Response:
        settings = request.app["ctx"].settings
        if not settings.password_reset_enabled:
            raise NotFoundError("password reset is disabled")
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"detail": "Invalid JSON body"},
                                     status=400)
        if not isinstance(body, dict):
            return web.json_response({"detail": "body must be a JSON object"},
                                     status=400)
        email = await request.app["auth_service"].reset_password(
            str(body.get("token", "")), str(body.get("new_password", "")))
        email_service = request.app.get("email_service")
        if email_service is not None:
            # background: the just-reset user must not wait out a slow MX
            import asyncio as _asyncio
            tasks = request.app["_token_usage_tasks"]
            task = _asyncio.get_running_loop().create_task(
                email_service.send_password_reset_confirmation(email))
            tasks.add(task)
            task.add_done_callback(tasks.discard)
        audit = request.app.get("audit_service")
        if audit is not None:
            await audit.record(email, "auth.password_reset")
        return web.json_response({"status": "reset"})

    # ----------------------------------------------------- admin user CRUD
    @routes.post("/admin/users")
    async def create_user(request: web.Request) -> web.Response:
        auth = request["auth"]
        auth.require("admin.all")
        body = await request.json()
        await request.app["auth_service"].create_user(
            body.get("email", ""), body.get("password", ""),
            full_name=body.get("full_name", ""),
            is_admin=bool(body.get("is_admin")), enforce_policy=True,
            require_password_change=bool(body.get("require_password_change")))
        return web.json_response({"email": body.get("email")}, status=201)

    @routes.get("/admin/config")
    async def effective_config(request: web.Request) -> web.Response:
        """The EFFECTIVE settings the worker is running with, secrets
        redacted (reference admin exposes its configuration view the
        same way) — the operator's 'what is this gateway actually
        configured to do' answer without shell access."""
        request["auth"].require("admin.all")
        from ..utils.redact import redact_settings
        return web.json_response(
            redact_settings(request.app["ctx"].settings))

    @routes.post("/admin/users/{email}/require-password-change")
    async def require_password_change(request: web.Request) -> web.Response:
        """Flag a user for mandatory rotation (reference
        password_change_enforcement.py); cleared by /auth/password."""
        request["auth"].require("admin.all")
        await request.app["auth_service"].set_password_change_required(
            request.match_info["email"], True)
        return web.json_response({"email": request.match_info["email"],
                                  "password_change_required": True})

    @routes.get("/admin/users")
    async def list_users(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        rows = await request.app["ctx"].db.fetchall(
            "SELECT email, full_name, is_admin, is_active, auth_provider,"
            " last_login, created_at FROM users ORDER BY email")
        return paginate(request, rows, lambda page: list(page),
                        key=lambda row: row["email"])

    @routes.post("/admin/users/{email}/toggle")
    async def toggle_user(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        email = request.match_info["email"]
        from ..services.base import now
        await request.app["ctx"].db.execute(
            "UPDATE users SET is_active=1-is_active, updated_at=? WHERE email=?",
            (now(), email))
        request.app["auth_service"].invalidate_user(email)
        row = await request.app["ctx"].db.fetchone(
            "SELECT email, is_active FROM users WHERE email=?", (email,))
        if row is None:
            raise NotFoundError(f"User {email} not found")
        return web.json_response(row)

    # ---------------------------------------------------------------- tools
    @routes.get("/tools")
    async def list_tools(request: web.Request) -> web.Response:
        request["auth"].require("tools.read")
        include_inactive = request.query.get("include_inactive") == "true"
        # the tool list is TEAM-scoped: the cache key must carry the
        # viewer's team set or private entries would leak across users
        teams = ",".join(sorted(request["auth"].teams or []))
        tools = await _cached_list(
            request, "tools", f"{include_inactive}:{teams}",
            lambda: request.app["tool_service"].list_tools(
                include_inactive=include_inactive,
                team_ids=request["auth"].teams))
        return paginate(request, tools, _dump)

    @routes.post("/tools")
    async def create_tool(request: web.Request) -> web.Response:
        request["auth"].require("tools.create")
        tool = await _body(request, ToolCreate)
        if not tool.owner_email:
            tool.owner_email = request["auth"].user
        created = await request.app["tool_service"].register_tool(tool)
        return web.json_response(_dump(created), status=201)

    @routes.get("/tools/{tool_id}")
    async def get_tool(request: web.Request) -> web.Response:
        request["auth"].require("tools.read")
        tool = await request.app["tool_service"].get_tool(request.match_info["tool_id"])
        return web.json_response(_dump(tool))

    @routes.put("/tools/{tool_id}")
    async def update_tool(request: web.Request) -> web.Response:
        request["auth"].require("tools.update")
        update = await _body(request, ToolUpdate)
        tool = await request.app["tool_service"].update_tool(
            request.match_info["tool_id"], update)
        return web.json_response(_dump(tool))

    @routes.delete("/tools/{tool_id}")
    async def delete_tool(request: web.Request) -> web.Response:
        request["auth"].require("tools.delete")
        await request.app["tool_service"].delete_tool(request.match_info["tool_id"])
        return web.Response(status=204)

    @routes.post("/tools/{tool_id}/toggle")
    async def toggle_tool(request: web.Request) -> web.Response:
        request["auth"].require("tools.update")
        body = {}
        if request.can_read_body and (await request.read()):
            # malformed JSON must 422, not silently select flip mode — a
            # client that MEANT {"enabled": false} must not re-enable
            body = json.loads(await request.text())
        tool_id = request.match_info["tool_id"]
        if "enabled" in body:
            enabled = bool(body["enabled"])
        else:  # bare POST (admin UI): flip the current state
            current = await request.app["tool_service"].get_tool(tool_id)
            enabled = not current.enabled
        tool = await request.app["tool_service"].toggle_tool(tool_id, enabled)
        return web.json_response(_dump(tool))

    # -------------------------------------------------------------- gateways
    @routes.get("/gateways")
    async def list_gateways(request: web.Request) -> web.Response:
        request["auth"].require("gateways.read")
        include_inactive = request.query.get("include_inactive") == "true"
        gws = await _cached_list(
            request, "gateways", str(include_inactive),
            lambda: request.app["gateway_service"].list_gateways(
                include_inactive))
        return paginate(request, gws, _dump)

    @routes.post("/gateways")
    async def register_gateway(request: web.Request) -> web.Response:
        request["auth"].require("gateways.create")
        gw = await _body(request, GatewayCreate)
        created = await request.app["gateway_service"].register_gateway(gw)
        return web.json_response(_dump(created), status=201)

    @routes.post("/gateways/test")
    async def test_gateway(request: web.Request) -> web.Response:
        """Registration-wizard dry run: probe a peer before persisting
        it (reference admin gateway connectivity test)."""
        request["auth"].require("gateways.create")
        body = await request.json()
        result = await request.app["gateway_service"].test_gateway(
            str(body.get("url", "")),
            transport=str(body.get("transport") or "streamablehttp"),
            auth_type=body.get("auth_type"),
            auth_value=body.get("auth_value"))
        return web.json_response(result)

    @routes.get("/gateways/{gateway_id}")
    async def get_gateway(request: web.Request) -> web.Response:
        request["auth"].require("gateways.read")
        gw = await request.app["gateway_service"].get_gateway(request.match_info["gateway_id"])
        return web.json_response(_dump(gw))

    @routes.put("/gateways/{gateway_id}")
    async def update_gateway(request: web.Request) -> web.Response:
        request["auth"].require("gateways.update")
        update = await _body(request, GatewayUpdate)
        gw = await request.app["gateway_service"].update_gateway(
            request.match_info["gateway_id"], update)
        return web.json_response(_dump(gw))

    @routes.delete("/gateways/{gateway_id}")
    async def delete_gateway(request: web.Request) -> web.Response:
        request["auth"].require("gateways.delete")
        await request.app["gateway_service"].delete_gateway(request.match_info["gateway_id"])
        return web.Response(status=204)

    @routes.post("/gateways/{gateway_id}/refresh")
    async def refresh_gateway(request: web.Request) -> web.Response:
        request["auth"].require("gateways.update")
        gw = await request.app["gateway_service"].refresh_gateway(
            request.match_info["gateway_id"])
        return web.json_response(_dump(gw))

    # ------------------------------------------------------------- resources
    @routes.get("/resources")
    async def list_resources(request: web.Request) -> web.Response:
        request["auth"].require("resources.read")
        include_inactive = request.query.get("include_inactive") == "true"
        res = await _cached_list(
            request, "resources", str(include_inactive),
            lambda: request.app["resource_service"].list_resources(
                include_inactive))
        return paginate(request, res, _dump)

    @routes.post("/resources")
    async def create_resource(request: web.Request) -> web.Response:
        request["auth"].require("resources.create")
        res = await _body(request, ResourceCreate)
        created = await request.app["resource_service"].register_resource(res)
        return web.json_response(_dump(created), status=201)

    @routes.put("/resources/{resource_id}")
    async def update_resource(request: web.Request) -> web.Response:
        request["auth"].require("resources.update")
        update = await _body(request, ResourceUpdate)
        res = await request.app["resource_service"].update_resource(
            request.match_info["resource_id"], update)
        return web.json_response(_dump(res))

    @routes.delete("/resources/{resource_id}")
    async def delete_resource(request: web.Request) -> web.Response:
        request["auth"].require("resources.delete")
        await request.app["resource_service"].delete_resource(
            request.match_info["resource_id"])
        return web.Response(status=204)

    @routes.post("/resources/read")
    async def read_resource(request: web.Request) -> web.Response:
        request["auth"].require("resources.read")
        body = await request.json()
        result = await request.app["resource_service"].read_resource(body.get("uri", ""))
        return web.json_response(result)

    # --------------------------------------------------------------- prompts
    @routes.get("/prompts")
    async def list_prompts(request: web.Request) -> web.Response:
        request["auth"].require("prompts.read")
        include_inactive = request.query.get("include_inactive") == "true"
        prompts = await _cached_list(
            request, "prompts", str(include_inactive),
            lambda: request.app["prompt_service"].list_prompts(
                include_inactive))
        return paginate(request, prompts, _dump)

    @routes.post("/prompts")
    async def create_prompt(request: web.Request) -> web.Response:
        request["auth"].require("prompts.create")
        prompt = await _body(request, PromptCreate)
        created = await request.app["prompt_service"].register_prompt(prompt)
        return web.json_response(_dump(created), status=201)

    @routes.put("/prompts/{prompt_id}")
    async def update_prompt(request: web.Request) -> web.Response:
        request["auth"].require("prompts.update")
        update = await _body(request, PromptUpdate)
        prompt = await request.app["prompt_service"].update_prompt(
            request.match_info["prompt_id"], update)
        return web.json_response(_dump(prompt))

    @routes.delete("/prompts/{prompt_id}")
    async def delete_prompt(request: web.Request) -> web.Response:
        request["auth"].require("prompts.delete")
        await request.app["prompt_service"].delete_prompt(request.match_info["prompt_id"])
        return web.Response(status=204)

    @routes.post("/prompts/{name}/render")
    async def render_prompt(request: web.Request) -> web.Response:
        request["auth"].require("prompts.read")
        try:
            args = await request.json()
        except Exception:
            args = {}
        result = await request.app["prompt_service"].render_prompt(
            request.match_info["name"], args)
        return web.json_response(result)

    # --------------------------------------------------------------- servers
    @routes.get("/servers")
    async def list_servers(request: web.Request) -> web.Response:
        request["auth"].require("servers.read")
        include_inactive = request.query.get("include_inactive") == "true"
        servers = await _cached_list(
            request, "servers", str(include_inactive),
            lambda: request.app["server_service"].list_servers(
                include_inactive))
        return paginate(request, servers, _dump)

    @routes.post("/servers")
    async def create_server(request: web.Request) -> web.Response:
        request["auth"].require("servers.create")
        server = await _body(request, ServerCreate)
        created = await request.app["server_service"].register_server(server)
        return web.json_response(_dump(created), status=201)

    @routes.get("/servers/{server_id}")
    async def get_server(request: web.Request) -> web.Response:
        request["auth"].require("servers.read")
        server = await request.app["server_service"].get_server(
            request.match_info["server_id"])
        return web.json_response(_dump(server))

    @routes.put("/servers/{server_id}")
    async def update_server(request: web.Request) -> web.Response:
        request["auth"].require("servers.update")
        update = await _body(request, ServerUpdate)
        server = await request.app["server_service"].update_server(
            request.match_info["server_id"], update)
        return web.json_response(_dump(server))

    @routes.delete("/servers/{server_id}")
    async def delete_server(request: web.Request) -> web.Response:
        request["auth"].require("servers.delete")
        await request.app["server_service"].delete_server(request.match_info["server_id"])
        return web.Response(status=204)

    # --------------------------------------------------------------- metrics
    @routes.get("/metrics/prometheus")
    async def prometheus(request: web.Request) -> web.Response:
        # content negotiation: a scraper that accepts OpenMetrics gets
        # the exemplar-bearing exposition (per-bucket trace ids on the
        # TTFT/TPOT/queue-wait/http histograms — the dashboard's
        # click-through into /admin/trace/{id}); classic text otherwise.
        # ?scope=fleet (multi-worker, docs/scaleout.md): the merged
        # cross-worker exposition — counters/histograms summed, gauges
        # per-worker under a `worker` label — from ANY worker
        if request.query.get("scope") == "fleet":
            fleet = request.app.get("fleet_metrics")
            if fleet is None:
                raise NotFoundError(
                    "fleet metrics aggregation is not enabled "
                    "(set MCPFORGE_GW_FLEET_METRICS=true)")
            body, content_type = fleet.render_fleet()
        else:
            body, content_type = request.app["ctx"].metrics.render(
                accept=request.headers.get("accept", ""))
        return web.Response(body=body,
                            headers={"Content-Type": content_type})

    @routes.get("/metrics")
    async def metrics_summary(request: web.Request) -> web.Response:
        request["auth"].require("observability.read")
        settings = request.app["ctx"].settings
        if settings.admin_stats_cache_enabled:
            # dashboard polling (auto-refresh tabs) must not re-aggregate
            # per request (reference admin_stats_cache_* family)
            import time as _time
            cached = request.app["_stats_cache"].get("v")
            if cached and cached[1] > _time.monotonic():
                return web.json_response(cached[0])
        db = request.app["ctx"].db
        buffer = request.app["ctx"].extras.get("metrics_buffer")
        if buffer is not None:
            await buffer.flush()  # read-after-write for the dashboard
        rows = await db.fetchall(
            "SELECT t.original_name AS name, COUNT(*) AS calls,"
            " SUM(1 - m.success) AS errors, AVG(m.duration_ms) AS avg_ms,"
            " MIN(m.duration_ms) AS min_ms, MAX(m.duration_ms) AS max_ms"
            " FROM tool_metrics m JOIN tools t ON t.id = m.tool_id"
            " WHERE m.entity_type='tool'"
            " GROUP BY t.original_name ORDER BY calls DESC LIMIT 100")
        out = {"tools": rows}
        # per-entity families (reference keeps separate metric models per
        # entity, db.py:2556-2848; here one discriminated table)
        for etype, key in (("resource", "resources"), ("prompt", "prompts"),
                           ("a2a", "a2a_agents")):
            out[key] = await db.fetchall(
                "SELECT tool_id AS name, COUNT(*) AS calls,"
                " SUM(1 - success) AS errors, AVG(duration_ms) AS avg_ms,"
                " MIN(duration_ms) AS min_ms, MAX(duration_ms) AS max_ms"
                " FROM tool_metrics WHERE entity_type=?"
                " GROUP BY tool_id ORDER BY calls DESC LIMIT 100", (etype,))
        if settings.admin_stats_cache_enabled:
            import time as _time
            request.app["_stats_cache"]["v"] = (
                out, _time.monotonic() + settings.admin_stats_cache_ttl_s)
        return web.json_response(out)

    # ----------------------------------------------------- admin observability
    @routes.get("/admin/logs")
    async def admin_logs(request: web.Request) -> web.Response:
        request["auth"].require("observability.read")
        return web.json_response(ring_buffer.search(
            query=request.query.get("q", ""),
            level=request.query.get("level"),
            limit=int(request.query.get("limit", "200"))))

    @routes.get("/admin/traces")
    async def admin_traces(request: web.Request) -> web.Response:
        """Span search: ?q= (name substring), ?status=ERROR, ?trace_id=,
        ?min_ms= (duration floor), ?store=db|memory (reference
        routers/observability + log_search)."""
        request["auth"].require("observability.read")
        tracer = request.app["ctx"].tracer
        limit = max(1, min(int(request.query.get("limit", "100")), 1000))
        q = request.query.get("q", "")
        status = request.query.get("status")
        trace_id = request.query.get("trace_id")
        min_ms = float(request.query.get("min_ms", "0") or 0)
        if request.query.get("store") == "db":
            clauses, params = [], []
            if q:
                clauses.append("name LIKE ?")
                params.append(f"%{q}%")
            if status:
                clauses.append("status=?")
                params.append(status)
            if trace_id:
                clauses.append("trace_id=?")
                params.append(trace_id)
            if min_ms:
                clauses.append("(end_ts - start_ts) * 1000 >= ?")
                params.append(min_ms)
            where = (" WHERE " + " AND ".join(clauses)) if clauses else ""
            rows = await request.app["ctx"].db.fetchall(
                f"SELECT * FROM observability_spans{where}"
                f" ORDER BY start_ts DESC LIMIT ?", [*params, limit])
            return web.json_response(rows)
        spans = [s for s in tracer.finished
                 if (not q or q in s.name)
                 and (not status or s.status == status)
                 and (not trace_id or s.trace_id == trace_id)
                 and (s.duration_ms or 0) >= min_ms][-limit:]
        return web.json_response([{
            "name": s.name, "trace_id": s.trace_id, "span_id": s.span_id,
            "parent_span_id": s.parent_span_id, "start_ts": s.start_ts,
            "duration_ms": s.duration_ms, "status": s.status,
            "attributes": {k: str(v) for k, v in s.attributes.items()},
        } for s in reversed(spans)])

    @routes.get("/admin/system/stats")
    async def system_stats(request: web.Request) -> web.Response:
        """Deployment-scale counters across every entity family
        (reference services/system_stats_service.py, admin.py:18142)."""
        request["auth"].require("observability.read")
        return web.json_response(
            await request.app["system_stats_service"].stats())

    @routes.get("/admin/performance")
    async def performance_summary(request: web.Request) -> web.Response:
        """Operation timing percentiles + slow-op counts (reference
        services/performance_tracker.py:178)."""
        request["auth"].require("observability.read")
        perf = request.app["ctx"].extras.get("perf_tracker")
        if perf is None:
            raise NotFoundError("performance tracking is disabled")
        op = request.query.get("operation")
        out = perf.summary(op)
        if op and request.query.get("degradation") == "true":
            settings = request.app["ctx"].settings
            out["degradation"] = perf.degradation(
                op, settings.performance_degradation_multiplier)
        return web.json_response(out)

    @routes.delete("/admin/performance")
    async def performance_clear(request: web.Request) -> web.Response:
        request["auth"].require("admin.all")
        perf = request.app["ctx"].extras.get("perf_tracker")
        if perf is None:
            raise NotFoundError("performance tracking is disabled")
        perf.clear(request.query.get("operation"))
        return web.Response(status=204)

    @routes.get("/admin/classification")
    async def classification_state(request: web.Request) -> web.Response:
        """Hot/cold polling state (reference
        server_classification_service.py; restored, not stubbed)."""
        request["auth"].require("observability.read")
        classifier = request.app["ctx"].extras.get("server_classifier")
        if classifier is None:
            raise NotFoundError("hot/cold classification is disabled")
        # recompute on read: the health loop refreshes only once per
        # interval, and the operator wants the CURRENT hot/cold split
        return web.json_response(await classifier.classify())

    @routes.get("/admin/support-bundle")
    async def support_bundle(request: web.Request) -> web.Response:
        """Sanitized diagnostics zip download (reference
        services/support_bundle_service.py, admin.py:18212)."""
        request["auth"].require("admin.all")
        settings = request.app["ctx"].settings
        if not settings.support_bundle_enabled:
            raise NotFoundError("support bundle generation is disabled")
        try:
            tail = int(request.query.get("tail",
                                         settings.support_bundle_log_tail))
        except ValueError as exc:
            raise ValidationFailure("tail must be an integer") from exc
        name, payload = await request.app["support_bundle_service"].generate(
            include_logs=request.query.get("logs") != "false",
            include_env=request.query.get("env") != "false",
            log_tail=tail)
        return web.Response(
            body=payload, content_type="application/zip",
            headers={"content-disposition":
                     f'attachment; filename="{name}"'})

    @routes.get("/admin/engine/stats")
    async def engine_stats(request: web.Request) -> web.Response:
        """Scheduler/cache counters of the in-process tpu_local engine
        (reference analog: runtime_admin/observability admin surfaces)."""
        request["auth"].require("observability.read")
        from ..services.diagnostics_service import live_tpu_engine
        engine = live_tpu_engine(request.app)
        if engine is None:
            raise NotFoundError("tpu_local engine is not enabled")
        stats = engine.stats
        alloc = engine.allocator
        return web.json_response({
            "model": engine.config.model,
            "mesh": dict(engine.mesh.shape),
            "requests": stats.requests,
            "prompt_tokens": stats.prompt_tokens,
            "completion_tokens": stats.completion_tokens,
            "decode_steps": stats.decode_steps,
            # host syncs: one retire per dispatch; steps/dispatches ≈ the
            # effective superstep K (token-loop fusion, perf_decode.md)
            "decode_dispatches": stats.decode_dispatches,
            "superstep": engine.config.fused_steps,
            "prefill_batches": stats.prefill_batches,
            "prefill_requests": stats.prefill_requests,
            "queue_depth": stats.queue_depth,
            "kv_pages_in_use": alloc.pages_in_use,
            "kv_pages_free": alloc.free_pages,
            "kv_quant": engine.config.kv_quant or "off",
            "kv_bytes_in_use": engine.kv_bytes_in_use(),
            "prefill_ms_total": round(stats.prefill_ms_total, 1),
            "decode_ms_total": round(stats.decode_ms_total, 1),
            "engine_restarts": stats.engine_restarts,
            "chunking": stats.chunking,  # long prompts mid-chunk-prefill
            "prefix_cache": {
                "enabled": engine.config.prefix_cache,
                "cached_pages": alloc.cached_pages,
                "hits": alloc.prefix_hits,
                "hit_tokens": alloc.prefix_hit_tokens,
                # tiered spill store (docs/kv_tiering.md): per-tier hit
                # split, spill/restore counters, store footprint
                "tiers": engine.tier_stats(),
            },
            # flat twins for the admin-UI engine cards (cell() renders
            # scalars; the nested block above is the API-facing detail)
            "tier_hits_host": alloc.tier_hits["host"],
            "tier_hits_disk": alloc.tier_hits["disk"],
            "tier_hits_object": alloc.tier_hits.get("object", 0),
            "tier_hit_tokens_spilled": (alloc.tier_hit_tokens["host"]
                                        + alloc.tier_hit_tokens["disk"]
                                        + alloc.tier_hit_tokens.get(
                                            "object", 0)),
            "spec_decode": {
                "enabled": engine.config.spec_decode,
                "steps": stats.spec_steps,
                "extra_tokens": stats.spec_tokens,
            },
        })

    @routes.get("/admin/slo")
    async def slo_status(request: web.Request) -> web.Response:
        """Serving-SLO verdicts over the TTFT/TPOT/queue-wait histograms
        (observability/slo.py): per-objective percentile estimates
        (cumulative + window since the previous call), fraction of window
        samples over target, and burn rate against the error budget.
        ``?window=<name>`` names the caller's delta window (default
        "default") — the admin UI polls its own so it cannot shred a
        load harness's phase-length windows. ``?tenant=<id>`` evaluates
        that tenant's assigned SLO CLASS (slo_classes /
        slo_tenant_classes) against the tenant's metric label slice,
        with its own per-(window, tenant) delta isolation."""
        request["auth"].require("observability.read")
        evaluator = request.app.get("slo_evaluator")
        if request.query.get("scope") == "fleet":
            # fleet-wide verdicts (docs/scaleout.md): objectives over
            # the SUMMED cross-worker histogram state — fleet p95, with
            # its own per-consumer delta windows
            evaluator = request.app.get("slo_evaluator_fleet")
            if evaluator is None:
                raise NotFoundError(
                    "fleet SLO evaluation needs MCPFORGE_GW_FLEET_METRICS")
        if evaluator is None:  # pragma: no cover - evaluator is unconditional
            raise NotFoundError("SLO evaluation is not enabled")
        consumer = request.query.get("window", "default")[:64] or "default"
        tenant = request.query.get("tenant") or None
        report = evaluator.evaluate(
            consumer=consumer, tenant=tenant[:128] if tenant else None)
        if request.query.get("scope") == "fleet":
            report["scope"] = "fleet"
        return web.json_response(report)

    @routes.get("/admin/engine/pool")
    async def engine_pool_status(request: web.Request) -> web.Response:
        """Replica-pool topology card: per-replica health, occupancy, and
        routing/failover counters (tpu_local/pool/, docs/serving_pool.md)."""
        request["auth"].require("observability.read")
        pool = request.app.get("tpu_engine_pool")
        if pool is None:
            raise NotFoundError(
                "engine replica pool is not enabled "
                "(set MCPFORGE_TPU_LOCAL_REPLICAS > 1)")
        return web.json_response(pool.status())

    @routes.post("/admin/engine/pool/{replica}/{action}")
    async def engine_pool_action(request: web.Request) -> web.Response:
        """drain | undrain | reload | role for one replica. Drain stops
        routing and waits for in-flight work; reload is the rolling
        weight hot-swap (drain -> rebuild engine from config.checkpoint
        -> readmit); role retargets the replica's prefill/decode/any
        assignment live (body {"role": "..."}, docs/disaggregation.md —
        routing-only state, nothing drains)."""
        request["auth"].require("admin.all")  # reload swaps weights
        pool = request.app.get("tpu_engine_pool")
        if pool is None:
            raise NotFoundError(
                "engine replica pool is not enabled "
                "(set MCPFORGE_TPU_LOCAL_REPLICAS > 1)")
        action = request.match_info["action"]
        rid = request.match_info["replica"]
        body = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except json.JSONDecodeError:
                raise ValidationFailure("body must be JSON")
        if not isinstance(body, dict):  # valid JSON but e.g. [30] or "60"
            raise ValidationFailure("body must be a JSON object")
        try:
            timeout_s = float(body.get("timeout_s", 60.0))
        except (TypeError, ValueError):
            raise ValidationFailure("timeout_s must be a number")
        try:
            if action == "drain":
                result = await pool.drain(rid, timeout_s=timeout_s)
            elif action == "undrain":
                result = await pool.undrain(rid)
            elif action == "reload":
                result = await pool.reload(rid, timeout_s=timeout_s)
            elif action == "role":
                role = body.get("role")
                if not isinstance(role, str) or not role:
                    raise ValidationFailure(
                        'role action needs a body {"role": '
                        '"prefill|decode|any"}')
                result = pool.set_role(rid, role)
            else:
                raise ValidationFailure(
                    f"action must be drain|undrain|reload|role, "
                    f"got {action!r}")
        except KeyError as exc:
            raise NotFoundError(str(exc)) from exc
        except ValueError as exc:
            raise ValidationFailure(str(exc)) from exc
        return web.json_response(result)

    @routes.post("/admin/engine/profile")
    async def engine_profile(request: web.Request) -> web.Response:
        """Capture a jax.profiler trace of the running engine (SURVEY §5.1
        TPU mapping: jax.profiler integration alongside the OTel layer).
        Body: {"duration_ms": 1000, "dir": "/tmp/mcpforge-jaxprof"}."""
        # writes to disk: an admin capability, not a read one — and opt-in
        # via config (profiling stalls the runtime and writes traces)
        request["auth"].require("admin.all")
        from .routers_extra import profiler_or_404

        # the shared JaxProfilerCapture serializes EVERY profiling surface
        # (the jax profiler is process-global): a timed capture and the
        # start/stop endpoints must see each other's state. A concurrent
        # capture raises ConflictError -> 409 via the error middleware.
        profiler = profiler_or_404(request)
        from ..services.diagnostics_service import live_tpu_engine
        engine = live_tpu_engine(request.app)
        if engine is None:
            raise NotFoundError("tpu_local engine is not enabled")
        body = await request.json() if request.can_read_body else {}
        duration_ms = min(float(body.get("duration_ms", 1000.0)), 30_000.0)

        import asyncio as _aio

        # profiler start/stop write trace files — run them off the loop
        # (async-blocking-call discipline; the capture's mutex serializes)
        started = (await _aio.to_thread(profiler.start))["started_at"]
        try:
            await _aio.sleep(duration_ms / 1000.0)
        finally:
            from ..services.base import ConflictError as _Conflict
            try:
                # stop OUR capture only: an operator who stopped it and
                # started their own mid-window must not lose theirs
                result = await _aio.to_thread(profiler.stop,
                                              expect_started_at=started)
            except _Conflict:
                result = {"active": profiler.active,
                          "trace_dir": profiler.trace_dir,
                          "detail": "capture was stopped externally"}
        result.update({
            "duration_ms": duration_ms,
            "decode_steps": engine.stats.decode_steps,
            "prefill_batches": engine.stats.prefill_batches,
        })
        return web.json_response(result)

    @routes.get("/admin/traces/{trace_id}")
    async def admin_trace_tree(request: web.Request) -> web.Response:
        """Full span tree for one trace (memory + db union, deduped)."""
        request["auth"].require("observability.read")
        trace_id = request.match_info["trace_id"]
        tracer = request.app["ctx"].tracer
        spans = {s.span_id: {
            "name": s.name, "span_id": s.span_id,
            "parent_span_id": s.parent_span_id, "start_ts": s.start_ts,
            "duration_ms": s.duration_ms, "status": s.status,
            "attributes": {k: str(v) for k, v in s.attributes.items()},
        } for s in tracer.finished if s.trace_id == trace_id}
        for row in await request.app["ctx"].db.fetchall(
                "SELECT * FROM observability_spans WHERE trace_id=?",
                (trace_id,)):
            # normalize db rows to the memory-span response shape
            try:
                attrs = json.loads(row["attributes"] or "{}")
            except (TypeError, json.JSONDecodeError):
                attrs = {}
            duration = (None if row["end_ts"] is None
                        else (row["end_ts"] - row["start_ts"]) * 1000)
            spans.setdefault(row["span_id"], {
                "name": row["name"], "span_id": row["span_id"],
                "parent_span_id": row["parent_span_id"],
                "start_ts": row["start_ts"], "duration_ms": duration,
                "status": row["status"], "attributes": attrs})
        if not spans:
            raise NotFoundError(f"Trace {trace_id} not found")
        ordered = sorted(spans.values(), key=lambda s: s["start_ts"])
        return web.json_response({"trace_id": trace_id, "spans": ordered})

    app.add_routes(routes)
