"""Shared secret redaction for operator-facing surfaces.

One redaction policy serves the admin effective-config view, the support
bundle and the env snapshot (reference keeps the same list duplicated in
`services/support_bundle_service.py:112-186` and its admin config view;
here it is a single module so the surfaces can't drift).

Policy: a value is a secret when its *name* carries a credential
fragment, when the field is a known compound carrier (embeds credentials
without a telltale name), or when it is a DSN whose userinfo would leak
a password.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

REDACTED = "***redacted***"

# name fragments that mark a credential regardless of casing.  "token"
# is deliberately a SUFFIX match only: token_expiry / csrf_token_ttl_s /
# token_usage_logging_enabled are tuning knobs, while *_token fields
# (access_token, bearer_token) carry the credential itself.
_SECRET_FRAGMENTS = (
    "secret", "password", "passwd", "api_key", "apikey",
    "private_key", "credential",
)

# fields that EMBED credentials in a compound value (JSON blobs, header
# maps) — the name alone doesn't give them away
_OPAQUE_FIELDS = {"sso_providers", "otel_otlp_headers"}

_DSN_USERINFO = re.compile(r"://[^@/\s]+@")


def is_secret_name(name: str) -> bool:
    low = name.lower()
    return (any(f in low for f in _SECRET_FRAGMENTS)
            or low.endswith("_token") or low == "token"
            or low in _OPAQUE_FIELDS)


def redact_value(name: str, value: Any) -> Any:
    """Redact one named value; DSNs keep host/db but lose userinfo."""
    if isinstance(value, bool) or isinstance(value, (int, float)):
        return value  # no credential is numeric; keep tuning knobs visible
    if is_secret_name(name):
        return REDACTED if value else ""
    if isinstance(value, str) and "://" in value:
        return _DSN_USERINFO.sub("://***@", value)
    return value


def redact_settings(settings: Any) -> list[dict[str, Any]]:
    """The effective-settings table, secrets redacted, stable order."""
    out = []
    for name in sorted(type(settings).model_fields):
        out.append({"name": name,
                    "value": redact_value(name, getattr(settings, name))})
    return out


def redact_env(environ: Mapping[str, str]) -> dict[str, str]:
    """A process-environment snapshot safe to put in a support bundle.

    Only configuration-shaped variables are included (MCPFORGE_*, JAX/XLA
    tuning, proxy settings) — a full environ dump ships unrelated host
    secrets even redacted-by-name, so allowlist the prefixes instead.
    """
    keep_prefixes = ("MCPFORGE_", "JAX_", "XLA_", "LIBTPU", "TPU_",
                     "HTTP_PROXY", "HTTPS_PROXY", "NO_PROXY", "PYTHONPATH")
    out: dict[str, str] = {}
    for key in sorted(environ):
        if not key.upper().startswith(keep_prefixes):
            continue
        out[key] = str(redact_value(key, environ[key]))
    return out
