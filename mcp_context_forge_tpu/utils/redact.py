"""Shared secret redaction for operator-facing surfaces.

One redaction policy serves the admin effective-config view, the support
bundle and the env snapshot (reference keeps the same list duplicated in
`services/support_bundle_service.py:112-186` and its admin config view;
here it is a single module so the surfaces can't drift).

Policy: a value is a secret when its *name* carries a credential
fragment, when the field is a known compound carrier (embeds credentials
without a telltale name), or when it is a DSN whose userinfo would leak
a password.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

REDACTED = "***redacted***"

# name fragments that mark a credential regardless of casing.  "token"
# is deliberately a SUFFIX match only: token_expiry / csrf_token_ttl_s /
# token_usage_logging_enabled are tuning knobs, while *_token fields
# (access_token, bearer_token) carry the credential itself.
_SECRET_FRAGMENTS = (
    "secret", "password", "passwd", "api_key", "apikey",
    "private_key", "credential",
)

# fields that EMBED credentials in a compound value (JSON blobs, header
# maps) — the name alone doesn't give them away
_OPAQUE_FIELDS = {"sso_providers", "otel_otlp_headers"}

_DSN_USERINFO = re.compile(r"://[^@/\s]+@")


def is_secret_name(name: str) -> bool:
    low = name.lower()
    return (any(f in low for f in _SECRET_FRAGMENTS)
            or low.endswith("_token") or low == "token"
            or low in _OPAQUE_FIELDS)


def redact_value(name: str, value: Any) -> Any:
    """Redact one named value; DSNs keep host/db but lose userinfo."""
    # name check FIRST: a secret-named field with a numeric value (a PIN,
    # a numeric API key in an opaque map) must redact too — the numeric
    # fast path below only keeps non-secret tuning knobs visible
    if is_secret_name(name):
        return REDACTED if value else ""
    if isinstance(value, bool) or isinstance(value, (int, float)):
        return value
    if isinstance(value, str) and "://" in value:
        return _DSN_USERINFO.sub("://***@", value)
    return value


# content-level patterns for FREE TEXT (log lines, exception strings):
# unlike the name-keyed policy above, these run over values whose field
# names carry no signal. False positives are acceptable here — the only
# consumer is the support bundle, where over-redaction is the safe side.
_TEXT_PATTERNS: tuple[tuple[re.Pattern, str], ...] = (
    # Authorization header material
    (re.compile(r"(?i)\b(bearer|basic)[ :=]+[A-Za-z0-9._+/=\-]{8,}"),
     r"\1 " + REDACTED),
    # JWTs (three base64url segments, first always 'eyJ')
    (re.compile(r"\beyJ[A-Za-z0-9_\-]{8,}\.[A-Za-z0-9_\-]{4,}"
                r"\.[A-Za-z0-9_\-]+"), REDACTED),
    # vendor API keys of the sk-... shape
    (re.compile(r"\bsk-[A-Za-z0-9_\-]{16,}\b"), REDACTED),
)

# key=value / "key": "value" pairs whose key names a credential.  The
# value is checked separately: purely numeric values stay — telemetry
# fields like max_tokens / prompt_tokens carry "token" in the KEY, and
# scrubbing their counts would blind the very bundle built for debugging
_KV_PATTERN = re.compile(
    r"(?i)([\"']?[\w.\-]*(?:secret|password|passwd|api[_-]?key"
    r"|apikey|credential|token)[\w.\-]*[\"']?\s*[:=]\s*[\"']?)"
    r"([^\s\"',;&]{4,})")


def _kv_replace(match: re.Match) -> str:
    value = match.group(2)
    try:
        float(value)
        return match.group(0)  # numeric telemetry, not a credential
    except ValueError:
        return match.group(1) + REDACTED


def redact_text(text: str) -> str:
    """Scrub credential-shaped content out of free text (the support
    bundle's log records; reference support_bundle_service sanitizes log
    CONTENT, not just named settings)."""
    if not text:
        return text
    for pattern, replacement in _TEXT_PATTERNS:
        text = pattern.sub(replacement, text)
    text = _KV_PATTERN.sub(_kv_replace, text)
    return _DSN_USERINFO.sub("://***@", text)


def redact_settings(settings: Any) -> list[dict[str, Any]]:
    """The effective-settings table, secrets redacted, stable order."""
    out = []
    for name in sorted(type(settings).model_fields):
        out.append({"name": name,
                    "value": redact_value(name, getattr(settings, name))})
    return out


def redact_env(environ: Mapping[str, str]) -> dict[str, str]:
    """A process-environment snapshot safe to put in a support bundle.

    Only configuration-shaped variables are included (MCPFORGE_*, JAX/XLA
    tuning, proxy settings) — a full environ dump ships unrelated host
    secrets even redacted-by-name, so allowlist the prefixes instead.
    """
    keep_prefixes = ("MCPFORGE_", "JAX_", "XLA_", "LIBTPU", "TPU_",
                     "HTTP_PROXY", "HTTPS_PROXY", "NO_PROXY", "PYTHONPATH")
    out: dict[str, str] = {}
    for key in sorted(environ):
        if not key.upper().startswith(keep_prefixes):
            continue
        out[key] = str(redact_value(key, environ[key]))
    return out
