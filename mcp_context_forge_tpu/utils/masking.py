"""Sensitive-value masking for request/response logs.

Native C++ fast path (native/masking.cpp via ctypes — the counterpart of the
reference's Rust extension, crates/request_logging_masking_native_extension)
with a pure-Python fallback. The shared object is compiled on first use and
cached next to the source.
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
import re
import subprocess
import threading
from typing import Any

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libmasking.so")
_CPP_PATH = os.path.join(_NATIVE_DIR, "masking.cpp")

_lib = None
_lib_lock = threading.Lock()
_native_failed = False

SENSITIVE_SUBSTRINGS = (
    "password", "passwd", "secret", "token", "api_key", "apikey",
    "authorization", "auth", "credential", "private_key", "session_id",
    "cookie", "x-api-key", "client_secret", "access_key", "bearer",
)

_sensitive_cache: dict[str, bool] = {}


def _build_native() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _CPP_PATH,
             "-o", _SO_PATH],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception as exc:
        logger.debug("native masking build failed (%s); using python fallback", exc)
        return False


def _load_native(build: bool = False):
    """Load the shared object; only compile when ``build`` is set — the hot
    path (mask_text inside request middleware) must never run g++ on the
    event loop. native_available() builds; call it from an executor at
    startup to prewarm."""
    global _lib, _native_failed
    if _lib is not None or _native_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _native_failed:
            return _lib
        if not os.path.exists(_SO_PATH) or (
                os.path.exists(_CPP_PATH)
                and os.path.getmtime(_CPP_PATH) > os.path.getmtime(_SO_PATH)):
            if not build:
                return None  # not built yet: caller falls back to python
            if not os.path.exists(_CPP_PATH) or not _build_native():
                _native_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
            lib.mask_sensitive.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
            lib.mask_sensitive.restype = ctypes.c_void_p
            lib.mask_free.argtypes = [ctypes.c_void_p]
            _lib = lib
        except OSError as exc:
            logger.debug("native masking load failed: %s", exc)
            _native_failed = True
    return _lib


def is_sensitive_key(key: str) -> bool:
    cached = _sensitive_cache.get(key)
    if cached is not None:
        return cached
    lower = key.lower()
    sensitive = any(s in lower for s in SENSITIVE_SUBSTRINGS)
    if len(_sensitive_cache) < 4096:
        _sensitive_cache[key] = sensitive
    return sensitive


def mask_text(text: str) -> str:
    """Mask sensitive values in a JSON-ish log payload string."""
    lib = _load_native()
    if lib is not None:
        raw = text.encode("utf-8", errors="replace")
        ptr = lib.mask_sensitive(raw, len(raw))
        if ptr:  # NULL on OOM -> fall through to the Python path
            try:
                return ctypes.string_at(ptr).decode("utf-8", errors="replace")
            finally:
                lib.mask_free(ptr)
    return _mask_python(text)


def mask_obj(obj: Any) -> Any:
    """Recursively mask a decoded structure (python fallback path)."""
    if isinstance(obj, dict):
        return {k: ("***" if is_sensitive_key(str(k)) else mask_obj(v))
                for k, v in obj.items()}
    if isinstance(obj, list):
        return [mask_obj(v) for v in obj]
    return obj


def _mask_python(text: str) -> str:
    try:
        return json.dumps(mask_obj(json.loads(text)), separators=(",", ":"))
    except (json.JSONDecodeError, TypeError):
        # non-JSON: regex pass over key=value / "key": "value" shapes
        pattern = re.compile(
            r'(?i)("?(?:[\w.-]*(?:' + "|".join(SENSITIVE_SUBSTRINGS) +
            r')[\w.-]*)"?\s*[:=]\s*)("([^"\\]|\\.)*"|[^\s,}\]]+)')
        return pattern.sub(r'\1"***"', text)


def native_available() -> bool:
    return _load_native(build=True) is not None
