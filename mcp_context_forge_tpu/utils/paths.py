"""Shared dot-path extraction: 'items[0].name' over parsed JSON."""

from __future__ import annotations

from typing import Any


def extract_path(data: Any, path: str) -> Any:
    """Walk a dot-path with [i] list indexing; None when unresolvable."""
    current = data
    for part in path.replace("]", "").split("."):
        if not part:
            continue
        key, _, index = part.partition("[")
        if key:
            if not isinstance(current, dict) or key not in current:
                return None
            current = current[key]
        if index:
            try:
                current = current[int(index)]
            except (ValueError, IndexError, TypeError, KeyError):
                return None
    return current
