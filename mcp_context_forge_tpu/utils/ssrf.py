"""SSRF guard for operator-supplied outbound URLs.

Reference: the ``ssrf_*`` settings family
(`/root/reference/mcpgateway/config.py` — ssrf_protection_enabled,
ssrf_allow_localhost, ssrf_allow_private_networks, ssrf_blocked_hosts,
ssrf_allowed_networks, ssrf_blocked_networks, ssrf_dns_fail_closed).

Applied where URLs ENTER the catalog (tool/gateway registration, update
and the wizard dry-run probe) rather than per outbound request: entries
are admin-authored and long-lived, so admission-time vetting covers the
runtime calls they produce while keeping the hot path free of DNS work.
DNS resolution runs in the executor; a resolution failure blocks or
passes per ``ssrf_dns_fail_closed``.
"""

from __future__ import annotations

import asyncio
import ipaddress
import socket
from urllib.parse import urlsplit

from ..services.base import ValidationFailure


def _parse_networks(csv: str) -> list[ipaddress._BaseNetwork]:
    nets = []
    for part in csv.split(","):
        part = part.strip()
        if part:
            nets.append(ipaddress.ip_network(part, strict=False))
    return nets


def _check_ip(ip: ipaddress._BaseAddress, settings) -> str | None:
    """Return a rejection reason or None."""
    for net in _parse_networks(settings.ssrf_allowed_networks_csv):
        if ip in net:
            return None  # explicit allow wins
    for net in _parse_networks(settings.ssrf_blocked_networks_csv):
        if ip in net:
            return f"address {ip} is in a blocked network"
    if ip.is_loopback:
        return (None if settings.ssrf_allow_localhost
                else f"loopback address {ip} is not allowed")
    if ip.is_private or ip.is_link_local:
        return (None if settings.ssrf_allow_private_networks
                else f"private address {ip} is not allowed")
    return None


async def ensure_url_allowed(settings, url: str) -> None:
    """Raise ValidationFailure when the URL's target is off-limits.

    No-op unless ``ssrf_protection_enabled`` — the flag defaults off so
    single-host deployments (where upstreams ARE localhost) keep working;
    internet-facing gateways flip it on and open pinholes via
    ``ssrf_allowed_networks_csv``.
    """
    if not settings.ssrf_protection_enabled or not url:
        return
    parts = urlsplit(url)
    if parts.scheme not in ("http", "https"):
        raise ValidationFailure(f"URL scheme {parts.scheme!r} is not allowed")
    host = parts.hostname or ""
    if not host:
        raise ValidationFailure("URL has no host")
    blocked_hosts = {h.strip().lower()
                     for h in settings.ssrf_blocked_hosts_csv.split(",")
                     if h.strip()}
    if host.lower() in blocked_hosts:
        raise ValidationFailure(f"host {host!r} is blocked")
    try:
        ip = ipaddress.ip_address(host)
        addresses = [ip]
    except ValueError:
        # hostname: resolve EVERY address — an attacker controls DNS, and
        # one private A record among public ones is the classic rebind
        try:
            infos = await asyncio.get_running_loop().run_in_executor(
                None, socket.getaddrinfo, host, None)
            addresses = [ipaddress.ip_address(info[4][0]) for info in infos]
        except (socket.gaierror, ValueError) as exc:
            if settings.ssrf_dns_fail_closed:
                raise ValidationFailure(
                    f"cannot resolve {host!r}: {exc}") from exc
            return
    for ip in addresses:
        reason = _check_ip(ip, settings)
        if reason:
            raise ValidationFailure(f"SSRF guard: {reason}")
