"""Minimal JWT (JWS compact) implementation — HS256/384/512.

PyJWT is not in the image; the gateway only needs HMAC-family tokens
(reference default HS256, `/root/reference/mcpgateway/config.py` jwt settings;
token creation `utils/create_jwt_token.py`). Asymmetric algorithms can be
added behind the same encode/decode API if SSO federation requires them.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Any

_ALGS = {
    "HS256": hashlib.sha256,
    "HS384": hashlib.sha384,
    "HS512": hashlib.sha512,
}


class JWTError(Exception):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def encode(payload: dict[str, Any], secret: str, algorithm: str = "HS256") -> str:
    if algorithm not in _ALGS:
        raise JWTError(f"Unsupported algorithm {algorithm}")
    header = {"alg": algorithm, "typ": "JWT"}
    signing_input = _b64url(json.dumps(header, separators=(",", ":")).encode()) + "." + \
        _b64url(json.dumps(payload, separators=(",", ":")).encode())
    sig = hmac.new(secret.encode(), signing_input.encode(), _ALGS[algorithm]).digest()
    return signing_input + "." + _b64url(sig)


def decode_unverified(token: str) -> dict[str, Any] | None:
    """Payload WITHOUT signature/expiry verification — identification
    only, never authentication (token-usage accounting of rejected
    requests needs the jti of a token that failed verification)."""
    try:
        _, payload_b64, _ = token.split(".")
        payload = json.loads(_b64url_decode(payload_b64))
        return payload if isinstance(payload, dict) else None
    except (ValueError, json.JSONDecodeError):
        return None


def decode(
    token: str,
    secret: str,
    algorithms: tuple[str, ...] = ("HS256", "HS384", "HS512"),
    audience: str | None = None,
    issuer: str | None = None,
    verify_exp: bool = True,
    leeway: float = 0.0,
) -> dict[str, Any]:
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
        header = json.loads(_b64url_decode(header_b64))
        payload = json.loads(_b64url_decode(payload_b64))
        sig = _b64url_decode(sig_b64)
    except (ValueError, json.JSONDecodeError) as exc:
        raise JWTError(f"Malformed token: {exc}") from exc

    alg = header.get("alg")
    if alg not in algorithms or alg not in _ALGS:
        raise JWTError(f"Algorithm {alg!r} not allowed")
    signing_input = (header_b64 + "." + payload_b64).encode()
    expected = hmac.new(secret.encode(), signing_input, _ALGS[alg]).digest()
    if not hmac.compare_digest(sig, expected):
        raise JWTError("Signature verification failed")

    now = time.time()
    try:
        exp = float(payload["exp"]) if "exp" in payload else None
        nbf = float(payload["nbf"]) if "nbf" in payload else None
    except (TypeError, ValueError) as exc:
        raise JWTError(f"Invalid exp/nbf claim: {exc}") from exc
    if verify_exp and exp is not None and now > exp + leeway:
        raise JWTError("Token expired")
    if nbf is not None and now < nbf - leeway:
        raise JWTError("Token not yet valid")
    if audience is not None:
        aud = payload.get("aud")
        auds = aud if isinstance(aud, list) else [aud]
        if audience not in auds:
            raise JWTError("Invalid audience")
    if issuer is not None and payload.get("iss") != issuer:
        raise JWTError("Invalid issuer")
    return payload


def create_token(
    claims: dict[str, Any],
    secret: str,
    algorithm: str = "HS256",
    expires_minutes: int | None = 60,
    audience: str | None = None,
    issuer: str | None = None,
) -> str:
    payload = dict(claims)
    now = int(time.time())
    payload.setdefault("iat", now)
    if expires_minutes is not None:
        payload.setdefault("exp", now + expires_minutes * 60)
    if audience is not None:
        payload.setdefault("aud", audience)
    if issuer is not None:
        payload.setdefault("iss", issuer)
    return encode(payload, secret, algorithm)
