"""SSL context construction + caching.

Reference: `utils/ssl_context_cache` — building an ``ssl.SSLContext``
loads and parses the CA bundle from disk (~10 ms and a syscall burst),
so contexts are built once per distinct (ca_bundle, cert, key, verify)
tuple and reused for every outbound connection.
"""

from __future__ import annotations

import ssl
from functools import lru_cache


@lru_cache(maxsize=64)
def _cached_context(ca_bundle: str, cert_file: str, key_file: str,
                    verify: bool) -> ssl.SSLContext:
    if not verify:
        context = ssl.create_default_context()
        context.check_hostname = False
        context.verify_mode = ssl.CERT_NONE
        return context
    context = ssl.create_default_context(
        cafile=ca_bundle or None)
    if cert_file:
        context.load_cert_chain(cert_file, key_file or None)
    return context


def outbound_ssl(settings) -> ssl.SSLContext | bool | None:
    """ssl= argument for outbound client connections.

    Returns False (verification off) when skip_ssl_verify, a cached
    custom context when a CA bundle is pinned, else None (library
    default context — aiohttp/httpx cache that themselves)."""
    if settings.skip_ssl_verify:
        return False
    if settings.ssl_ca_bundle:
        return _cached_context(settings.ssl_ca_bundle, "", "", True)
    return None


def serving_ssl(settings) -> ssl.SSLContext | None:
    """Server-side TLS context (ssl_enabled + cert/key), else None."""
    if not settings.ssl_enabled:
        return None
    context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    context.load_cert_chain(settings.ssl_cert_file,
                            settings.ssl_key_file or None)
    return context


def context_cache_info():
    return _cached_context.cache_info()
