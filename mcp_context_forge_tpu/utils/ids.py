"""ID + slug helpers."""

from __future__ import annotations

import re
import uuid


def new_id() -> str:
    return uuid.uuid4().hex


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


_slug_re = re.compile(r"[^a-z0-9]+")


def slugify(name: str) -> str:
    s = _slug_re.sub("-", name.lower()).strip("-")
    return s or new_id()[:8]
