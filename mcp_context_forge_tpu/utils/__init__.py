"""Shared utilities (reference: /root/reference/mcpgateway/utils/ — 45 modules)."""
