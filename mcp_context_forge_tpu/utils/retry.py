"""Resilient retry helper (reference: utils/retry_manager.py:1-19 —
exponential backoff + jitter + Retry-After awareness)."""

from __future__ import annotations

import asyncio
import random
from typing import Awaitable, Callable, TypeVar

import httpx

T = TypeVar("T")

RETRYABLE_STATUS = {429, 502, 503, 504}


class RetryExhausted(Exception):
    def __init__(self, attempts: int, last: BaseException):
        super().__init__(f"All {attempts} attempts failed: {last}")
        self.attempts = attempts
        self.last = last


def backoff_delay(attempt: int, base: float = 0.25, cap: float = 8.0,
                  retry_after: float | None = None) -> float:
    if retry_after is not None:
        return min(retry_after, cap)
    exp = min(cap, base * (2 ** attempt))
    return random.uniform(0, exp)  # full jitter


async def with_retries(
    fn: Callable[[], Awaitable[T]],
    attempts: int = 3,
    base: float = 0.25,
    cap: float = 8.0,
    retryable: Callable[[BaseException], bool] | None = None,
) -> T:
    """Run ``fn`` with retries. httpx transport errors and 429/5xx retry by
    default; JSON-RPC/application errors do not."""
    import aiohttp

    last: BaseException | None = None
    for attempt in range(attempts):
        try:
            return await fn()
        except (httpx.HTTPStatusError, aiohttp.ClientResponseError) as exc:
            last = exc
            if isinstance(exc, httpx.HTTPStatusError):
                status = exc.response.status_code
                ra = exc.response.headers.get("retry-after")
            else:
                status = exc.status
                ra = (exc.headers or {}).get("Retry-After") if exc.headers else None
            if status not in RETRYABLE_STATUS:
                raise
            retry_after = (float(ra) if ra and str(ra).replace(".", "", 1).isdigit()
                           else None)
            if attempt + 1 < attempts:
                await asyncio.sleep(backoff_delay(attempt, base, cap, retry_after))
        except (httpx.TransportError, aiohttp.ClientError,
                asyncio.TimeoutError, ConnectionError) as exc:
            last = exc
            if attempt + 1 < attempts:
                await asyncio.sleep(backoff_delay(attempt, base, cap))
        except BaseException as exc:
            if retryable is not None and retryable(exc):
                last = exc
                if attempt + 1 < attempts:
                    await asyncio.sleep(backoff_delay(attempt, base, cap))
            else:
                raise
    assert last is not None
    raise RetryExhausted(attempts, last)
