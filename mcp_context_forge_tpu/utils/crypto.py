"""Field encryption for stored secrets.

Parity with the reference's AES-256-GCM field encryption
(`/root/reference/mcpgateway/services/encryption_service.py:109`): secrets at
rest (gateway auth headers, LLM provider configs, export bundles) are sealed
with a key derived from ``auth_encryption_secret``.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
from typing import Any

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

_MAGIC = "enc:v1:"


class DecryptionError(Exception):
    """Sealed value could not be opened (wrong key, corruption, truncation)."""


def _derive_key(secret: str) -> bytes:
    return hashlib.sha256(("mcpforge-field-enc:" + secret).encode()).digest()


def encrypt_field(value: Any, secret: str) -> str:
    """Seal a JSON-serializable value. Output is ASCII-safe."""
    key = _derive_key(secret)
    nonce = os.urandom(12)
    plaintext = json.dumps(value, separators=(",", ":")).encode()
    ct = AESGCM(key).encrypt(nonce, plaintext, None)
    return _MAGIC + base64.urlsafe_b64encode(nonce + ct).decode()


def decrypt_field(token: str | None, secret: str) -> Any:
    """Open a sealed value; passthrough for legacy/plaintext values."""
    if token is None:
        return None
    if not token.startswith(_MAGIC):
        try:
            return json.loads(token)
        except (json.JSONDecodeError, TypeError):
            return token
    try:
        raw = base64.urlsafe_b64decode(token[len(_MAGIC):].encode())
        nonce, ct = raw[:12], raw[12:]
        plaintext = AESGCM(_derive_key(secret)).decrypt(nonce, ct, None)
        return json.loads(plaintext)
    except Exception as exc:
        raise DecryptionError(f"Cannot decrypt sealed field: {type(exc).__name__}") from exc
