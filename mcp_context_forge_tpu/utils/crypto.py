"""Field encryption for stored secrets.

Parity with the reference's AES-256-GCM field encryption
(`/root/reference/mcpgateway/services/encryption_service.py:109`): secrets at
rest (gateway auth headers, LLM provider configs, export bundles) are sealed
with a key derived from ``auth_encryption_secret``.

The ``cryptography`` package is a GATED dependency: when it is absent
(slim TPU images bake jax + the serving stack only), sealing falls back to
an in-tree encrypt-then-MAC construction (SHA-256 counter keystream XOR +
HMAC-SHA256 tag) so the gateway still boots and the provider-config CRUD
surface keeps working. The fallback shares the wire prefix; a value sealed
by one mode is not readable by the other (decrypt raises DecryptionError),
which only matters if a database migrates between images with and without
the library. A warning is logged once at import so the degraded mode is
visible in operator logs.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging
import os
from typing import Any

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # gated: no pip installs in the serving image
    AESGCM = None
    logging.getLogger(__name__).warning(
        "cryptography is not installed: field encryption is using the "
        "in-tree HMAC-authenticated stream-cipher fallback")

_MAGIC = "enc:v1:"
_TAG_LEN = 16


class DecryptionError(Exception):
    """Sealed value could not be opened (wrong key, corruption, truncation)."""


def _derive_key(secret: str) -> bytes:
    return hashlib.sha256(("mcpforge-field-enc:" + secret).encode()).digest()


def _keystream(key: bytes, nonce: bytes, length: int) -> bytes:
    out = b""
    counter = 0
    while len(out) < length:
        out += hashlib.sha256(
            key + nonce + counter.to_bytes(4, "big")).digest()
        counter += 1
    return out[:length]


def encrypt_field(value: Any, secret: str) -> str:
    """Seal a JSON-serializable value. Output is ASCII-safe."""
    key = _derive_key(secret)
    nonce = os.urandom(12)
    plaintext = json.dumps(value, separators=(",", ":")).encode()
    if AESGCM is not None:
        ct = AESGCM(key).encrypt(nonce, plaintext, None)
        return _MAGIC + base64.urlsafe_b64encode(nonce + ct).decode()
    stream = _keystream(key, nonce, len(plaintext))
    ct = bytes(a ^ b for a, b in zip(plaintext, stream))
    tag = hmac.new(key, nonce + ct, hashlib.sha256).digest()[:_TAG_LEN]
    return _MAGIC + base64.urlsafe_b64encode(nonce + ct + tag).decode()


def decrypt_field(token: str | None, secret: str) -> Any:
    """Open a sealed value; passthrough for legacy/plaintext values."""
    if token is None:
        return None
    if not token.startswith(_MAGIC):
        try:
            return json.loads(token)
        except (json.JSONDecodeError, TypeError):
            return token
    try:
        raw = base64.urlsafe_b64decode(token[len(_MAGIC):].encode())
        key = _derive_key(secret)
        nonce = raw[:12]
        if AESGCM is not None:
            plaintext = AESGCM(key).decrypt(nonce, raw[12:], None)
        else:
            ct, tag = raw[12:-_TAG_LEN], raw[-_TAG_LEN:]
            want = hmac.new(key, nonce + ct, hashlib.sha256).digest()[:_TAG_LEN]
            if not hmac.compare_digest(tag, want):
                raise ValueError("bad auth tag")
            stream = _keystream(key, nonce, len(ct))
            plaintext = bytes(a ^ b for a, b in zip(ct, stream))
        return json.loads(plaintext)
    except Exception as exc:
        raise DecryptionError(f"Cannot decrypt sealed field: {type(exc).__name__}") from exc
