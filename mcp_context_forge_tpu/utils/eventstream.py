"""AWS event-stream binary framing (application/vnd.amazon.eventstream).

Bedrock's ConverseStream API answers in this framing (reference proxies
bedrock via boto3 which hides it, `/root/reference/mcpgateway/services/
llm_proxy_service.py:529`; in-tree we speak the wire format directly).

Frame layout (all integers big-endian):

    [4] total length | [4] headers length | [4] prelude CRC32
    [headers ...] [payload ...] [4] message CRC32

- prelude CRC covers the first 8 bytes;
- message CRC covers everything before it (prelude + CRC + headers + payload);
- each header: [1] name-len, name, [1] value-type, value. Type 7 (string)
  and 6 (bytes) carry a [2] length prefix; scalar types are fixed-width.

Spec: AWS SDK "event stream encoding" (the vnd.amazon.eventstream media
type, used by S3 Select / Transcribe / Bedrock streaming).
"""

from __future__ import annotations

import zlib
from typing import Any, AsyncIterator

_PRELUDE_LEN = 12
_CRC_LEN = 4

# scalar value-type tag -> fixed byte width (bools 0/1 carry no payload
# and are handled before this table; 6/7 are length-prefixed)
_FIXED_WIDTH = {2: 1, 3: 2, 4: 4, 5: 8, 8: 8, 9: 16}


class EventStreamError(ValueError):
    pass


def _parse_headers(data: bytes) -> dict[str, Any]:
    headers: dict[str, Any] = {}
    i = 0
    while i < len(data):
        name_len = data[i]
        i += 1
        name = data[i:i + name_len].decode("utf-8")
        i += name_len
        vtype = data[i]
        i += 1
        if vtype in (0, 1):          # bool true / false, no payload
            headers[name] = vtype == 0
        elif vtype in (6, 7):        # bytes / string: u16 length prefix
            vlen = int.from_bytes(data[i:i + 2], "big")
            i += 2
            raw = data[i:i + vlen]
            i += vlen
            headers[name] = raw.decode("utf-8") if vtype == 7 else raw
        elif vtype in _FIXED_WIDTH:  # integer/timestamp scalars + uuid
            width = _FIXED_WIDTH[vtype]
            raw = data[i:i + width]
            i += width
            headers[name] = (raw if vtype == 9
                             else int.from_bytes(raw, "big", signed=True))
        else:
            raise EventStreamError(f"unknown header value type {vtype}")
    return headers


def decode_frame(frame: bytes) -> tuple[dict[str, Any], bytes]:
    """One complete frame -> (headers, payload). Validates both CRCs."""
    if len(frame) < _PRELUDE_LEN + _CRC_LEN:
        raise EventStreamError("frame shorter than prelude")
    total = int.from_bytes(frame[0:4], "big")
    headers_len = int.from_bytes(frame[4:8], "big")
    prelude_crc = int.from_bytes(frame[8:12], "big")
    if zlib.crc32(frame[0:8]) != prelude_crc:
        raise EventStreamError("prelude CRC mismatch")
    if total != len(frame):
        raise EventStreamError("frame length mismatch")
    message_crc = int.from_bytes(frame[-4:], "big")
    if zlib.crc32(frame[:-4]) != message_crc:
        raise EventStreamError("message CRC mismatch")
    headers_end = _PRELUDE_LEN + headers_len
    headers = _parse_headers(frame[_PRELUDE_LEN:headers_end])
    payload = frame[headers_end:-4]
    return headers, payload


def encode_frame(headers: dict[str, str], payload: bytes) -> bytes:
    """Build a frame (string headers only — what event APIs actually use).
    Used by tests to synthesize Bedrock streams; inverse of decode_frame."""
    hdr = bytearray()
    for name, value in headers.items():
        name_b = name.encode()
        value_b = value.encode()
        hdr += bytes([len(name_b)]) + name_b + bytes([7])
        hdr += len(value_b).to_bytes(2, "big") + value_b
    total = _PRELUDE_LEN + len(hdr) + len(payload) + _CRC_LEN
    prelude = total.to_bytes(4, "big") + len(hdr).to_bytes(4, "big")
    prelude += zlib.crc32(prelude).to_bytes(4, "big")
    body = prelude + bytes(hdr) + payload
    return body + zlib.crc32(body).to_bytes(4, "big")


async def iter_frames(byte_iter: AsyncIterator[bytes]
                      ) -> AsyncIterator[tuple[dict[str, Any], bytes]]:
    """Incremental decoder over an async byte stream (httpx aiter_bytes):
    yields (headers, payload) per complete frame, tolerating frames split
    across arbitrary chunk boundaries."""
    buf = bytearray()
    async for chunk in byte_iter:
        buf += chunk
        while len(buf) >= _PRELUDE_LEN:
            total = int.from_bytes(buf[0:4], "big")
            if total < _PRELUDE_LEN + _CRC_LEN or total > 16 * 1024 * 1024:
                raise EventStreamError(f"implausible frame length {total}")
            if len(buf) < total:
                break
            frame = bytes(buf[:total])
            del buf[:total]
            yield decode_frame(frame)
    if buf:
        raise EventStreamError(f"{len(buf)} trailing bytes after last frame")
