"""Transport bridge: expose a stdio MCP server over HTTP (streamable/SSE),
or a remote HTTP MCP endpoint over stdio.

Reference: `/root/reference/mcpgateway/translate.py` (2.5k LoC bidirectional
stdio⇄SSE⇄streamable-HTTP bridge). Two directions in-tree:

- ``stdio→http``: spawn a stdio MCP server subprocess and mount it at /mcp
  (streamable-HTTP) + /sse (legacy) on a local port.
- ``http→stdio``: speak MCP on this process's stdio, forwarding to a remote
  streamable-HTTP endpoint (the ``wrapper`` direction; native C++ sibling in
  native/stdio_wrapper.cpp).

CLI: ``python -m mcp_context_forge_tpu.translate --stdio "cmd ..." --port 9000``
     ``python -m mcp_context_forge_tpu.translate --connect http://gw:4444/mcp``
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from typing import Any

from aiohttp import web


class StdioServerBridge:
    """Own a stdio MCP subprocess; correlate JSON-RPC ids across clients."""

    def __init__(self, command: str):
        self.command = command
        self._process: asyncio.subprocess.Process | None = None
        self._pending: dict[str, asyncio.Future] = {}
        self._next_id = 1
        self._reader_task: asyncio.Task | None = None
        self._lock = asyncio.Lock()

    async def start(self) -> None:
        self._process = await asyncio.create_subprocess_shell(
            self.command,
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            stderr=sys.stderr,
        )
        self._reader_task = asyncio.create_task(self._read_loop())

    async def stop(self) -> None:
        if self._reader_task:
            self._reader_task.cancel()
        if self._process:
            if self._process.stdin:
                try:
                    self._process.stdin.close()
                    await self._process.stdin.wait_closed()
                except Exception:
                    pass
            if self._process.returncode is None:
                self._process.terminate()
                try:
                    await asyncio.wait_for(self._process.wait(), timeout=5)
                except asyncio.TimeoutError:
                    self._process.kill()
                    await self._process.wait()

    async def _read_loop(self) -> None:
        assert self._process and self._process.stdout
        while True:
            line = await self._process.stdout.readline()
            if not line:
                break
            try:
                message = json.loads(line)
            except json.JSONDecodeError:
                continue
            key = str(message.get("id"))
            future = self._pending.pop(key, None)
            if future is not None and not future.done():
                future.set_result(message)
        # subprocess died (EOF): fail everything in flight immediately
        error = ConnectionError("stdio MCP server exited")
        for future in self._pending.values():
            if not future.done():
                future.set_exception(error)
        self._pending.clear()

    async def request(self, message: dict[str, Any],
                      timeout: float = 60.0) -> dict[str, Any] | None:
        """Forward one JSON-RPC message; returns the response (None for
        notifications). Ids are rewritten to avoid cross-client collisions."""
        assert self._process and self._process.stdin
        is_notification = "id" not in message
        original_id = message.get("id")
        if not is_notification:
            async with self._lock:
                bridge_id = f"b{self._next_id}"
                self._next_id += 1
            message = {**message, "id": bridge_id}
            future: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending[bridge_id] = future
        data = json.dumps(message, separators=(",", ":")) + "\n"
        try:
            self._process.stdin.write(data.encode())
            await self._process.stdin.drain()
            if is_notification:
                return None
            response = await asyncio.wait_for(future, timeout=timeout)
        finally:
            if not is_notification:
                self._pending.pop(bridge_id, None)
        response["id"] = original_id
        return response


def build_bridge_app(bridge: StdioServerBridge) -> web.Application:
    app = web.Application()

    async def handle_mcp(request: web.Request) -> web.Response:
        try:
            payload = json.loads(await request.read())
        except json.JSONDecodeError:
            return web.json_response({"jsonrpc": "2.0", "id": None,
                                      "error": {"code": -32700,
                                                "message": "Parse error"}},
                                     status=400)
        messages = payload if isinstance(payload, list) else [payload]
        responses = []
        for message in messages:
            response = await bridge.request(message)
            if response is not None:
                responses.append(response)
        if not responses:
            return web.Response(status=202)
        return web.json_response(responses if isinstance(payload, list)
                                 else responses[0])

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "healthy"})

    app.router.add_post("/mcp", handle_mcp)
    app.router.add_get("/health", health)
    return app


async def run_stdio_to_http(command: str, host: str, port: int) -> None:
    bridge = StdioServerBridge(command)
    await bridge.start()
    app = build_bridge_app(bridge)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    print(f"bridging stdio server to http://{host}:{port}/mcp", file=sys.stderr)
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await bridge.stop()
        await runner.cleanup()


async def run_http_to_stdio(endpoint: str, headers: dict[str, str]) -> None:
    """Speak MCP on stdio; forward to a remote streamable-HTTP endpoint."""
    import httpx

    async with httpx.AsyncClient(timeout=60.0) as client:
        loop = asyncio.get_running_loop()
        reader = asyncio.StreamReader()
        await loop.connect_read_pipe(lambda: asyncio.StreamReaderProtocol(reader),
                                     sys.stdin)
        session_id: str | None = None
        while True:
            line = await reader.readline()
            if not line:
                break
            try:
                message = json.loads(line)
            except json.JSONDecodeError:
                continue
            send_headers = {"content-type": "application/json",
                            "accept": "application/json, text/event-stream",
                            **headers}
            if session_id:
                send_headers["mcp-session-id"] = session_id
            try:
                response = await client.post(endpoint, json=message,
                                             headers=send_headers)
            except Exception as exc:
                if "id" in message:
                    sys.stdout.write(json.dumps({
                        "jsonrpc": "2.0", "id": message.get("id"),
                        "error": {"code": -32000,
                                  "message": f"gateway unreachable: {exc}"}}) + "\n")
                    sys.stdout.flush()
                continue
            sid = response.headers.get("mcp-session-id")
            if sid:
                session_id = sid
            if "id" not in message or response.status_code == 202:
                continue
            content_type = response.headers.get("content-type", "")
            if content_type.startswith("text/event-stream"):
                # SSE reply: the JSON-RPC messages ride data: lines
                for block in response.text.split("\n\n"):
                    for line in block.splitlines():
                        if line.startswith("data: "):
                            sys.stdout.write(line[6:] + "\n")
                sys.stdout.flush()
                continue
            try:
                body = response.json()
            except Exception:
                body = {"jsonrpc": "2.0", "id": message.get("id"),
                        "error": {"code": -32000,
                                  "message": f"HTTP {response.status_code}: "
                                             f"{response.text[:200]}"}}
            sys.stdout.write(json.dumps(body, separators=(",", ":")) + "\n")
            sys.stdout.flush()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="mcpforge-translate")
    parser.add_argument("--stdio", help="command of a stdio MCP server to expose")
    parser.add_argument("--connect", help="remote /mcp endpoint to expose on stdio")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=9000)
    parser.add_argument("--header", action="append", default=[],
                        help="extra header K:V for --connect")
    args = parser.parse_args(argv)
    if bool(args.stdio) == bool(args.connect):
        parser.error("exactly one of --stdio / --connect is required")
    if args.stdio:
        asyncio.run(run_stdio_to_http(args.stdio, args.host, args.port))
    else:
        headers = dict(h.split(":", 1) for h in args.header)
        asyncio.run(run_http_to_stdio(args.connect, headers))
    return 0


if __name__ == "__main__":
    sys.exit(main())
