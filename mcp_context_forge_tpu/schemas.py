"""API request/response schemas (reference: mcpgateway/schemas.py, 9k LoC —
here table-driven and compact; one Create/Update/Read triple per entity)."""

from __future__ import annotations

import time
from typing import Any, Literal

from pydantic import BaseModel, Field, field_validator

Visibility = Literal["public", "team", "private"]


class _Entity(BaseModel):
    description: str | None = None
    tags: list[str] = Field(default_factory=list)
    team_id: str | None = None
    owner_email: str | None = None
    visibility: Visibility = "public"


# ------------------------------------------------------------------ gateways

class GatewayCreate(_Entity):
    name: str
    url: str
    transport: Literal["streamablehttp", "sse"] = "streamablehttp"
    auth_type: Literal["none", "basic", "bearer", "headers", "oauth"] | None = None
    # {username,password} | {token} | {headers} | {token_url,client_id,client_secret}
    auth_value: dict[str, Any] | None = None
    passthrough_headers: list[str] = Field(default_factory=list)
    enabled: bool = True

    @field_validator("url")
    @classmethod
    def _check_url(cls, v: str) -> str:
        if not v.startswith(("http://", "https://")):
            raise ValueError("gateway url must be http(s)")
        return v


class GatewayUpdate(BaseModel):
    name: str | None = None
    url: str | None = None
    description: str | None = None
    transport: Literal["streamablehttp", "sse"] | None = None
    auth_type: Literal["none", "basic", "bearer", "headers", "oauth"] | None = None
    auth_value: dict[str, Any] | None = None
    passthrough_headers: list[str] | None = None
    enabled: bool | None = None
    tags: list[str] | None = None
    visibility: Visibility | None = None


class GatewayRead(_Entity):
    id: str
    name: str
    url: str
    transport: str = "streamablehttp"
    auth_type: str | None = None
    enabled: bool = True
    reachable: bool = False
    state: str = "pending"
    capabilities: dict[str, Any] = Field(default_factory=dict)
    last_seen: float | None = None
    created_at: float = Field(default_factory=time.time)
    updated_at: float = Field(default_factory=time.time)


# --------------------------------------------------------------------- tools

class ToolCreate(_Entity):
    name: str
    display_name: str | None = None
    integration_type: Literal["MCP", "REST", "A2A", "GRPC"] = "REST"
    request_type: Literal["GET", "POST", "PUT", "PATCH", "DELETE"] = "POST"
    url: str | None = None
    input_schema: dict[str, Any] = Field(default_factory=lambda: {"type": "object", "properties": {}})
    output_schema: dict[str, Any] | None = None
    annotations: dict[str, Any] = Field(default_factory=dict)
    headers: dict[str, str] = Field(default_factory=dict)
    auth_type: str | None = None
    auth_value: dict[str, Any] | None = None
    jsonpath_filter: str | None = None
    gateway_id: str | None = None
    enabled: bool = True

    @field_validator("name")
    @classmethod
    def _check_name(cls, v: str) -> str:
        if not v or len(v) > 255:
            raise ValueError("tool name must be 1-255 chars")
        return v


class ToolUpdate(BaseModel):
    display_name: str | None = None
    custom_name: str | None = None
    description: str | None = None
    url: str | None = None
    request_type: str | None = None
    input_schema: dict[str, Any] | None = None
    output_schema: dict[str, Any] | None = None
    annotations: dict[str, Any] | None = None
    headers: dict[str, str] | None = None
    auth_type: str | None = None
    auth_value: dict[str, Any] | None = None
    jsonpath_filter: str | None = None
    enabled: bool | None = None
    tags: list[str] | None = None
    visibility: Visibility | None = None


class ToolRead(_Entity):
    id: str
    name: str  # effective name (custom_name or original)
    original_name: str
    display_name: str | None = None
    integration_type: str = "REST"
    request_type: str = "POST"
    url: str | None = None
    input_schema: dict[str, Any] = Field(default_factory=dict)
    output_schema: dict[str, Any] | None = None
    annotations: dict[str, Any] = Field(default_factory=dict)
    gateway_id: str | None = None
    enabled: bool = True
    reachable: bool = True
    created_at: float = 0.0
    updated_at: float = 0.0


# ----------------------------------------------------------------- resources

class ResourceCreate(_Entity):
    uri: str
    name: str
    mime_type: str | None = None
    uri_template: str | None = None
    content: str | None = None
    is_binary: bool = False
    gateway_id: str | None = None
    enabled: bool = True


class ResourceUpdate(BaseModel):
    name: str | None = None
    description: str | None = None
    mime_type: str | None = None
    content: str | None = None
    enabled: bool | None = None
    tags: list[str] | None = None
    visibility: Visibility | None = None


class ResourceRead(_Entity):
    id: str
    uri: str
    name: str
    mime_type: str | None = None
    uri_template: str | None = None
    size: int | None = None
    gateway_id: str | None = None
    enabled: bool = True
    created_at: float = 0.0
    updated_at: float = 0.0


# ------------------------------------------------------------------- prompts

class PromptArgument(BaseModel):
    name: str
    description: str | None = None
    required: bool = False


class PromptCreate(_Entity):
    name: str
    template: str
    arguments: list[PromptArgument] = Field(default_factory=list)
    gateway_id: str | None = None
    enabled: bool = True


class PromptUpdate(BaseModel):
    description: str | None = None
    template: str | None = None
    arguments: list[PromptArgument] | None = None
    enabled: bool | None = None
    tags: list[str] | None = None
    visibility: Visibility | None = None


class PromptRead(_Entity):
    id: str
    name: str
    template: str
    arguments: list[PromptArgument] = Field(default_factory=list)
    gateway_id: str | None = None
    enabled: bool = True
    created_at: float = 0.0
    updated_at: float = 0.0


# ------------------------------------------------------------------- servers

class ServerCreate(_Entity):
    name: str
    icon: str | None = None
    associated_tools: list[str] = Field(default_factory=list)
    associated_resources: list[str] = Field(default_factory=list)
    associated_prompts: list[str] = Field(default_factory=list)
    enabled: bool = True


class ServerUpdate(BaseModel):
    name: str | None = None
    description: str | None = None
    icon: str | None = None
    associated_tools: list[str] | None = None
    associated_resources: list[str] | None = None
    associated_prompts: list[str] | None = None
    enabled: bool | None = None
    tags: list[str] | None = None
    visibility: Visibility | None = None


class ServerRead(_Entity):
    id: str
    name: str
    icon: str | None = None
    associated_tools: list[str] = Field(default_factory=list)
    associated_resources: list[str] = Field(default_factory=list)
    associated_prompts: list[str] = Field(default_factory=list)
    enabled: bool = True
    created_at: float = 0.0
    updated_at: float = 0.0


# ----------------------------------------------------------------- A2A agents

class A2AAgentCreate(_Entity):
    name: str
    endpoint_url: str
    agent_type: Literal["jsonrpc", "openai", "anthropic", "custom", "tpu_local"] = "jsonrpc"
    protocol_version: str = "1.0"
    capabilities: dict[str, Any] = Field(default_factory=dict)
    config: dict[str, Any] = Field(default_factory=dict)
    auth_type: str | None = None
    auth_value: dict[str, Any] | None = None
    enabled: bool = True


class A2AAgentRead(_Entity):
    id: str
    name: str
    slug: str
    endpoint_url: str
    agent_type: str = "jsonrpc"
    protocol_version: str = "1.0"
    capabilities: dict[str, Any] = Field(default_factory=dict)
    enabled: bool = True
    reachable: bool = True
    created_at: float = 0.0
    updated_at: float = 0.0
