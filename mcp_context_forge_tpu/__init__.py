"""mcp-context-forge-tpu: a TPU-native MCP gateway framework.

A ground-up rebuild of the capability set of IBM/mcp-context-forge (an MCP
gateway / registry / proxy federating MCP servers, A2A agents and REST APIs
behind one authenticated endpoint — see /root/reference/mcpgateway/__init__.py:6-12)
plus a genuinely new component the reference lacks: an in-tree ``tpu_local``
LLM provider — a JAX/XLA inference engine sharded over a TPU slice with
continuous batching and a paged KV cache — that serves the LLM proxy, the A2A
chat routing and the LLM-backed plugins without any outbound GPU/SaaS endpoint.

Architecture is TPU-first and dependency-light by design:

- HTTP stack: aiohttp (no FastAPI/granian); middleware chain + JSON-RPC
  dispatcher + streamable-HTTP/SSE/WS transports built in-tree.
- Persistence: sqlite3 (stdlib) behind an async repository layer (no
  SQLAlchemy); in-tree migration runner.
- Coordination: pluggable EventBus/Lease abstractions (memory backend
  in-proc; file/socket backends for multi-worker) instead of Redis.
- Compute: jax + pjit/NamedSharding over a Mesh, Pallas kernels for the
  attention hot path, XLA collectives over ICI/DCN as the communication
  backend.
"""

__version__ = "0.1.0"

PROTOCOL_VERSION = "2025-06-18"
"""Latest MCP protocol revision this gateway speaks."""

SUPPORTED_PROTOCOL_VERSIONS = ("2024-11-05", "2025-03-26", "2025-06-18")
