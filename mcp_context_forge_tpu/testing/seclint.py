"""In-tree static security linter (bandit/semgrep analog, SURVEY §5.2).

The reference gates CI on bandit + semgrep rule packs; neither tool is in
this image, so the high-signal rules are re-implemented over ``ast``:

- S001 eval/exec
- S002 shell execution (os.system/os.popen, subprocess ``shell=True``)
- S003 unsafe deserialization (pickle/marshal loads)
- S004 yaml.load without an explicit Safe loader
- S005 weak hash (md5/sha1) — allowlist non-crypto uses with a trailing
       ``# seclint: allow S005 <reason>`` comment
- S006 SQL built by interpolation (f-string/%/+/.format) passed straight
       to an execute/fetch call — the codebase contract is ``?`` params
- S007 tempfile.mktemp (TOCTOU)
- S008 ``assert`` used for auth/permission enforcement in non-test code
       (stripped under ``python -O``)

Findings fail the suite via ``tests/security/test_seclint.py``; suppress a
true-but-accepted finding with the trailing allow comment so every
exception is visible and greppable, exactly like ``# nosec``.

CLI: ``python -m mcp_context_forge_tpu.testing.seclint [path...]``
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path

_ALLOW_RE = re.compile(r"#\s*seclint:\s*allow\s+(S\d{3})")
_FILE_ALLOW_RE = re.compile(r"#\s*seclint:\s*file-allow\s+(S\d{3})")

_SHELL_FUNCS = {("os", "system"), ("os", "popen")}
_PICKLE_FUNCS = {("pickle", "load"), ("pickle", "loads"),
                 ("marshal", "load"), ("marshal", "loads")}
_WEAK_HASHES = {"md5", "sha1"}
_SQL_METHODS = {"execute", "executemany", "executescript",
                "fetchone", "fetchall", "fetchval"}
_AUTH_HINTS = re.compile(r"admin|permission|auth|token|scope|secret", re.I)


@dataclass
class Finding:
    rule: str
    path: str
    lineno: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.lineno}: {self.rule} {self.message}"


def _dotted(node: ast.AST) -> tuple[str, ...]:
    """('os','path','join') for os.path.join; () when not a plain name path."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _own_statements(body: list[ast.stmt]) -> list[ast.AST]:
    """All nodes in ``body`` excluding nested function/class scopes."""
    out: list[ast.AST] = []
    stack: list[ast.AST] = [n for n in body if not isinstance(n, _SCOPE_NODES)]
    while stack:
        node = stack.pop()
        out.append(node)
        stack.extend(child for child in ast.iter_child_nodes(node)
                     if not isinstance(child, _SCOPE_NODES))
    return out


def _is_clean(node: ast.AST, clean: set[str]) -> bool:
    """True when the expression provably contains no tainted data: constant
    strings, variables only ever assigned clean strings, concatenation /
    f-strings / ``sep.join(...)`` of clean parts."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str)
    if isinstance(node, ast.Name):
        return node.id in clean
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_clean(e, clean) for e in node.elts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Mod)):
        return _is_clean(node.left, clean) and _is_clean(node.right, clean)
    if isinstance(node, ast.JoinedStr):
        return all(_is_clean(v.value, clean) for v in node.values
                   if isinstance(v, ast.FormattedValue))
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join" and len(node.args) == 1
            and _is_clean(node.func.value, clean)):
        arg = node.args[0]
        if isinstance(arg, (ast.GeneratorExp, ast.ListComp)):
            return _is_clean(arg.elt, clean)
        return _is_clean(arg, clean)
    return False


def _clean_vars(body: list[ast.stmt],
                params: tuple[str, ...] = ()) -> tuple[set[str], set[str]]:
    """(assigned, clean) for the scope.

    Fixed-point: a local is clean iff every assignment to it is clean.
    ``params`` (function arguments) bind as opaque so a parameter
    shadowing a clean outer constant cannot launder taint; ``assigned``
    lets the caller distinguish "tracked and tainted" from "unknown"
    (imports, builtins) — only tracked-tainted names are worth flagging
    when passed bare.
    """
    assigns: dict[str, list[ast.AST]] = {}
    opaque = ast.Call(func=ast.Name(id="<opaque>", ctx=ast.Load()),
                      args=[], keywords=[])

    def record(target: ast.expr, value: ast.AST) -> None:
        if isinstance(target, ast.Name):
            assigns.setdefault(target.id, []).append(value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            # pair elementwise when the value is a matching literal
            # (``a, b = [], []``); otherwise the unpacking is opaque
            if isinstance(value, (ast.Tuple, ast.List)) and \
                    len(value.elts) == len(target.elts):
                for el, val in zip(target.elts, value.elts):
                    record(el, val)
            else:
                for el in target.elts:
                    record(el, opaque)

    for name in params or ():
        assigns.setdefault(name, []).append(opaque)

    for node in _own_statements(body):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                record(t, node.value)
        elif isinstance(node, ast.AugAssign):
            record(node.target, node.value)
        elif isinstance(node, (ast.AnnAssign, ast.NamedExpr)) and node.value:
            record(node.target, node.value)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            record(node.target, opaque)     # loop over unknown iterable
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    record(item.optional_vars, opaque)
        elif isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp,
                               ast.DictComp)):
            for comp in node.generators:
                record(comp.target, opaque)
        elif (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)
              and isinstance(node.value.func, ast.Attribute)
              and isinstance(node.value.func.value, ast.Name)
              and node.value.func.attr in ("append", "extend", "insert")
              and node.value.args):
            # mutations count as assignments for list cleanliness
            arg = node.value.args[-1]
            record(ast.Name(id=node.value.func.value.id, ctx=ast.Store()), arg)
    # optimistic start (all locals clean), then strip any var with a
    # non-clean assignment until stable; self-reference (sql += "...")
    # stays clean as long as every fragment is
    clean = set(assigns)
    while True:
        nxt = {v for v in clean
               if all(_is_clean(e, clean) for e in assigns[v])}
        if nxt == clean:
            return set(assigns), clean
        clean = nxt


class _Scanner(ast.NodeVisitor):
    def __init__(self, path: str, allowed: dict[int, set[str]]):
        self.path = path
        self.allowed = allowed
        self.findings: list[Finding] = []
        self._scopes: list[tuple[set[str], set[str]]] = []

    def _resolve(self) -> tuple[set[str], set[str]]:
        """(assigned, clean) with innermost-wins shadowing: a clean outer
        binding must not launder a tainted inner rebinding of the name."""
        assigned: set[str] = set()
        clean: set[str] = set()
        for scope_assigned, scope_clean in self._scopes:  # outer -> inner
            assigned |= scope_assigned
            clean -= scope_assigned          # inner rebinding shadows outer
            clean |= scope_clean
        return assigned, clean

    def visit_Module(self, node: ast.Module) -> None:
        self._scopes.append(_clean_vars(node.body))
        self.generic_visit(node)
        self._scopes.pop()

    @staticmethod
    def _param_names(a: ast.arguments) -> tuple[str, ...]:
        return tuple(arg.arg for arg in
                     [*a.posonlyargs, *a.args, *a.kwonlyargs,
                      *([a.vararg] if a.vararg else []),
                      *([a.kwarg] if a.kwarg else [])])

    def _visit_scope(self, node) -> None:
        params = self._param_names(node.args)
        self._scopes.append(_clean_vars(node.body, params))
        self.generic_visit(node)
        self._scopes.pop()

    visit_FunctionDef = _visit_scope
    visit_AsyncFunctionDef = _visit_scope

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # a lambda's params are a scope too: `lambda db, sql: db.execute(sql)`
        # must flag exactly like the def spelling
        self._scopes.append((set(self._param_names(node.args)), set()))
        self.generic_visit(node)
        self._scopes.pop()

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        lineno = getattr(node, "lineno", 0)
        if rule in self.allowed.get(lineno, set()):
            return
        self.findings.append(Finding(rule, self.path, lineno, message))

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)
        name = dotted[-1] if dotted else ""

        if dotted in (("eval",), ("exec",)):
            self._flag("S001", node, f"use of {name}()")
        if dotted in _SHELL_FUNCS:
            self._flag("S002", node, f"shell execution via {'.'.join(dotted)}")
        for kw in node.keywords:
            if (kw.arg == "shell" and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True):
                self._flag("S002", node, "subprocess call with shell=True")
        if dotted in _PICKLE_FUNCS:
            self._flag("S003", node,
                       f"unsafe deserialization: {'.'.join(dotted)}")
        if len(dotted) >= 2 and dotted[-2:] == ("yaml", "load"):
            loader: ast.AST | None = None
            if len(node.args) >= 2:
                loader = node.args[1]
            for kw in node.keywords:
                if kw.arg == "Loader":
                    loader = kw.value
            loader_name = _dotted(loader)[-1] if loader is not None and \
                _dotted(loader) else ""
            if "Safe" not in loader_name:
                self._flag("S004", node,
                           "yaml.load without a Safe loader "
                           "(use yaml.safe_load or Loader=yaml.SafeLoader)")
        if len(dotted) >= 1 and name in _WEAK_HASHES and \
                dotted[0] in ("hashlib", name):
            self._flag("S005", node, f"weak hash {name} "
                       "(allow non-crypto uses explicitly)")
        if name in _SQL_METHODS and node.args:
            sql = node.args[0]
            dynamic = isinstance(sql, (ast.JoinedStr, ast.BinOp)) or (
                isinstance(sql, ast.Call)
                and isinstance(sql.func, ast.Attribute)
                and sql.func.attr in ("format", "join"))
            assigned, clean = self._resolve()
            tainted_name = (isinstance(sql, ast.Name)
                            and sql.id in assigned
                            and sql.id not in clean)
            if tainted_name or (dynamic and not _is_clean(sql, clean)):
                self._flag("S006", node,
                           f"{name}() with interpolated SQL "
                           "(tainted or unprovable fragment)")
        if dotted == ("tempfile", "mktemp"):
            self._flag("S007", node, "tempfile.mktemp is TOCTOU-unsafe")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        text = ast.dump(node.test)
        if _AUTH_HINTS.search(text):
            self._flag("S008", node,
                       "assert used for auth/permission logic "
                       "(stripped under python -O)")
        self.generic_visit(node)


def _allow_directives(source: str) -> tuple[dict[int, set[str]], set[str]]:
    """Per-line and file-level allow directives from REAL comments only —
    a string literal containing the directive text must not whitelist
    anything (tokenized, the way bandit matches ``# nosec``).

    File-level directives also count when they appear in the module
    docstring header (first statement), where multi-line policy notes
    naturally live.
    """
    import io
    import tokenize

    allowed: dict[int, set[str]] = {}
    file_allowed: set[str] = set()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return allowed, file_allowed
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            for m in _ALLOW_RE.finditer(tok.string):
                allowed.setdefault(tok.start[0], set()).add(m.group(1))
            if tok.start[0] <= 30:
                for m in _FILE_ALLOW_RE.finditer(tok.string):
                    file_allowed.add(m.group(1))
    # the REAL module docstring (per ast, not "a string on line 1" — an
    # assigned string literal must not launder directives) may also carry
    # file-level directives: that's where policy notes naturally live
    try:
        doc = ast.get_docstring(ast.parse(source), clean=False)
    except SyntaxError:
        doc = None
    if doc:
        for m in _FILE_ALLOW_RE.finditer(doc):
            file_allowed.add(m.group(1))
    return allowed, file_allowed


def scan_file(path: Path) -> list[Finding]:
    source = path.read_text()
    allowed, file_allowed = _allow_directives(source)
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Finding("S000", str(path), exc.lineno or 0, "syntax error")]
    scanner = _Scanner(str(path), allowed)
    scanner.visit(tree)
    return [f for f in scanner.findings if f.rule not in file_allowed]


def scan_tree(root: Path) -> list[Finding]:
    findings: list[Finding] = []
    for path in sorted(root.rglob("*.py")):
        findings.extend(scan_file(path))
    return findings


def main(argv: list[str]) -> int:
    roots = [Path(a) for a in argv] or [Path(__file__).resolve().parent.parent]
    findings: list[Finding] = []
    for root in roots:
        findings.extend(scan_tree(root) if root.is_dir() else scan_file(root))
    for f in findings:
        print(f)
    print(f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    import sys

    raise SystemExit(main(sys.argv[1:]))
