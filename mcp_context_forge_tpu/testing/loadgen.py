"""Multi-process HTTP load-generator worker (north-star 1k-concurrency).

The driver target is 1,000 *concurrent* MCP tool-calls (BASELINE.json).
One asyncio loop juggling the server AND 1000 client tasks measures its
own scheduling delay, not the gateway — so ``bench.py`` spawns N worker
*processes* of this module, each holding ``concurrency`` real TCP
connections, and merges their reports. The reference drives the same
scale with Locust worker processes (`/root/reference/docs/release/
benchmark.md:21`, `tests/load/locustfile.py`).

Protocol: argv[1] is a JSON spec; the worker prints ONE JSON line:
``{"latencies_ms": [...], "failures": int, "wall_s": float,
"first_ts": float, "last_ts": float, "errors": {reason: count}}``.

Spec fields:
    base          http://host:port
    mode          "tools_call" | "chat"
    tool          tool name (tools_call mode)
    model         model name (chat mode)
    max_tokens    completion budget (chat mode)
    total         requests this worker issues
    concurrency   in-flight cap this worker holds
    worker        worker index (payload uniqueness)
    user/password basic auth
    ramp_s        sleep before first request (stagger process starts)

Workers are pure clients — they never import jax (launch with
``JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=`` anyway: the axon
sitecustomize hook runs at every interpreter start and can hang when the
TPU tunnel is down).
"""

from __future__ import annotations

import asyncio
import json
import sys
import time
from collections import Counter


async def run_worker(spec: dict) -> dict:
    import aiohttp

    base = spec["base"]
    mode = spec.get("mode", "tools_call")
    total = int(spec["total"])
    concurrency = int(spec["concurrency"])
    widx = int(spec.get("worker", 0))
    auth = aiohttp.BasicAuth(spec.get("user", "admin"),
                             spec.get("password", "changeme"))
    timeout = aiohttp.ClientTimeout(total=float(spec.get("timeout_s", 300)))

    latencies: list[float] = []
    errors: Counter = Counter()
    semaphore = asyncio.Semaphore(concurrency)
    first_ts = last_ts = 0.0

    async def one(session: aiohttp.ClientSession, i: int) -> None:
        nonlocal first_ts, last_ts
        if mode == "chat":
            path, payload = "/v1/chat/completions", {
                "model": spec.get("model", ""),
                "messages": [{"role": "user",
                              "content": f"w{widx} request {i}: say hi"}],
                "max_tokens": int(spec.get("max_tokens", 16))}
        else:
            path, payload = "/mcp", {
                "jsonrpc": "2.0", "id": f"w{widx}-{i}",
                "method": "tools/call",
                "params": {"name": spec["tool"],
                           "arguments": {"n": i,
                                         "text": f"payload w{widx} {i}"}}}
        async with semaphore:
            started = time.monotonic()
            if not first_ts:
                first_ts = time.time()
            try:
                async with session.post(base + path, json=payload,
                                        auth=auth) as resp:
                    body = await resp.json()
                if mode == "chat":
                    ok = resp.status == 200 and bool(body.get("choices"))
                else:
                    ok = (resp.status == 200 and "result" in body
                          and not body["result"].get("isError"))
                if not ok:
                    errors[f"http_{resp.status}"] += 1
            except Exception as exc:
                errors[type(exc).__name__] += 1
            latencies.append((time.monotonic() - started) * 1000)
            last_ts = time.time()

    await asyncio.sleep(float(spec.get("ramp_s", 0)))
    connector = aiohttp.TCPConnector(limit=concurrency)
    wall_start = time.monotonic()
    async with aiohttp.ClientSession(connector=connector,
                                     timeout=timeout) as session:
        await asyncio.gather(*[one(session, i) for i in range(total)])
    wall = time.monotonic() - wall_start
    return {"latencies_ms": [round(x, 3) for x in latencies],
            "failures": sum(errors.values()), "wall_s": round(wall, 3),
            "first_ts": first_ts, "last_ts": last_ts,
            "errors": dict(errors)}


def main() -> None:
    spec = json.loads(sys.argv[1])
    print(json.dumps(asyncio.run(run_worker(spec))))


if __name__ == "__main__":
    main()
