"""AST mutation testing: generate single-fault mutants, check the oracles kill them.

Reference analog: `run_mutmut.py` (mutmut campaign over mcpgateway/ with a
kill-rate gate). mutmut is not in this image, so this is a from-scratch
mutator built on `ast`: each mutant is the original module source with
exactly ONE fault injected (comparison flipped, boolean operator swapped,
`not` dropped, constant nudged, `raise` silenced, `startswith`/`endswith`
confused). A mutant is *killed* when the module's behavioral oracle fails
against the mutated module object; survivors are reported so equivalent
mutants can be allowlisted explicitly in the test.

Usage (test): see `tests/mutation/test_mutation_kill.py`.
Usage (CLI):  `python -m mcp_context_forge_tpu.testing.mutation jsonrpc`
"""

from __future__ import annotations

import ast
import copy
import types
from dataclasses import dataclass, field
from typing import Any, Callable

# comparison operator -> its off-by-one/negation confusion
_COMPARE_SWAPS: dict[type, type] = {
    ast.Eq: ast.NotEq, ast.NotEq: ast.Eq,
    ast.Lt: ast.LtE, ast.LtE: ast.Lt,
    ast.Gt: ast.GtE, ast.GtE: ast.Gt,
    ast.In: ast.NotIn, ast.NotIn: ast.In,
    ast.Is: ast.IsNot, ast.IsNot: ast.Is,
}

_ATTR_SWAPS = {"startswith": "endswith", "endswith": "startswith"}


@dataclass
class Mutant:
    index: int
    description: str
    lineno: int
    source: str


@dataclass
class CampaignReport:
    module: str
    total: int
    survivors: list[Mutant] = field(default_factory=list)
    invalid: int = 0  # mutants that failed to even exec (count as killed)

    @property
    def killed(self) -> int:
        return self.total - len(self.survivors)


class _Mutator(ast.NodeTransformer):
    """One pass = one (possibly applied) mutation.

    With ``apply_at=None`` it only enumerates mutation sites into
    ``found``; with ``apply_at=i`` it rewrites the i-th site.
    """

    def __init__(self, apply_at: int | None = None):
        self.apply_at = apply_at
        self.counter = 0
        self.found: list[tuple[str, int]] = []
        self.applied: str | None = None

    def _site(self, description: str, lineno: int) -> bool:
        idx = self.counter
        self.counter += 1
        self.found.append((description, lineno))
        if idx == self.apply_at:
            self.applied = description
            return True
        return False

    def visit_Compare(self, node: ast.Compare) -> ast.AST:
        self.generic_visit(node)
        for i, op in enumerate(node.ops):
            swap = _COMPARE_SWAPS.get(type(op))
            if swap is None:
                continue
            desc = f"{type(op).__name__}->{swap.__name__}"
            if self._site(desc, node.lineno):
                new = copy.deepcopy(node)
                new.ops[i] = swap()
                return new
        return node

    def visit_BoolOp(self, node: ast.BoolOp) -> ast.AST:
        self.generic_visit(node)
        swap = ast.Or if isinstance(node.op, ast.And) else ast.And
        desc = f"{type(node.op).__name__}->{swap.__name__}"
        if self._site(desc, node.lineno):
            new = copy.deepcopy(node)
            new.op = swap()
            return new
        return node

    def visit_UnaryOp(self, node: ast.UnaryOp) -> ast.AST:
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            if self._site("drop-not", node.lineno):
                return node.operand
        return node

    def visit_Constant(self, node: ast.Constant) -> ast.AST:
        if node.value is True or node.value is False:
            if self._site(f"{node.value}->{not node.value}", node.lineno):
                return ast.copy_location(ast.Constant(not node.value), node)
        elif isinstance(node.value, int) and not isinstance(node.value, bool):
            if self._site(f"{node.value}->{node.value + 1}", node.lineno):
                return ast.copy_location(ast.Constant(node.value + 1), node)
        return node

    def visit_Raise(self, node: ast.Raise) -> ast.AST:
        self.generic_visit(node)
        if self._site("raise->pass", node.lineno):
            return ast.copy_location(ast.Pass(), node)
        return node

    def visit_Attribute(self, node: ast.Attribute) -> ast.AST:
        self.generic_visit(node)
        swap = _ATTR_SWAPS.get(node.attr)
        if swap is not None:
            if self._site(f"{node.attr}->{swap}", node.lineno):
                new = copy.deepcopy(node)
                new.attr = swap
                return new
        return node


def generate_mutants(source: str) -> list[Mutant]:
    """Every single-fault variant of ``source`` (docstrings untouched)."""
    tree = ast.parse(source)
    scan = _Mutator(apply_at=None)
    scan.visit(copy.deepcopy(tree))
    mutants = []
    for idx, (desc, lineno) in enumerate(scan.found):
        mut = _Mutator(apply_at=idx)
        mutated = mut.visit(copy.deepcopy(tree))
        ast.fix_missing_locations(mutated)
        mutants.append(Mutant(index=idx, description=desc, lineno=lineno,
                              source=ast.unparse(mutated)))
    return mutants


def load_module_from_source(source: str, module_name: str, package: str) -> types.ModuleType:
    """Exec ``source`` as a throwaway module, leaving the real one untouched.

    The module is registered in sys.modules under a reserved alias only for
    the duration of the exec (dataclass/typing machinery resolves
    ``cls.__module__`` through sys.modules); ``package`` makes relative
    imports inside the module resolve.
    """
    import sys

    alias = f"{module_name}__mutant__"
    mod = types.ModuleType(alias)
    mod.__package__ = package
    code = compile(source, f"<mutant:{module_name}>", "exec")
    sys.modules[alias] = mod
    try:
        exec(code, mod.__dict__)  # noqa: S102 - in-tree test tooling  # seclint: allow S001 in-tree mutant loader
    finally:
        sys.modules.pop(alias, None)
    return mod


def run_campaign(module_name: str, source: str, package: str,
                 oracle: Callable[[types.ModuleType], Any],
                 skip_lines: frozenset[int] = frozenset(),
                 line_range: tuple[int, int] | None = None) -> CampaignReport:
    """Run ``oracle`` against every mutant of ``source``.

    The oracle gets the (mutated) module object and must raise on any
    behavioral deviation. ``skip_lines`` excludes sites on lines known to be
    outside the oracle's contract (e.g. log formatting); ``line_range``
    restricts the campaign to one region (e.g. a single class) so a focused
    oracle is not graded on code it never exercises.
    """
    baseline = load_module_from_source(source, module_name, package)
    oracle(baseline)  # the oracle must pass on the unmutated module

    mutants = [m for m in generate_mutants(source)
               if m.lineno not in skip_lines
               and (line_range is None or line_range[0] <= m.lineno <= line_range[1])]
    report = CampaignReport(module=module_name, total=len(mutants))
    for m in mutants:
        try:
            mod = load_module_from_source(m.source, module_name, package)
        except Exception:
            report.invalid += 1
            continue
        try:
            oracle(mod)
        except Exception:
            pass
        else:
            report.survivors.append(m)
    return report


def main(argv: list[str]) -> int:
    from . import oracles

    targets = oracles.TARGETS if not argv else {k: oracles.TARGETS[k] for k in argv}
    worst = 1.0
    for name, target in targets.items():
        report = target.run()
        # allowlisted equivalent mutants (line- or marker-anchored) don't
        # count against the gate — same rule as the pytest tier
        real = [s for s in report.survivors
                if not target.is_equivalent(s.lineno)]
        rate = 1.0 if not report.total else (report.total - len(real)) / report.total
        worst = min(worst, rate)
        print(f"{name}: {report.total - len(real)}/{report.total} killed "
              f"({rate:.1%}), {report.invalid} invalid")
        for s in report.survivors:
            mark = (" (allowlisted)"
                    if target.is_equivalent(s.lineno) else "")
            print(f"  survivor L{s.lineno}: {s.description}{mark}")
    return 0 if worst >= 0.85 else 1


if __name__ == "__main__":
    import sys

    raise SystemExit(main(sys.argv[1:]))
