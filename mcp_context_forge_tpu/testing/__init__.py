"""In-tree test-quality tooling (mutation testing).

Parity: the reference drives mutmut via `run_mutmut.py` at its repo root
(SURVEY §5.2). No mutmut in this image, so the mutator is in-tree: an
AST-level mutant generator + oracle runner (`mutation.py`) with behavioral
oracles for the security-critical pure-logic modules (`oracles.py`).
"""

from .mutation import Mutant, generate_mutants, run_campaign  # noqa: F401
