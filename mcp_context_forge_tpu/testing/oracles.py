"""Behavioral oracles for the mutation campaigns.

Each oracle is a dense re-statement of a module's CONTRACT (not its code):
it must pass on the real module and fail on any single-fault mutant that
changes observable behavior. Targets are the pure-logic, security-critical
modules where a silent fault is most expensive — JSON-RPC validation and
the RBAC permission check (reference gates the same surfaces through its
mutmut run, `run_mutmut.py`).

Oracles signal a killed mutant by raising — plain ``assert`` is their
mechanism, not auth enforcement, and they never run under ``python -O``.
# seclint: file-allow S008
"""

from __future__ import annotations

import ast
import types
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable

from .mutation import CampaignReport, run_campaign

_PKG_ROOT = Path(__file__).resolve().parent.parent


def _class_line_range(source: str, class_name: str) -> tuple[int, int]:
    for node in ast.walk(ast.parse(source)):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return node.lineno, node.end_lineno or node.lineno
    raise ValueError(f"class {class_name} not found")


@dataclass
class MutationTarget:
    rel_path: str                 # package-relative source path
    module_name: str
    package: str
    oracle: Callable[[types.ModuleType], None]
    class_name: str | None = None  # restrict campaign to this class
    equivalent_lines: frozenset[int] = field(default_factory=frozenset)
    # CONTENT-anchored equivalence exemptions: a surviving mutant whose
    # ORIGINAL source line contains one of these substrings is accepted
    # as behaviorally equivalent. Use these instead of equivalent_lines —
    # absolute line numbers silently stop exempting (or exempt the WRONG
    # line) whenever unrelated edits shift the file.
    equivalent_markers: tuple[str, ...] = ()

    def source(self) -> str:
        """THE source the campaign mutates — every equivalence check
        must read the same bytes (one derivation, three call sites)."""
        return (_PKG_ROOT / self.rel_path).read_text()

    def is_equivalent(self, lineno: int, source: str | None = None) -> bool:
        if lineno in self.equivalent_lines:
            return True
        lines = (source if source is not None
                 else self.source()).splitlines()
        if not (1 <= lineno <= len(lines)):
            return False
        line = lines[lineno - 1]
        return any(marker in line for marker in self.equivalent_markers)

    def run(self) -> CampaignReport:
        source = self.source()
        line_range = (_class_line_range(source, self.class_name)
                      if self.class_name else None)
        return run_campaign(self.module_name, source, self.package, self.oracle,
                            line_range=line_range)


# --------------------------------------------------------------- jsonrpc

def jsonrpc_oracle(mod: types.ModuleType) -> None:
    # exact wire constants
    assert mod.PARSE_ERROR == -32700
    assert mod.INVALID_REQUEST == -32600
    assert mod.METHOD_NOT_FOUND == -32601
    assert mod.INVALID_PARAMS == -32602
    assert mod.INTERNAL_ERROR == -32603
    assert mod.REQUEST_CANCELLED == -32800
    assert mod.CONTENT_TOO_LARGE == -32801
    assert mod.UPSTREAM_UNAVAILABLE == -32003

    E = mod.JSONRPCError

    def rejects(payload, code=mod.INVALID_REQUEST):
        try:
            mod.RPCRequest.parse(payload)
        except E as exc:
            assert exc.code == code, (payload, exc.code)
        else:
            raise AssertionError(f"accepted {payload!r}")

    # JSONRPCError shape
    err = E(-32000, "boom").to_dict("id1")
    assert err == {"jsonrpc": "2.0", "id": "id1",
                   "error": {"code": -32000, "message": "boom"}}
    err = E(-32000, "boom", data={"k": 1}).to_dict(None)
    assert err["error"]["data"] == {"k": 1} and err["id"] is None
    assert mod.error_response(3, -32601, "nf")["error"]["code"] == -32601
    assert mod.result_response(7, {"ok": 1}) == {
        "jsonrpc": "2.0", "id": 7, "result": {"ok": 1}}

    # request validation
    rejects(None)
    rejects([])
    rejects("x")
    rejects({})                                   # no jsonrpc
    rejects({"jsonrpc": "2.0"})                   # no method
    rejects({"jsonrpc": "1.0", "method": "ping"})
    rejects({"jsonrpc": "2.0", "method": ""})
    rejects({"jsonrpc": "2.0", "method": 7})
    rejects({"jsonrpc": "2.0", "method": "m", "params": 3})
    rejects({"jsonrpc": "2.0", "method": "m", "params": "s"})
    rejects({"jsonrpc": "2.0", "method": "m", "id": True})
    rejects({"jsonrpc": "2.0", "method": "m", "id": {}})
    rejects({"jsonrpc": "2.0", "method": "m", "id": []})

    direct = mod.RPCRequest(method="m")   # direct construction = a call
    assert direct.is_notification is False and direct.params == {}

    r = mod.RPCRequest.parse({"jsonrpc": "2.0", "method": "ping", "id": 1})
    assert (r.method, r.id, r.is_notification, r.params) == ("ping", 1, False, {})
    r = mod.RPCRequest.parse({"jsonrpc": "2.0", "method": "n"})
    assert r.is_notification and r.id is None
    r = mod.RPCRequest.parse({"jsonrpc": "2.0", "method": "m", "id": None})
    assert not r.is_notification          # explicit null id is still a request
    r = mod.RPCRequest.parse({"jsonrpc": "2.0", "method": "m", "id": "s",
                              "params": None})
    assert r.params == {} and r.id == "s"
    r = mod.RPCRequest.parse({"jsonrpc": "2.0", "method": "m", "id": 1.5,
                              "params": [1, 2]})
    assert r.params == {"__args__": [1, 2]} and r.id == 1.5
    r = mod.RPCRequest.parse({"jsonrpc": "2.0", "method": "m",
                              "params": {"a": 1}})
    assert r.params == {"a": 1}

    # body parsing + size cap
    assert mod.parse_body(b'{"a": 1}') == {"a": 1}
    assert mod.parse_body(b"[1]", max_size=3) == [1]
    try:
        mod.parse_body(b"[1, 2]", max_size=3)
    except E as exc:
        assert exc.code == mod.CONTENT_TOO_LARGE
    else:
        raise AssertionError("size cap not enforced")
    assert mod.parse_body(b"[1, 2]") == [1, 2]   # default: no cap
    try:
        mod.parse_body(b"{nope")
    except E as exc:
        assert exc.code == mod.PARSE_ERROR
    else:
        raise AssertionError("parse error not raised")

    # response-message detection (elicitation replies on the POST channel)
    assert mod.is_response_message({"id": 1, "result": {}})
    assert mod.is_response_message({"id": 1, "error": {"code": -1}})
    assert not mod.is_response_message({"id": 1, "method": "m", "result": {}})
    assert not mod.is_response_message({"id": 1})
    assert not mod.is_response_message([1])
    assert not mod.is_response_message("x")

    # method registry
    reg = mod.MCPMethodRegistry()
    assert reg.is_known("tools/call") and reg.is_known("initialize")
    assert reg.is_known("notifications/cancelled")
    assert not reg.is_known("bogus/method")
    reg.register("x/custom")
    assert reg.is_known("x/custom")
    assert reg.is_notification("notifications/anything")
    assert not reg.is_notification("tools/list")
    assert not reg.is_notification("x-notifications/foo")
    for m in ("ping", "tools/list", "tools/call", "resources/list",
              "resources/read", "resources/subscribe", "resources/unsubscribe",
              "resources/templates/list", "prompts/list", "prompts/get",
              "roots/list", "completion/complete", "sampling/createMessage",
              "elicitation/create", "logging/setLevel"):
        assert m in mod.CORE_METHODS, m
    for m in ("notifications/initialized", "notifications/progress",
              "notifications/message", "notifications/roots/list_changed",
              "notifications/tools/list_changed",
              "notifications/resources/list_changed",
              "notifications/resources/updated",
              "notifications/prompts/list_changed"):
        assert m in mod.NOTIFICATION_METHODS, m


# ------------------------------------------------- RoleGrantResolver (RBAC)

def role_resolver_oracle(mod: types.ModuleType) -> None:
    """Contract of role-assignment permission resolution (role_service.py):
    global grants always apply, team grants only with membership, grants
    never escape the catalog, and no scope ever leaks across teams."""
    resolve = mod.RoleGrantResolver.resolve
    catalog = {"a.read", "a.write", "b.read", "c.run"}
    rows = [
        {"scope": "global", "scope_id": "", "permissions": '["a.read"]'},
        {"scope": "team", "scope_id": "t1", "permissions": '["a.write"]'},
        {"scope": "team", "scope_id": "t2", "permissions": '["b.read"]'},
        {"scope": "global", "scope_id": "", "permissions": '["ghost.perm"]'},
    ]
    assert resolve(rows, ["t1"], catalog) == {"a.read", "a.write"}
    assert resolve(rows, [], catalog) == {"a.read"}
    assert resolve(rows, ["t2"], catalog) == {"a.read", "b.read"}
    assert resolve(rows, ["t1", "t2"], catalog) == {"a.read", "a.write",
                                                    "b.read"}
    assert resolve(rows, ["t3"], catalog) == {"a.read"}
    assert resolve([], ["t1"], catalog) == set()
    # multi-permission rows resolve in full; catalog intersection applies
    many = [{"scope": "global", "scope_id": "",
             "permissions": '["a.read", "c.run", "x.never"]'}]
    assert resolve(many, [], catalog) == {"a.read", "c.run"}
    # a team grant needs BOTH conditions: team scope AND membership — a
    # global row with a stray scope_id must still apply
    stray = [{"scope": "global", "scope_id": "tX",
              "permissions": '["b.read"]'}]
    assert resolve(stray, [], catalog) == {"b.read"}


# ----------------------------------------------------- AuthContext (RBAC)

def auth_context_oracle(mod: types.ModuleType) -> None:
    AC = mod.AuthContext

    # plain user: only granted permissions; no spurious rotation flag
    # (a default-True flag would lock every identity out of the surface)
    user = AC(user="u@x", permissions={"tools.read"})
    assert user.password_change_required is False
    assert AC(user="u@x", password_change_required=True
              ).password_change_required is True
    assert user.can("tools.read")
    assert not user.can("tools.delete")
    assert not user.can("admin.all")
    user.require("tools.read")
    try:
        user.require("tools.delete")
    except mod.PermissionDenied:
        pass
    else:
        raise AssertionError("require() let a denied permission through")

    # admin shortcut applies ONLY to unscoped identities
    admin = AC(user="a@x", is_admin=True)
    assert admin.can("tools.delete") and admin.can("anything.at.all")

    # scoped token minted by an admin must NOT inherit admin power
    scoped = AC(user="a@x", is_admin=True, scoped=True,
                permissions={"tools.read"})
    assert scoped.can("tools.read")
    assert not scoped.can("tools.delete")
    assert not scoped.can("admin.all")

    # a scoped token that explicitly carries admin.all is a real admin token
    scoped_admin = AC(user="a@x", is_admin=False, scoped=True,
                      permissions={"admin.all"})
    assert scoped_admin.can("tools.delete")

    # admin.all grant acts as wildcard for unscoped users too
    granted = AC(user="u@x", permissions={"admin.all"})
    assert granted.can("plugins.manage")

    # defaults
    anon = AC(user="anon")
    assert not anon.can("tools.read")
    assert anon.via == "jwt" and not anon.scoped and not anon.is_admin
    assert anon.token_jti is None and anon.server_id is None


# ------------------------------------------------- int8 quantization

def quantize_oracle(mod: types.ModuleType) -> None:
    """Behavioral spec of quantize.py: exact scales, exact rounding, both
    matmul forms, gather, rule mapping. A surviving mutant here means a
    silent numerics fault in the serving weight path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    w = np.array([[1.0, -2.0], [3.0, 0.5], [-0.25, 4.0]], np.float32)
    leaf = mod.quantize_leaf(w, axis=0)
    assert leaf["q"].dtype == jnp.int8
    np.testing.assert_allclose(np.asarray(leaf["s"]),
                               [3.0 / 127, 4.0 / 127], rtol=1e-6)
    scales = np.asarray(leaf["s"])
    np.testing.assert_array_equal(
        np.asarray(leaf["q"]),
        np.round(w / scales[None]).astype(np.int8))
    recon = np.asarray(leaf["q"], np.float32) * scales[None]

    # all-zero weights hit the epsilon clamp EXACTLY (no zero-division)
    tiny = mod.quantize_leaf(np.zeros((2, 2), np.float32), axis=0)
    np.testing.assert_allclose(np.asarray(tiny["s"]), np.float32(1e-8),
                               rtol=0)

    # qmm: quant path equals x @ reconstruction; plain path exact
    x = jnp.asarray(np.array([[1.0, 0.0, 2.0]], np.float32))
    np.testing.assert_allclose(np.asarray(mod.qmm(x, leaf)),
                               np.asarray(x) @ recon, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(mod.qmm(x, jnp.asarray(w))),
                               np.asarray(x @ jnp.asarray(w)), rtol=1e-6)

    # per-ROW table (embedding) + transposed head form
    emb = np.array([[1.0, 2.0], [3.0, -4.0], [0.5, 0.25]], np.float32)
    leaf_e = mod.quantize_leaf(emb, axis=1)
    recon_e = (np.asarray(leaf_e["q"], np.float32)
               * np.asarray(leaf_e["s"])[:, None])
    xt = jnp.asarray(np.array([[1.0, -1.0]], np.float32))
    np.testing.assert_allclose(np.asarray(mod.qmm_t(xt, leaf_e)),
                               np.asarray(xt) @ recon_e.T, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(mod.qmm_t(xt, jnp.asarray(emb))),
                               np.asarray(xt) @ emb.T, rtol=1e-6)

    # gather: quantized rows reconstruct; plain rows pass through exactly
    rows = np.asarray(mod.embed_rows(leaf_e, jnp.asarray([2, 0])))
    np.testing.assert_allclose(rows, recon_e[[2, 0]], rtol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(mod.embed_rows(jnp.asarray(emb), jnp.asarray([1]))),
        emb[[1]])

    # discrimination + rule mapping
    assert mod.is_quant(leaf)
    assert not mod.is_quant(w) and not mod.is_quant({"q": 1})
    logical = mod.quantize_logical({"embed": "vocab_in",
                                    "norm": "replicated"})
    assert logical["embed"] == {"q": "vocab_in", "s": "scale_model"}
    assert logical["norm"] == "replicated"
    tree = mod.quantize_tree({"embed": emb,
                              "norm": np.ones((3,), np.float32)},
                             {"embed": "vocab_in", "norm": "replicated"})
    assert mod.is_quant(tree["embed"]) and not mod.is_quant(tree["norm"])
    # vocab_in reduces along axis 1 (per-ROW scales): must match leaf_e
    np.testing.assert_array_equal(np.asarray(tree["embed"]["q"]),
                                  np.asarray(leaf_e["q"]))
    # every matmul-weight rule reduces along axis 0 (per-OUT-channel)
    for name in ("vocab_out", "attn_qkv", "attn_out", "ffn_up", "ffn_down"):
        out = mod.quantize_tree({"w": w}, {"w": name})
        np.testing.assert_array_equal(np.asarray(out["w"]["q"]),
                                      np.asarray(leaf["q"]))
        expected_scale = ("scale_model"
                          if name in ("vocab_out", "attn_qkv", "ffn_up")
                          else "replicated")
        assert mod.quantize_logical({"w": name})["w"]["s"] == expected_scale

    abstract = jax.eval_shape(lambda: {"a": jnp.zeros((4,), jnp.int8),
                                       "b": jnp.zeros((2,), jnp.float32)})
    assert mod.param_bytes(abstract) == 4 + 8


# ---------------------------------------------------- RateLimiter

def rate_limiter_oracle(mod: types.ModuleType) -> None:
    """Token-bucket semantics: burst honored exactly, refill at rps,
    recency-ordered eviction, rps<=0 disables. A surviving mutant is a
    silent DoS-protection fault."""
    import time as _time

    RL = mod.RateLimiter

    # burst: exactly `burst` immediate requests pass, the next fails
    limiter = RL(rps=1, burst=3)
    assert [limiter.allow("k") for _ in range(4)] == [True, True, True, False]

    # refill: advance time by 2s at 5 rps -> 10 tokens, capped at burst 3
    limiter = RL(rps=5, burst=3)
    for _ in range(3):
        assert limiter.allow("k")
    assert not limiter.allow("k")
    tokens, last = limiter._buckets["k"]
    limiter._buckets["k"] = (tokens, last - 2.0)  # simulate 2s elapsed
    results = [limiter.allow("k") for _ in range(4)]
    assert results == [True, True, True, False], results

    # independent buckets per key
    limiter = RL(rps=1, burst=1)
    assert limiter.allow("a")
    assert limiter.allow("b")
    assert not limiter.allow("a")

    # disabled limiter always allows and stores nothing
    off = RL(rps=0, burst=1)
    assert all(off.allow("x") for _ in range(5))
    assert not off._buckets

    # recency-ordered eviction: oldest-seen key leaves first
    limiter = RL(rps=1, burst=1, max_buckets=3)
    for key in ("k0", "k1", "k2"):
        limiter.allow(key)
    limiter.allow("k0")          # refresh k0
    limiter.allow("k3")          # overflow -> evict k1 (oldest)
    assert "k1" not in limiter._buckets
    assert {"k0", "k2", "k3"} <= set(limiter._buckets)
    assert len(limiter._buckets) == 3

    # sweep prunes only refilled-to-full buckets (back-dated timestamps —
    # no wall-clock sleeps in a per-mutant campaign)
    limiter = RL(rps=100, burst=1)
    now = _time.monotonic()
    limiter._buckets["gone"] = (0.0, now - 1.0)   # refilled to full long ago
    limiter._buckets["hot"] = (0.0, now + 100)    # never full
    limiter._sweep(now)
    assert "gone" not in limiter._buckets
    assert "hot" in limiter._buckets
    # boundary: an EXACTLY-full bucket is state-free and must prune (the
    # documented sweep contract — recreating it at full burst is identical)
    limiter = RL(rps=1, burst=2)
    now = _time.monotonic()
    limiter._buckets["edge"] = (2.0, now)
    limiter._sweep(now)
    assert "edge" not in limiter._buckets


# -------------------------------------------------- PageAllocator

def page_allocator_oracle(mod: types.ModuleType) -> None:
    """KV-page bookkeeping spec: capacity math, refcounted sharing,
    prefix chains, LRU eviction, slot moves, trash-page reservation. A
    surviving mutant is silent KV corruption or a page leak."""
    PA = mod.PageAllocator

    # capacity: page 0 reserved, ceil-division page math
    alloc = PA(num_pages=8, page_size=4, max_slots=4, max_pages_per_slot=4)
    assert alloc.free_pages == 7 and alloc.pages_in_use == 0
    assert alloc.peak_pages_in_use == 0   # nothing allocated yet
    assert alloc.pages_needed(1) == 1 and alloc.pages_needed(4) == 1
    assert alloc.pages_needed(5) == 2
    assert alloc.can_allocate(28) and not alloc.can_allocate(29)

    # allocation consumes exactly ceil(tokens/page) pages; page 0 never
    # hands out
    assert alloc.allocate_slot(0, 9)  # 3 pages
    assert alloc.pages_in_use == 3 and alloc.free_pages == 4
    assert alloc.peak_pages_in_use == 3   # high-water mark tracks
    assert 0 not in alloc._slots[0]

    # per-slot cap enforced
    assert not alloc.allocate_slot(1, 17)  # 5 pages > max_pages_per_slot
    # pool exhaustion enforced
    assert alloc.allocate_slot(1, 16)      # 4 pages -> pool empty
    assert alloc.free_pages == 0
    assert not alloc.allocate_slot(2, 1)

    # growth happens by whole pages and respects both caps: grow_slot
    # returns the granted token capacity (pages * page_size)
    alloc.free_slot(1)
    assert alloc.free_pages == 4
    assert alloc.grow_slot(0, 12) >= 12    # still 3 pages
    assert alloc.pages_in_use == 3
    assert alloc.grow_slot(0, 13) >= 13    # grows to 4
    assert alloc.pages_in_use == 4
    assert alloc.grow_slot(0, 17) < 17     # per-slot cap
    assert alloc.pages_in_use == 4

    # free returns everything; the peak is MONOTONIC (a bench reading it
    # after the run must see the high-water mark, not the final state)
    alloc.free_slot(0)
    assert alloc.pages_in_use == 0 and alloc.free_pages == 7
    assert alloc.peak_pages_in_use == 7

    # prefix chains: register full pages, probe is read-only, match
    # refcounts, shared pages survive the owner's free
    alloc = PA(num_pages=8, page_size=4, max_slots=4, max_pages_per_slot=4)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]          # 2 full pages + 1 token
    assert alloc.allocate_slot(0, len(prompt))
    alloc.register_prefix(0, prompt)
    assert alloc.cached_pages == 2
    before_refs = dict(alloc._ref)
    before_in_use = alloc.pages_in_use
    assert alloc.probe_prefix(prompt) == 8        # full pages only
    assert alloc.pages_in_use == before_in_use    # probe took nothing
    assert alloc._ref == before_refs              # ...not even a refcount
    # a prompt sharing ONE page matches one page
    assert alloc.probe_prefix([1, 2, 3, 4, 99, 98, 97, 96, 95]) == 4
    # the last token never matches (at least one must prefill)
    assert alloc.probe_prefix([1, 2, 3, 4]) == 0

    hist, shared = alloc.match_prefix(prompt)
    assert hist == 8 and len(shared) == 2
    assert alloc.allocate_slot(1, len(prompt), prefix_pages=shared)
    assert alloc.prefix_hits == 1 and alloc.prefix_hit_tokens == 8
    # shared pages counted once, refcounted at exactly 2
    assert alloc.pages_in_use == 3 + 1 + 2 - 2    # 3 owner + 1 fresh
    assert alloc._ref[shared[0]] == 2
    alloc.free_slot(0)                            # owner leaves...
    assert alloc._ref[shared[0]] == 1             # one reference released
    table = alloc.tables()
    import numpy as np
    assert int(np.asarray(table)[1, 0]) == shared[0]  # ...sharer keeps pages

    # unmatched release drops the references again
    hist2, shared2 = alloc.match_prefix(prompt)
    assert hist2 == 8
    alloc.release_prefix(shared2)
    alloc.free_slot(1)
    # refcount zero + registered -> pages stay warm on the LRU, so the
    # free list alone shrinks but free_pages (incl. evictable) is full
    assert alloc.free_pages == 7
    # matching LRU-RESIDENT pages (ref entry deleted at zero) starts the
    # count from scratch: exactly one reference per matched page
    hist3, shared3 = alloc.match_prefix(prompt)
    assert hist3 == 8 and alloc._ref[shared3[0]] == 1
    alloc.release_prefix(shared3)
    assert alloc.free_pages == 7
    # an allocation EXACTLY covered by shared pages (zero fresh) is valid
    hist4, shared4 = alloc.match_prefix(prompt)
    assert alloc.allocate_slot(2, 8, prefix_pages=shared4)
    assert alloc.pages_in_use == 2
    alloc.free_slot(2)

    # eviction: allocation pressure reclaims LRU cache pages
    for slot in range(3):
        assert alloc.allocate_slot(slot, 8)       # 6 pages; evicts cache
    assert alloc.allocate_slot(3, 4)              # the 7th page
    assert alloc.free_pages == 0
    assert alloc.cached_pages <= 1                # chain broken by eviction

    # move_slot: pages follow the new id, old id empties
    alloc = PA(num_pages=8, page_size=4, max_slots=4, max_pages_per_slot=4)
    assert alloc.allocate_slot(3, 8)
    pages = list(alloc._slots[3])
    alloc.move_slot(3, 0)
    assert alloc._slots[0] == pages and 3 not in alloc._slots
    table = alloc.tables()
    assert int(np.asarray(table)[0, 0]) == pages[0]
    assert int(np.asarray(table)[3, 0]) == 0


def _dirty_tracking_spec(mod: types.ModuleType) -> None:
    """Dirty-row contract: the engine skips the block-table upload iff no
    row changed, so a mutant that over- or under-reports dirt is either a
    per-step upload regression or a stale device table (KV reads through
    wrong pages)."""
    import numpy as np

    PA = mod.PageAllocator
    alloc = PA(num_pages=8, page_size=4, max_slots=4, max_pages_per_slot=4)
    assert not alloc.dirty                       # fresh allocator is clean
    assert alloc.allocate_slot(0, 4)
    assert alloc.dirty                           # allocation dirties its row
    alloc.tables()
    assert not alloc.dirty                       # reading the table cleans

    # growth WITHIN the allocated pages is clean (no upload); crossing a
    # page boundary dirties exactly then
    assert alloc.grow_slot(0, 3) == 4
    assert not alloc.dirty
    assert alloc.grow_slot(0, 5) == 8
    assert alloc.dirty
    row = np.asarray(alloc.tables())[0]
    assert (row[:2] > 0).all() and (row[2:] == 0).all()

    # a cap-bound partial grant persists the pages it DID take
    assert alloc.grow_slot(0, 99) == 16          # capped by max_pages_per_slot
    assert alloc.slot_pages(0) == 4 and alloc.dirty
    alloc.tables()

    # ...and so does a POOL-DRY partial grant (distinct branch: free list
    # exhausted below both the target and the per-slot cap)
    dry = PA(num_pages=4, page_size=4, max_slots=4, max_pages_per_slot=8)
    assert dry.allocate_slot(0, 4) and dry.allocate_slot(1, 4)
    assert dry.grow_slot(0, 12) == 8             # wanted 3 pages, pool had 1
    assert dry.slot_pages(0) == 2 and dry.free_pages == 0
    assert dry.dirty

    # move and free both dirty; the freed row reads back as zeros
    alloc.move_slot(0, 2)
    assert alloc.dirty
    assert int(np.asarray(alloc.tables())[2, 0]) > 0
    alloc.free_slot(2)
    assert alloc.dirty
    assert (np.asarray(alloc.tables()) == 0).all()
    assert not alloc.dirty


def _pregrant_block_spec(mod: types.ModuleType) -> None:
    """Super-step pre-grant contract (token-loop fusion): ONE call grants
    a K-token decode block's pages and returns the usable token budget.
    The off-by-one space here — input token at position n_ctx-1, the
    LAST sampled token's KV deferred to the next dispatch — is exactly
    where a silent mutant truncates streams or overruns granted pages."""
    PA = mod.PageAllocator
    alloc = PA(num_pages=8, page_size=4, max_slots=2, max_pages_per_slot=4)
    assert alloc.allocate_slot(0, 4)            # 1 page, capacity 4
    # k=0 is a no-op: zero budget AND zero page-growth side effect
    before = alloc.pages_in_use
    assert alloc.pregrant_block(0, 9, 0) == 0
    assert alloc.pages_in_use == before
    # k=1 at the page edge: capacity n_ctx+k-1 = 4 still fits 1 page
    assert alloc.pregrant_block(0, 4, 1) == 1
    assert alloc.pages_in_use == before
    # crossing the boundary by exactly one token grows exactly one page
    assert alloc.pregrant_block(0, 4, 2) == 2   # needs 5 tokens -> 2 pages
    assert alloc.pages_in_use == before + 1

    # partial grant: wants 3 pages' capacity, the pool has one free page
    dry = PA(num_pages=3, page_size=4, max_slots=2, max_pages_per_slot=4)
    assert dry.allocate_slot(0, 4)              # 1 page; 1 free remains
    assert dry.pregrant_block(0, 6, 4) == 3     # capacity 8: min(4, 8-5)
    # dry pool + slot at its capacity edge: zero budget, never 1/negative
    assert dry.pregrant_block(0, 9, 4) == 0


def _quantize_moe_and_scale_spec(mod: types.ModuleType) -> None:
    """MoE expert-stack quant rules + the embed multiplier knob."""
    import jax.numpy as jnp
    import numpy as np

    # [E, D, F] stack quantizes per (expert, out-channel): axis 1 reduced
    w = np.arange(2 * 3 * 4, dtype=np.float32).reshape(2, 3, 4) - 10.0
    logical = {"w1": "moe_up", "w2": "moe_down", "n": "replicated"}
    tree = {"w1": w, "w2": np.transpose(w, (0, 2, 1)),
            "n": np.ones((3,), np.float32)}
    quant = mod.quantize_tree(tree, logical, scale_dtype=jnp.float32)
    assert quant["w1"]["q"].shape == (2, 3, 4)
    assert quant["w1"]["s"].shape == (2, 4)      # axis 1 reduced
    assert quant["w2"]["s"].shape == (2, 3)
    np.testing.assert_allclose(
        np.asarray(quant["w1"]["s"]),
        np.max(np.abs(w), axis=1) / 127.0, rtol=1e-6)
    # reconstruction error bounded by one quant step per channel
    recon = (np.asarray(quant["w1"]["q"], np.float32)
             * np.asarray(quant["w1"]["s"])[:, None, :])
    assert np.max(np.abs(recon - w)) <= np.max(np.asarray(quant["w1"]["s"]))
    # norms (no rule) stay untouched
    np.testing.assert_array_equal(np.asarray(quant["n"]), tree["n"])

    # embed multiplier: exact scaling, plain AND quantized tables
    table = np.array([[1.0, -2.0], [0.5, 4.0]], np.float32)
    tokens = jnp.asarray([1, 0])
    plain = np.asarray(mod.embed_rows(jnp.asarray(table), tokens, 8.0))
    np.testing.assert_allclose(plain, table[[1, 0]] * 8.0, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(mod.embed_rows(jnp.asarray(table), tokens)),
        table[[1, 0]], rtol=1e-6)  # default multiplier is identity
    qtable = mod.quantize_leaf(table, axis=1)
    scaled = np.asarray(mod.embed_rows(qtable, tokens, 8.0))
    unscaled = np.asarray(mod.embed_rows(qtable, tokens))
    np.testing.assert_allclose(scaled, unscaled * 8.0, rtol=1e-6)


# ----------------------------------------------------- avg slot footprint

def _avg_slot_pages_spec(mod: types.ModuleType) -> None:
    a = mod.PageAllocator(num_pages=32, page_size=4, max_slots=4,
                          max_pages_per_slot=8)
    # nothing active: conservative max footprint
    assert a.avg_slot_pages() == 8
    assert a.allocate_slot(0, 8)    # 2 pages
    assert a.avg_slot_pages() == 2
    assert a.allocate_slot(1, 16)   # 4 pages
    assert a.avg_slot_pages() == 3  # (2 + 4) // 2
    a.free_slot(1)
    assert a.avg_slot_pages() == 2
    a.free_slot(0)
    assert a.allocate_slot(2, 2)    # 1 page: floor of the average is 1
    assert a.avg_slot_pages() == 1


def _prefix_tier_spec(mod: types.ModuleType) -> None:
    """Tiered-prefix-cache contract (docs/kv_tiering.md): spill-on-evict
    hands the EXACT chain identity (hash, parent, chunk) to the tier
    client, probe caps tier promises at restore capacity (a probe that
    over-promises livelocks admission), fetch-on-miss restores take one
    reference per page and register locally, failed restores hand the
    page back, and the per-tier hit split conserves against
    prefix_hit_tokens at the same consume site."""
    from mcp_context_forge_tpu.tpu_local.kv.prefix_index import (
        ROOT_HASH, chain_hash, chain_hashes)

    PA = mod.PageAllocator

    class Tiers:
        active = True

        def __init__(self):
            self.keys: set[bytes] = set()
            self.spills: list[tuple] = []
            self.published: list[bytes] = []
            self.unpublished: list[bytes] = []
            self.fail = False

        def probe(self, key_hash):
            return key_hash in self.keys

        def spill(self, key_hash, parent, chunk, page):
            self.spills.append((key_hash, parent, tuple(chunk), page))
            self.keys.add(key_hash)
            return True

        def restore(self, key_hash, parent, chunk, page):
            if self.fail or key_hash not in self.keys:
                return None
            return "host"

        def publish_hbm(self, key_hash):
            self.published.append(key_hash)

        def unpublish_hbm(self, key_hash):
            self.unpublished.append(key_hash)

    tiers = Tiers()
    alloc = PA(num_pages=8, page_size=4, max_slots=4, max_pages_per_slot=4,
               tiers=tiers)
    assert alloc.tier_hits == {"hbm": 0, "host": 0, "disk": 0,
                               "object": 0}
    assert alloc.tier_hit_tokens == {"hbm": 0, "host": 0, "disk": 0,
                                     "object": 0}
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    assert alloc.allocate_slot(0, 9)
    alloc.register_prefix(0, prompt)               # 2 pages + 2 publishes
    assert len(tiers.published) == 2

    # resident consume: the hbm split counts at the SAME site as
    # prefix_hit_tokens (the tenant ledger's cache_hit mirror)
    hist, shared = alloc.match_prefix(prompt)
    assert hist == 8
    assert alloc.allocate_slot(1, 9, prefix_pages=shared)
    assert alloc.tier_hits == {"hbm": 2, "host": 0, "disk": 0,
                               "object": 0}
    assert alloc.tier_hit_tokens == {"hbm": 8, "host": 0, "disk": 0,
                                     "object": 0}
    assert sum(alloc.tier_hit_tokens.values()) == alloc.prefix_hit_tokens
    alloc.free_slot(1)
    alloc.free_slot(0)

    # spill-on-evict: pressure reclaims the (now ref==0) registered
    # pages; each handoff carries the exact chain identity and retracts
    # the HBM publication
    for slot in range(3):
        assert alloc.allocate_slot(slot, 8)
    assert alloc.allocate_slot(3, 4)
    assert len(tiers.spills) >= 2
    by_chunk = {s[2]: s for s in tiers.spills}    # eviction order is
    s0 = by_chunk[(1, 2, 3, 4)]                   # LRU-by-last-match,
    s1 = by_chunk[(5, 6, 7, 8)]                   # not chain order
    assert s0[1] == ROOT_HASH
    assert s0[0] == chain_hash(ROOT_HASH, (1, 2, 3, 4))
    assert s1[1] == s0[0]                         # chained parent
    assert s1[0] == chain_hash(s0[0], (5, 6, 7, 8))
    assert s0[0] in tiers.unpublished and s1[0] in tiers.unpublished

    # fetch-on-miss: a FRESH allocator (same shared tiers) serves the
    # chain from the tier store — probe promises it, match restores it
    # with exactly one reference per page, and the split says "host"
    alloc2 = PA(num_pages=8, page_size=4, max_slots=2, max_pages_per_slot=4,
                tiers=tiers)
    assert alloc2.probe_prefix(prompt) == 8
    assert alloc2.probe_prefix([1, 2, 3, 4]) == 0  # last token never matches
    hist, pages2 = alloc2.match_prefix(prompt)
    assert hist == 8 and len(pages2) == 2
    assert all(alloc2._ref[p] == 1 for p in pages2)
    assert alloc2.allocate_slot(0, 9, prefix_pages=pages2)
    assert alloc2.tier_hits == {"hbm": 0, "host": 2, "disk": 0,
                                "object": 0}
    assert alloc2.tier_hit_tokens["host"] == 8
    assert sum(alloc2.tier_hit_tokens.values()) == alloc2.prefix_hit_tokens
    alloc2.free_slot(0)
    # restored pages registered locally: the re-match is resident (hbm),
    # and re-referencing an LRU page starts its count at exactly one
    assert alloc2.probe_prefix(prompt) == 8
    hist, pages3 = alloc2.match_prefix(prompt)
    assert hist == 8
    assert all(alloc2._ref[p] == 1 for p in pages3)
    assert alloc2.allocate_slot(1, 9, prefix_pages=pages3)
    assert alloc2.tier_hits["hbm"] == 2
    alloc2.free_slot(1)

    # spill-on-drain (docs/resilience.md): EVERY ref==0 registered page
    # spills with its exact chain identity, the count is exact, pinned
    # spans never spill, and a page missing its hash evidence is
    # SKIPPED (never unpacked) — tier-less/inactive allocators return
    # exactly 0
    spills_before = len(tiers.spills)
    assert alloc2.spill_resident_prefix() == 2
    assert len(tiers.spills) == spills_before + 2
    assert {s[2] for s in tiers.spills[-2:]} == {(1, 2, 3, 4),
                                                 (5, 6, 7, 8)}
    page = next(iter(alloc2._lru))
    saved = alloc2._page_hash.pop(page)            # defensive-skip branch
    assert alloc2.spill_resident_prefix() == 1
    alloc2._page_hash[page] = saved
    hist, pages4 = alloc2.match_prefix(prompt)     # pin both pages
    assert hist == 8 and alloc2.allocate_slot(0, 9, prefix_pages=pages4)
    assert alloc2.spill_resident_prefix() == 0     # in-flight: untouched
    alloc2.free_slot(0)
    assert PA(num_pages=8, page_size=4, max_slots=2,
              max_pages_per_slot=4).spill_resident_prefix() == 0
    tiers.active = False
    assert alloc2.spill_resident_prefix() == 0
    tiers.active = True

    # migration export (docs/disaggregation.md): spill_chain walks the
    # prompt's registered FULL pages in chain order with exact identity,
    # COPY semantics (pages stay resident and matchable), includes the
    # final page of an exact-boundary prompt (the continuation prompt's
    # matchable depth), stops at the first unregistered depth, and
    # tier-less/inactive allocators return exactly 0
    spills_before = len(tiers.spills)
    assert alloc2.spill_chain(prompt) == 2         # exact count
    assert len(tiers.spills) == spills_before + 2
    assert [s[2] for s in tiers.spills[-2:]] == [(1, 2, 3, 4),
                                                 (5, 6, 7, 8)]  # chain order
    assert tiers.spills[-2][0] == chain_hash(ROOT_HASH, (1, 2, 3, 4))
    assert tiers.spills[-2][1] == ROOT_HASH        # exact identity
    assert tiers.spills[-1][1] == tiers.spills[-2][0]
    assert alloc2.probe_prefix(prompt) == 8        # copy: still resident
    assert alloc2.spill_chain(prompt[:8]) == 2     # exact page boundary
    assert alloc2.spill_chain([90, 91, 92, 93]) == 0   # unregistered chain
    assert alloc2.spill_chain(prompt[:3]) == 0     # no full page to walk
    assert PA(num_pages=8, page_size=4, max_slots=2,
              max_pages_per_slot=4).spill_chain(prompt) == 0   # tier-less
    tiers.active = False
    assert alloc2.spill_chain(prompt) == 0
    tiers.active = True

    # probe caps tier promises at restore capacity: free+evictable of 2
    # limits a 3-chunk tiered chain to 2 pages; a fully-pinned pool
    # promises nothing (an over-promise here is an admission livelock)
    prompt13 = list(range(20, 33))                 # 3 full pages + 1 token
    tiers.keys.update(chain_hashes(prompt13, 4))
    alloc3 = PA(num_pages=6, page_size=4, max_slots=2, max_pages_per_slot=4,
                tiers=tiers)                       # 5 usable
    assert alloc3.allocate_slot(0, 12)             # 3 pinned -> capacity 2
    assert alloc3.probe_prefix(prompt13) == 8
    alloc4 = PA(num_pages=4, page_size=4, max_slots=2, max_pages_per_slot=4,
                tiers=tiers)                       # 3 usable
    assert alloc4.allocate_slot(0, 12)             # everything pinned
    assert alloc4.probe_prefix(prompt13) == 0

    # matching a resident ref==0 (LRU) chain page PINS it, consuming one
    # unit of the capacity later restores draw from — the probe must
    # model that or it promises a hist match_prefix cannot deliver
    # (admission livelock). A ref>0 resident page consumes nothing.
    alloc6 = PA(num_pages=5, page_size=4, max_slots=3, max_pages_per_slot=4,
                tiers=tiers)                       # 4 usable
    assert alloc6.allocate_slot(0, 5)              # 2 pages
    alloc6.register_prefix(0, prompt13[:5])        # chunk0 resident
    alloc6.free_slot(0)                            # chunk0 -> LRU
    assert alloc6.allocate_slot(1, 8)              # pin two free pages
    assert alloc6.free_pages == 2                  # 1 free + 1 evictable
    # chunk0 local-LRU (consumes 1) + chunk1 from tier (consumes 1);
    # chunk2 finds no capacity left
    assert alloc6.probe_prefix(prompt13) == 8
    hist, pages6 = alloc6.match_prefix(prompt13[:5])
    assert hist == 4
    assert alloc6.allocate_slot(2, 5, prefix_pages=pages6)  # chunk0 ref>0
    alloc6.free_slot(1)                            # capacity back: 2 free
    assert alloc6.free_pages == 2
    # the PINNED chunk0 consumes NO capacity: both tier chunks fit it
    assert alloc6.probe_prefix(prompt13) == 12

    # registration covers the FINAL page of an exact-multiple prompt
    # (matches never cover the last token, but longer prompts share it)
    exact = PA(num_pages=8, page_size=4, max_slots=2, max_pages_per_slot=4)
    assert exact.allocate_slot(0, 8)
    exact.register_prefix(0, [11, 12, 13, 14, 15, 16, 17, 18])
    assert exact.cached_pages == 2
    hist, m = exact.match_prefix([11, 12, 13, 14, 15, 16, 17, 18, 90, 91])
    assert hist == 8
    exact.release_prefix(m)
    # ...and registering a prompt LONGER than the slot's pages stops at
    # the pages the slot actually holds
    assert exact.allocate_slot(1, 4)               # 1 page
    exact.register_prefix(1, list(range(40, 52)))  # 3 full chunks
    assert exact.cached_pages == 3                 # 2 from slot 0 + 1 new

    # failed restore: the taken page goes BACK (no leak) and the match
    # ends at the pages already secured
    tiers.fail = True
    free_before = alloc3.free_pages
    hist, pages4 = alloc3.match_prefix(prompt13)
    assert hist == 0 and pages4 == []
    assert alloc3.free_pages == free_before
    tiers.fail = False

    # ...and a fully-pinned MATCH stops cleanly at zero (a mutant that
    # reads the capacity guard wrong walks into _take_page's trap)
    hist, none = alloc4.match_prefix(prompt13)
    assert hist == 0 and none == []

    # a TIER-LESS allocator's match breaks at the first uncached chunk
    # even with free pages in hand (the tier walk must be unreachable
    # without a client — reaching it here is an attribute error)
    plain = PA(num_pages=8, page_size=4, max_slots=2, max_pages_per_slot=4)
    assert plain.allocate_slot(0, 9)
    plain.register_prefix(0, prompt)
    hist, partial = plain.match_prefix([1, 2, 3, 4, 90, 91, 92, 93, 94])
    assert hist == 4 and len(partial) == 1
    plain.release_prefix(partial)

    # first registration of a chain key WINS: a later identical prompt's
    # pages stay private (a mutant that re-registers would swap the
    # cached chain onto the newer slot's pages)
    first_pages = list(plain._slots[0][:2])
    assert plain.allocate_slot(1, 9)
    plain.register_prefix(1, prompt)
    hist, m = plain.match_prefix(prompt)
    assert hist == 8 and m == first_pages
    plain.release_prefix(m)

    # the empty-pool bug trap: _take_page with nothing free and nothing
    # evictable must raise, not hand out a phantom page
    boom = PA(num_pages=2, page_size=4, max_slots=1, max_pages_per_slot=4,
              tiers=tiers)
    assert boom.allocate_slot(0, 4)                # the only usable page
    try:
        boom._take_page()
        raise AssertionError("exhausted pool handed out a phantom page")
    except RuntimeError:
        pass


# ----------------------------------------------------------- fabric index

def _fabric_index_spec(mod: types.ModuleType) -> None:
    """Behavioral spec of the cross-host fabric index
    (docs/cache_fabric.md): advert merge is monotone and counts only
    NEW hashes, TTL expiry is the only eviction (lazy on covers + eager
    sweep), tenant namespaces never cross, origin attribution is
    first-registration-wins, and the wire codec round-trips / rejects
    malformed frames. A surviving mutant here means a host promising
    cross-host restores it cannot deliver (admission livelock) or one
    tenant's cached pages visible to another."""
    clock = [1000.0]
    idx = mod.FabricIndex(default_ttl_s=10.0, clock=lambda: clock[0])
    h1, h2, h3 = b"\x01" * 32, b"\x02" * 32, b"\x03" * 32

    # merge counts NEW hashes only; covers/lookup agree
    assert idx.merge(mod.FabricAdvert(tenant="t", host="A",
                                      hashes=[h1, h2])) == 2
    assert idx.merge(mod.FabricAdvert(tenant="t", host="A",
                                      hashes=[h1, h3])) == 1
    assert idx.covers(h1, "t") and idx.covers(h3, "t")
    assert idx.lookup(h1, "t") == "A"
    assert idx.stats()["keys"] == 3

    # tenant isolation: the SAME hash under another namespace is a miss
    assert not idx.covers(h1, "other")
    assert idx.lookup(h1, "other") is None
    assert idx.hashes("other") == []
    idx.invalidate(h1, "other")                    # wrong tenant: no-op
    assert idx.covers(h1, "t")

    # first-registration-wins: a re-advert from another host refreshes
    # the expiry but never reassigns the origin
    clock[0] = 1005.0
    assert idx.merge(mod.FabricAdvert(tenant="t", host="B",
                                      hashes=[h1])) == 0
    assert idx.lookup(h1, "t") == "A"

    # ...and the refresh only EXTENDS: an advert with a shorter ttl
    # cannot pull an existing expiry earlier
    idx.merge(mod.FabricAdvert(tenant="t", host="B", hashes=[h1],
                               ttl_s=0.5))
    clock[0] = 1011.0                              # h2/h3 (exp 1010) dead
    assert idx.covers(h1, "t")                     # refreshed to 1015
    assert not idx.covers(h2, "t")                 # lazy expiry on read
    assert idx.sweep() == 1                        # h3 swept eagerly
    assert idx.stats()["keys"] == 1

    # invalidate drops exactly the (tenant, hash) entry
    idx.invalidate(h1, "t")
    assert not idx.covers(h1, "t")
    assert idx.lookup(h1, "t") is None
    assert idx.invalidated == 1

    # expiry is the ONLY eviction a merge can never perform: re-merging
    # after expiry counts as NEW again (monotone within a lifetime)
    assert idx.merge(mod.FabricAdvert(tenant="t", host="C",
                                      hashes=[h2])) == 1
    assert idx.lookup(h2, "t") == "C"              # fresh registration

    # wire codec: round trip exact; malformed frames raise ValueError
    advert = mod.FabricAdvert(tenant="t", host="A", hashes=[h1],
                              ttl_s=5.0)
    assert mod.FabricAdvert.from_wire(advert.to_wire()) == advert
    for bad in ("nope", {"tenant": "t"}, {"tenant": "t", "host": ""},
                {"tenant": "t", "host": "A", "hashes": ["zz"]},
                {"tenant": "t", "host": "A", "hashes": ["abcd"]}):
        try:
            mod.FabricAdvert.from_wire(bad)
            raise AssertionError(f"malformed advert accepted: {bad!r}")
        except ValueError:
            pass
    # oversize adverts truncate at the wire boundary, never reject
    digest_hex = (b"\x07" * 32).hex()
    big = {"tenant": "t", "host": "A",
           "hashes": [digest_hex] * (mod.MAX_ADVERT_HASHES + 5)}
    assert len(mod.FabricAdvert.from_wire(big).hashes) \
        == mod.MAX_ADVERT_HASHES
    fresh = mod.FabricIndex(default_ttl_s=10.0, clock=lambda: clock[0])
    assert mod.merge_wire_adverts(fresh, [advert.to_wire()]) == 1
    assert fresh.covers(h1, "t")

    # the re-advertisable view groups by tenant and relabels the relay
    fresh.merge(mod.FabricAdvert(tenant="u", host="B", hashes=[h2]))
    out = fresh.adverts("relay")
    assert [(a.tenant, a.host, a.hashes) for a in out] \
        == [("t", "relay", [h1]), ("u", "relay", [h2])]

    # counters start at zero and count by exactly one — no-ops (a
    # wrong-tenant invalidate) are NOT counted
    z = mod.FabricIndex(default_ttl_s=10.0, clock=lambda: clock[0])
    assert (z.merged, z.refreshed, z.expired, z.invalidated) \
        == (0, 0, 0, 0)
    z.merge(mod.FabricAdvert(tenant="t", host="A", hashes=[h1]))
    assert z.merged == 1 and z.refreshed == 0
    z.merge(mod.FabricAdvert(tenant="t", host="A", hashes=[h1]))
    assert z.merged == 1 and z.refreshed == 1
    z.invalidate(h1, "nope")
    assert z.invalidated == 0
    z.invalidate(h1, "t")
    assert z.invalidated == 1

    # an explicit positive ttl REPLACES the default (shorter is legal
    # for a fresh entry): a 0.5 s advert on a 10 s-default index is
    # gone at +1 s
    clock[0] = 2000.0
    z.merge(mod.FabricAdvert(tenant="t", host="A", hashes=[h2],
                             ttl_s=0.5))
    clock[0] = 2001.0
    assert not z.covers(h2, "t")

    # the expiry boundary is EXACT: at expires_at == now the entry is
    # dead on EVERY read path, and each lazy expiry counts once
    b = mod.FabricIndex(default_ttl_s=10.0, clock=lambda: clock[0])
    clock[0] = 3000.0
    b.merge(mod.FabricAdvert(tenant="t", host="A", hashes=[h1, h2]))
    clock[0] = 3010.0                              # == expires_at
    assert b.stats()["keys"] == 0
    assert b.stats()["hosts"] == [] and b.stats()["tenants"] == []
    assert b.hashes("t") == [] and b.adverts("r") == []
    assert b.lookup(h1, "t") is None
    assert not b.covers(h1, "t")                   # lazy-expires h1
    assert b.expired == 1
    assert b.sweep() == 1                          # h2, at the boundary
    assert b.expired == 2


# ------------------------------------------------------------ eventstream

def eventstream_oracle(mod: types.ModuleType) -> None:
    """Behavioral spec of the AWS event-stream codec: exact framing
    layout, both CRCs live, typed headers, incremental reassembly. A
    surviving mutant means silently corrupt Bedrock streams."""
    import asyncio
    import zlib

    headers = {":event-type": "contentBlockDelta", ":message-type": "event"}
    payload = b'{"delta":{"text":"hi"}}'
    frame = mod.encode_frame(headers, payload)
    # exact layout: total length, headers length, prelude CRC
    total = int.from_bytes(frame[0:4], "big")
    assert total == len(frame)
    hlen = int.from_bytes(frame[4:8], "big")
    assert hlen == len(frame) - 12 - 4 - len(payload)
    assert int.from_bytes(frame[8:12], "big") == zlib.crc32(frame[0:8])
    assert int.from_bytes(frame[-4:], "big") == zlib.crc32(frame[:-4])
    assert mod.decode_frame(frame) == (headers, payload)
    assert mod.decode_frame(mod.encode_frame({}, b"")) == ({}, b"")

    # every corrupted byte position must be caught by SOME check
    for pos in (2, 5, 9, 13, len(frame) - 6, len(frame) - 2):
        corrupt = bytearray(frame)
        corrupt[pos] ^= 0xFF
        try:
            mod.decode_frame(bytes(corrupt))
        except mod.EventStreamError:
            pass
        else:
            raise AssertionError(f"corruption at byte {pos} accepted")

    # typed headers: bool true/false + every scalar width + bytes + string
    hdr = bytes([1]) + b"t" + bytes([0])
    hdr += bytes([1]) + b"f" + bytes([1])
    hdr += bytes([1]) + b"a" + bytes([2]) + (5).to_bytes(1, "big")
    hdr += bytes([1]) + b"b" + bytes([3]) + (-300).to_bytes(2, "big",
                                                            signed=True)
    hdr += bytes([1]) + b"c" + bytes([4]) + (7).to_bytes(4, "big")
    hdr += bytes([1]) + b"d" + bytes([5]) + (2**40).to_bytes(8, "big")
    hdr += bytes([1]) + b"e" + bytes([8]) + (123456).to_bytes(8, "big")
    hdr += bytes([1]) + b"u" + bytes([9]) + bytes(range(16))
    hdr += bytes([1]) + b"s" + bytes([7]) + (2).to_bytes(2, "big") + b"ok"
    hdr += bytes([1]) + b"r" + bytes([6]) + (3).to_bytes(2, "big") + b"\x01\x02\x03"
    parsed = mod._parse_headers(hdr)
    assert parsed == {"t": True, "f": False, "a": 5, "b": -300, "c": 7,
                      "d": 2**40, "e": 123456, "u": bytes(range(16)),
                      "s": "ok", "r": b"\x01\x02\x03"}
    # unknown value type is an error, not silent garbage
    try:
        mod._parse_headers(bytes([1]) + b"x" + bytes([99]))
    except mod.EventStreamError:
        pass
    else:
        raise AssertionError("unknown header type accepted")

    # bad prelude CRC with a RECOMPUTED (valid) message CRC: only the
    # prelude check can catch this one
    broken = bytearray(frame)
    broken[8] ^= 0xFF
    broken[-4:] = zlib.crc32(bytes(broken[:-4])).to_bytes(4, "big")
    try:
        mod.decode_frame(bytes(broken))
    except mod.EventStreamError as exc:
        assert "prelude" in str(exc)
    else:
        raise AssertionError("bad prelude CRC accepted")

    # extra bytes past the claimed total, with the TRAILING CRC recomputed
    # so both CRC checks pass: only the length check can catch this
    padded = bytearray(frame + b"\x00" * 6)
    padded[-4:] = zlib.crc32(bytes(padded[:-4])).to_bytes(4, "big")
    try:
        mod.decode_frame(bytes(padded))
    except mod.EventStreamError as exc:
        assert "length" in str(exc)
    else:
        raise AssertionError("over-long frame accepted")

    # incremental reassembly across every split granularity
    frames = [mod.encode_frame({"k": str(i)}, bytes([i]) * i)
              for i in range(5)]
    frames.append(mod.encode_frame({}, b""))   # the minimal 16-byte frame
    blob = b"".join(frames)

    async def collect(step):
        async def chunks():
            for i in range(0, len(blob), step):
                yield blob[i:i + step]
        return [h async for h, _ in mod.iter_frames(chunks())]

    for step in (1, 3, len(blob)):
        got = asyncio.run(collect(step))
        assert [h.get("k") for h in got] == ["0", "1", "2", "3", "4", None]

    async def feed(data):
        async def chunks():
            yield data
        return [f async for f in mod.iter_frames(chunks())]

    try:
        asyncio.run(feed(blob + b"\x00"))
    except mod.EventStreamError:
        pass
    else:
        raise AssertionError("trailing bytes accepted")
    # implausible frame lengths fail fast instead of buffering forever
    for claimed in (3, 17 * 1024 * 1024):
        bad = claimed.to_bytes(4, "big") + b"\x00" * 12
        try:
            asyncio.run(feed(bad))
        except mod.EventStreamError:
            pass
        else:
            raise AssertionError(f"implausible length {claimed} accepted")
    # a stream that ENDS mid-frame is an error (incomplete trailing frame)
    try:
        asyncio.run(feed(frame[:11]))
    except mod.EventStreamError:
        pass
    else:
        raise AssertionError("truncated stream accepted")


# ------------------------------------------------------------- tool_calls

def tool_calls_oracle(mod: types.ModuleType) -> None:
    """Behavioral spec of the function-calling wire layer: accepted
    emission shapes, rejection of plain answers, OpenAI tool_calls
    structure, render/parse round trip."""
    import json as _json

    calls = mod.parse_tool_calls('{"name": "f", "parameters": {"a": 1}}')
    assert len(calls) == 1
    call = calls[0]
    assert call["type"] == "function"
    assert call["id"].startswith("call_")
    assert call["function"]["name"] == "f"
    assert _json.loads(call["function"]["arguments"]) == {"a": 1}

    # alternate key spellings
    assert mod.parse_tool_calls(
        '{"name": "g", "arguments": {"x": 2}}')[0]["function"]["name"] == "g"
    assert mod.parse_tool_calls(
        '{"tool": "h", "arguments": {}}')[0]["function"]["name"] == "h"
    # arrays = parallel calls, order preserved, unique ids
    multi = mod.parse_tool_calls(
        '[{"name": "a", "parameters": {}}, {"name": "b", "parameters": {}}]')
    assert [c["function"]["name"] for c in multi] == ["a", "b"]
    assert multi[0]["id"] != multi[1]["id"]
    # python_tag prefix and prose wrapping
    assert mod.parse_tool_calls(
        '<|python_tag|>{"name": "f", "parameters": {}}') is not None
    assert mod.parse_tool_calls(
        'Sure.\n{"name": "f", "parameters": {}}\nDone.') is not None
    # rejections: plain text, missing/empty name, scalar args, non-dicts
    for bad in ("plain answer", '{"x": 1}', '{"name": "", "parameters": {}}',
                '{"name": "f", "parameters": 3}', "[1, 2]", "[]",
                '[{"name": "f", "parameters": {}}, {"x": 1}]'):
        assert mod.parse_tool_calls(bad) is None, bad

    # non-string names reject; id carries 16 hex chars after the prefix
    assert mod.parse_tool_calls('{"name": 3, "parameters": {}}') is None
    assert len(call["id"]) == len("call_") + 16
    # leading-JSON-with-trailing-prose parses via the outermost span
    tail = mod.parse_tool_calls('{"name": "t", "parameters": {}}thanks!')
    assert tail[0]["function"]["name"] == "t"

    # render block lists every signature + the call instruction,
    # INCLUDING the parameters schema
    block = mod.render_tools_block([
        {"type": "function", "function": {"name": "fn1", "description": "D",
                                          "parameters": {"type": "object"}}}])
    assert "fn1" in block and "D" in block
    assert '{"type":"object"}' in block
    assert '"<function-name>"' in block

    # round trip: rendered call text re-parses to the same call
    text = mod.tool_call_message_text(calls)
    again = mod.parse_tool_calls(text)
    assert again[0]["function"]["name"] == "f"
    assert _json.loads(again[0]["function"]["arguments"]) == {"a": 1}
    multi_text = mod.tool_call_message_text(multi)
    assert [c["function"]["name"] for c in mod.parse_tool_calls(multi_text)] \
        == ["a", "b"]


# ------------------------------------------------------------- lint engine

def lint_core_oracle(mod: types.ModuleType) -> None:
    """Behavioral spec of tools/lint/core.py: marker parsing from real
    comments only, per-line suppression, content-anchored baseline
    match/consume/stale, registry invariants, and finding triage. A
    surviving mutant is a linter that silently eats findings — the gate
    stays green while the hazard ships."""
    import json as _json
    import tempfile
    import types as _types
    from pathlib import Path as _Path

    # ---- Finding shape
    f = mod.Finding("r1", "a.py", 3, "msg", code="xx")
    assert str(f) == "a.py:3: r1 msg"
    assert f.to_dict() == {"rule": "r1", "path": "a.py", "lineno": 3,
                           "message": "msg", "code": "xx"}

    # ---- FileContext: markers from real comments, line-keyed
    src = ("first = 1  # lint: allow[rule-a] reason\n"
           "second = 2  # lint: thread[dispatch]\n"
           "s = '# lint: allow[rule-b]'\n"
           "def fn(a,\n"
           "       b):  # lint: hot-path\n"
           "    pass  # lint: runs-on[loop]\n"
           "# lint: allow[rule-c] # lint: allow[rule-d]\n"
           "def one(): pass  # lint: hot-path\n"
           "after = 3  # lint: runs-on[next]\n")
    ctx = mod.FileContext.from_source(src, "m.py")
    assert ctx.path == "m.py"
    assert ctx.allowed(1) == {"rule-a"}
    assert ctx.allowed(2) == set()         # thread marker is not allow
    assert ctx.allowed(3) == set()         # string literal never counts
    assert ctx.allowed(7) == {"rule-c", "rule-d"}
    assert ctx.markers_of("thread") == {2: "dispatch"}
    assert ctx.markers_of("hot-path") == {5: "", 8: ""}
    assert ctx.markers_of("runs-on") == {6: "loop", 9: "next"}
    assert ctx.markers_of("nope") == {}
    assert ctx.line(1) == "first = 1  # lint: allow[rule-a] reason"
    assert ctx.line(7) == "# lint: allow[rule-c] # lint: allow[rule-d]"
    assert ctx.line(0) == "" and ctx.line(99) == ""

    # def_marker: anywhere in the (multi-line) signature counts, the
    # body does not
    fndef = ctx.tree.body[3]
    assert mod.FileContext.def_marker(ctx, fndef, "hot-path") == ""
    assert mod.FileContext.def_marker(ctx, fndef, "runs-on") is None
    # a ONE-LINE def counts its only line — and ONLY that line (the
    # runs-on marker on line 9 belongs to the next statement)
    onedef = ctx.tree.body[4]
    assert onedef.lineno == onedef.body[0].lineno == 8
    assert mod.FileContext.def_marker(ctx, onedef, "hot-path") == ""
    assert mod.FileContext.def_marker(ctx, onedef, "runs-on") is None
    # body-less node: the one-line fallback window
    probe = _types.SimpleNamespace(lineno=1, body=[])
    assert ctx.def_marker(probe, "allow") == "rule-a"
    probe = _types.SimpleNamespace(lineno=1, body=None)
    assert ctx.def_marker(probe, "thread") is None  # line 2 is outside

    # ---- Rule base + registry
    base = mod.Rule()
    assert list(base.check(ctx)) == []
    assert list(base.check_project([ctx])) == []
    assert list(base.check_graph(None, [ctx])) == []

    class ROne(mod.Rule):
        rule_id = "r-one"

    mod.register(ROne)
    assert mod.registered_rules()["r-one"] is ROne
    try:
        mod.register(ROne)
    except ValueError:
        pass
    else:
        raise AssertionError("duplicate rule id accepted")

    class RNone(mod.Rule):
        pass

    try:
        mod.register(RNone)
    except ValueError:
        pass
    else:
        raise AssertionError("empty rule id accepted")

    # ---- path identity across invocation styles: exact or whole-segment
    # suffix, both directions; never a partial-segment match
    assert mod.paths_match("a/b.py", "a/b.py") is True
    assert mod.paths_match("/root/repo/pkg/b.py", "pkg/b.py") is True
    assert mod.paths_match("pkg/b.py", "/root/repo/pkg/b.py") is True
    assert mod.paths_match("my.py", "y.py") is False
    assert mod.paths_match("a/b.py", "a/c.py") is False

    # ---- Baseline: content-anchored match, consume-once, stale report
    entry = {"rule": "fire", "path": "a.py", "code": "BAD = 2",
             "reason": "known"}
    other = {"rule": "fire", "path": "b.py", "code": "BAD = 9",
             "reason": "known"}
    hit = mod.Finding("fire", "a.py", 2, "m", code="BAD = 2")
    baseline = mod.Baseline(entries=[entry, other])
    assert baseline.match(hit) is True
    assert baseline.match(hit) is False      # consumed: match exactly once
    assert baseline.stale() == [other]
    # every anchor field is load-bearing
    for wrong in (mod.Finding("other", "a.py", 2, "m", code="BAD = 2"),
                  mod.Finding("fire", "z.py", 2, "m", code="BAD = 2"),
                  mod.Finding("fire", "a.py", 2, "m", code="OTHER")):
        assert mod.Baseline(entries=[entry]).match(wrong) is False
    # a relative entry suppresses the absolute spelling of the same file
    absolute = mod.Finding("fire", "/root/repo/a.py", 2, "m", code="BAD = 2")
    assert mod.Baseline(entries=[entry]).match(absolute) is True
    assert mod.Baseline.entry_for(hit, "why") == {
        "rule": "fire", "path": "a.py", "code": "BAD = 2", "reason": "why"}

    with tempfile.TemporaryDirectory() as tmp:
        path = _Path(tmp) / "baseline.json"
        mod.Baseline(entries=[entry]).save(path)
        assert path.read_text() == _json.dumps(
            {"entries": [entry]}, indent=2, sort_keys=True) + "\n"
        assert mod.Baseline.load(path).entries == [entry]
        assert mod.Baseline.load(path).stale() == [entry]  # fresh _used
        try:
            mod.Baseline(entries=[{"rule": "x", "path": "y",
                                   "code": "z"}]).save(path)
        except ValueError:
            pass
        else:
            raise AssertionError("reason-less baseline entry saved")
        # ...and load refuses it too: a hand-added reason-less entry
        # must not silently suppress
        path.write_text(_json.dumps(
            {"entries": [{"rule": "x", "path": "y", "code": "z"}]}))
        try:
            mod.Baseline.load(path)
        except ValueError:
            pass
        else:
            raise AssertionError("reason-less baseline entry loaded")
        # the gate-side load also refuses --write-baseline's TODO
        # placeholder, while save accepts it (the authoring flow writes
        # placeholders for the maintainer to replace)
        todo = {"rule": "x", "path": "y", "code": "z",
                "reason": "TODO: justify or fix"}
        mod.Baseline(entries=[todo]).save(path)      # authoring: ok
        assert _json.loads(path.read_text())["entries"] == [todo]
        try:
            mod.Baseline.load(path)
        except ValueError:
            pass
        else:
            raise AssertionError("TODO placeholder reason loaded")
        real = dict(todo, reason="legacy client; migrating")
        path.write_text(_json.dumps({"entries": [real]}))
        assert mod.Baseline.load(path).entries == [real]

    # ---- LintResult.clean
    ok = mod.Finding("r", "p", 1, "m")
    assert mod.LintResult().clean is True
    assert mod.LintResult(findings=[ok]).clean is False
    assert mod.LintResult(errors=[ok]).clean is False

    # ---- triage pipeline: fire / suppress / baseline / project / sort
    class Fire(mod.Rule):
        rule_id = "fire"

        def check(self, c):
            for i, line in enumerate(c.lines, start=1):
                if "BAD" in line:
                    yield mod.Finding("fire", c.path, i, "bad thing")

    class Proj(mod.Rule):
        rule_id = "proj"

        def check_project(self, cs):
            if len(cs) >= 2:
                yield mod.Finding("proj", cs[0].path, 1, "pair",
                                  code="anchored")
            yield mod.Finding("proj", "outside.py", 5, "external")

    rules = [Fire(), Proj()]
    res = mod.lint_sources({"a.py": "ok = 1\nBAD = 2\n"}, [Fire()])
    assert [f.lineno for f in res.findings] == [2]
    assert res.findings[0].code == "BAD = 2"   # code filled from source
    assert res.clean is False and res.suppressed == [] \
        and res.baselined == [] and res.stale_baseline == []

    res = mod.lint_sources(
        {"a.py": "BAD = 2  # lint: allow[fire] migrating\n"}, [Fire()])
    assert res.findings == [] and len(res.suppressed) == 1
    res = mod.lint_sources(
        {"a.py": "BAD = 2  # lint: allow[other]\n"}, [Fire()])
    assert len(res.findings) == 1              # wrong rule id still fires

    res = mod.lint_sources(
        {"a.py": "BAD = 2\n"}, [Fire()],
        mod.Baseline(entries=[dict(entry), dict(other)]))
    assert res.findings == [] and len(res.baselined) == 1
    assert res.stale_baseline == [other]

    # two files: per-file + project findings, sorted by (path, lineno);
    # a finding for a path outside the context set passes through with
    # its own code anchor intact
    res = mod.lint_sources({"a.py": "ok = 3\n", "b.py": "x = 1\nBAD = 2\n"},
                           rules)
    assert [(f.path, f.lineno, f.rule) for f in res.findings] == [
        ("a.py", 1, "proj"), ("b.py", 2, "fire"), ("outside.py", 5, "proj")]
    assert res.findings[0].code == "anchored"  # pre-set code not clobbered

    # syntax errors are findings, not crashes, and poison cleanliness
    res = mod.lint_sources({"bad.py": "def broken(:\n", "ok.py": "x = 1\n"},
                           [Fire()])
    assert res.clean is False
    assert [e.rule for e in res.errors] == ["syntax-error"]
    assert res.errors[0].path == "bad.py" and res.errors[0].lineno == 1

    # ---- check_graph dispatch: rules that OVERRIDE check_graph get one
    # shared ProjectGraph + the full context list; base-Rule instances
    # must not trigger a build or receive a call
    seen_graphs: list = []
    seen_paths: list = []

    class Graphy(mod.Rule):
        rule_id = "graphy"

        def check_graph(self, graph, contexts):
            seen_graphs.append(graph)
            seen_paths.append([c.path for c in contexts])
            for name in sorted(graph.signal_published):
                if name not in graph.signal_read:
                    site = graph.signal_published[name][0]
                    yield mod.Finding("graphy", site.path, site.lineno,
                                      f"unread {name}")

    class Graphy2(mod.Rule):
        rule_id = "graphy2"

        def check_graph(self, graph, contexts):
            seen_graphs.append(graph)
            return ()

    graph_srcs = {
        "r.py": ('def f(bus):\n'
                 '    bus.publish("a.read", 1.0)\n'
                 '    bus.publish("a.orphan", 1.0)\n'),
        "s.py": 'def g(bus, rid):\n    return bus.get("a.read", rid)\n',
    }
    res = mod.lint_sources(graph_srcs, [Graphy(), Graphy2(), mod.Rule()])
    assert [(f.path, f.lineno, f.message) for f in res.findings] == [
        ("r.py", 3, "unread a.orphan")]
    assert len(seen_graphs) == 2
    assert seen_graphs[0] is seen_graphs[1]    # built ONCE, shared
    assert seen_paths[0] == ["r.py", "s.py"]   # full context list handed in
    # graph findings flow through the same triage: allow[] suppresses
    res = mod.lint_sources(
        {"r.py": ('def f(bus):\n'
                  '    bus.publish("a.orphan", 1.0)'
                  '  # lint: allow[graphy] dashboard-only\n')},
        [Graphy()])
    assert res.findings == [] and len(res.suppressed) == 1

    # ---- triage() direct: the runner calls it with pre-gathered raw
    # findings — code backfill, allow, baseline, sort, stale must all
    # behave exactly as the serial path
    tctx = mod.FileContext.from_source(
        "keep = 1\nBAD = 2  # lint: allow[fire] migrating\n", "t.py")
    raw = [mod.Finding("fire", "t.py", 2, "allowed here"),
           mod.Finding("fire", "t.py", 1, "plain"),
           mod.Finding("fire", "a.py", 2, "baselined", code="BAD = 2"),
           mod.Finding("zz", "no-ctx.py", 9, "passthrough", code="kept")]
    tri = mod.triage([tctx], raw, mod.Baseline(entries=[dict(entry)]))
    assert [(f.path, f.lineno, f.rule) for f in tri.findings] == [
        ("no-ctx.py", 9, "zz"), ("t.py", 1, "fire")]
    assert tri.findings[1].code == "keep = 1"      # backfilled from ctx
    assert tri.findings[0].code == "kept"          # pre-set survives
    assert [f.message for f in tri.suppressed] == ["allowed here"]
    assert [f.message for f in tri.baselined] == ["baselined"]
    assert tri.stale_baseline == []
    assert mod.triage([], [], None).clean is True  # default empty baseline

    # ---- collect_sources: dirs recurse, __pycache__ skipped, files ok
    with tempfile.TemporaryDirectory() as tmp:
        root = _Path(tmp)
        (root / "pkg" / "sub").mkdir(parents=True)
        (root / "pkg" / "__pycache__").mkdir()
        (root / "pkg" / "a.py").write_text("a = 1\n")
        (root / "pkg" / "sub" / "b.py").write_text("b = 2\n")
        (root / "pkg" / "__pycache__" / "c.py").write_text("c = 3\n")
        (root / "lone.py").write_text("d = 4\n")
        got = mod.collect_sources([root / "pkg", root / "lone.py"])
        names = {p.rsplit("/", 1)[-1] for p in got}
        assert names == {"a.py", "b.py", "lone.py"}
        assert got[(root / "pkg" / "a.py").as_posix()] == "a = 1\n"


def lint_project_oracle(mod: types.ModuleType) -> None:
    """Behavioral spec of tools/lint/project.py: every registry the
    cross-file rules query, extracted from small in-memory trees with
    exact expected contents. A surviving mutant is a ProjectGraph that
    silently drops (or invents) a registry entry — a whole-program rule
    gone blind while the gate stays green."""
    import tempfile
    from pathlib import Path as _Path

    from mcp_context_forge_tpu.tools.lint.core import FileContext

    def build(sources, docs_text=None):
        ctxs = [FileContext.from_source(src, path)
                for path, src in sorted(sources.items())]
        return mod.ProjectGraph.build(ctxs, docs_text=docs_text)

    # ---- site dataclasses are frozen value objects (rules dedupe them
    # in sets — an unfrozen mutant is unhashable)
    assert len({mod.Site("a.py", 1), mod.Site("a.py", 1)}) == 1
    assert len({mod.RpcSite("a.py", 1, "unary"),
                mod.RpcSite("a.py", 1, "unary")}) == 1
    assert len({mod.MetricDecl("a", "n", (), "p", 1)}) == 1
    assert len({mod.LockDecl("k", "", "threading", "p", 1)}) == 1

    # ---- Bus-RPC registry: register/register_stream (positional and
    # keyword names), call/call_stream with timeout detection, literal
    # names resolved through same-class forwarders (keyword AND
    # positional passing); dotless names and non-rpc receivers never
    # count, on the direct path or the forwarder path
    rpc_server = (
        'class Srv:\n'
        '    def __init__(self, rpc):\n'
        '        rpc.register("pool.status", self._st)\n'
        '        rpc.register_stream("pool.tail", self._tl)\n'
        '        rpc.register(method="pool.kw", handler=self._kw)\n'
        '        rpc.register("nodot", self._nd)\n'
        '        other.register("pool.ghost", self._gh)\n'
    )
    rpc_client = (
        'class Cli:\n'
        '    def __init__(self, rpc):\n'
        '        self._rpc = rpc\n'
        '    def plain(self, w):\n'
        '        return self._rpc.call(w, "pool.status")\n'
        '    def timed(self, w):\n'
        '        return self._rpc.call(w, "pool.status", timeout_s=1.0)\n'
        '    def tail(self, w):\n'
        '        return self._rpc.call_stream(w, "pool.tail",\n'
        '                                     idle_timeout_s=2.0)\n'
        '    def tail_bare(self, w):\n'
        '        return self._rpc.call_stream(w, "pool.tail")\n'
        '    def _fwd(self, w, method):\n'
        '        return self._rpc.call(w, method=method)\n'
        '    def via(self, w):\n'
        '        return self._fwd(w, "pool.fwd")\n'
        '    def _fwd2(self, w, m):\n'
        '        return self._rpc.call(w, m)\n'
        '    def via2(self, w):\n'
        '        return self._fwd2(w, "pool.fwd2")\n'
        '    def via_dotless(self, w):\n'
        '        return self._fwd(w, "nodotfwd")\n'
        '    def bogus(self, w):\n'
        '        return other.call(w, "pool.bogus")\n'
        '    def _notrpc(self, w, method):\n'
        '        return self.conn.call(w, method)\n'
        '    def use_notrpc(self, w):\n'
        '        return self._notrpc(w, "pool.fake")\n'
    )
    g = build({"fx/server.py": rpc_server, "fx/client.py": rpc_client})
    assert g.paths == ["fx/client.py", "fx/server.py"]
    assert set(g.rpc_registered) == {"pool.status", "pool.tail", "pool.kw"}
    st, = g.rpc_registered["pool.status"]
    assert (st.path, st.lineno, st.kind) == ("fx/server.py", 3, "unary")
    assert st.has_idle_timeout is False        # the dataclass default
    tl, = g.rpc_registered["pool.tail"]
    assert (tl.path, tl.lineno, tl.kind) == ("fx/server.py", 4, "stream")
    kw, = g.rpc_registered["pool.kw"]
    assert (kw.lineno, kw.kind) == (5, "unary")
    assert set(g.rpc_called) == {"pool.status", "pool.tail",
                                 "pool.fwd", "pool.fwd2"}
    assert sorted((c.lineno, c.kind, c.has_idle_timeout)
                  for c in g.rpc_called["pool.status"]) == [
        (5, "unary", False), (7, "unary", True)]
    assert sorted((c.lineno, c.kind, c.has_idle_timeout)
                  for c in g.rpc_called["pool.tail"]) == [
        (9, "stream", True), (12, "stream", False)]
    fwd, = g.rpc_called["pool.fwd"]
    assert (fwd.path, fwd.lineno, fwd.kind) == ("fx/client.py", 16, "unary")
    fwd2, = g.rpc_called["pool.fwd2"]
    assert (fwd2.lineno, fwd2.kind, fwd2.has_idle_timeout) == \
        (20, "unary", False)
    # subset-run degradation: registries anchored on an absent module
    # come out empty, never invented
    g = build({"fx/client.py": rpc_client})
    assert g.rpc_registered == {}
    assert set(g.rpc_called) == {"pool.status", "pool.tail",
                                 "pool.fwd", "pool.fwd2"}

    # ---- SignalBus names: sync publishes on signal-shaped receivers
    # only (awaited / dict-payload calls are the EventBus twin), valid
    # dotted lowercase names only, f-strings as dynamic prefixes; reads
    # via get/ewma/replicas including the forwarder and const-tuple-loop
    # idioms
    signal_engine = (
        'class Eng:\n'
        '    def step(self, signals, shard):\n'
        '        signals.publish("llm.occupancy", 0.5)\n'
        '        signals.publish(f"slo.burn.{shard}", 1.0)\n'
        '        signals.publish(f"nodot{shard}", 1.0)\n'
        '        signals.publish("UPPER.Name", 1.0)\n'
        '        signals.publish("flat", 1.0)\n'
        '        signals.publish("llm.unread", 1.0)\n'
        '    async def emit(self, bus):\n'
        '        await bus.publish("llm.event", {"k": 1})\n'
        '        await bus.publish("llm.awaited", 1.0)\n'
        '    def dictpub(self, bus):\n'
        '        bus.publish("llm.dictpay", {"k": 1})\n'
        '    def other(self, queue):\n'
        '        queue.publish("llm.queue", 1.0)\n'
        '    def qread(self, queue, rid):\n'
        '        queue.get("llm.qread", rid)\n'
        '    def badargs(self, signals, shard):\n'
        '        signals.publish(5, 1.0)\n'
        '        signals.publish(f"{shard}.dyn", 1.0)\n'
    )
    signal_ctl = (
        '_MOD_SIGS = ("ctl.mod_sig",)\n'
        '\n'
        'class Ctl:\n'
        '    _EFFECTS = ("llm.eff_a", "llm.eff_b")\n'
        '    _LIMIT = 3\n'
        '    def __init__(self, bus):\n'
        '        self.bus = bus\n'
        '    def _view(self, name, rid):\n'
        '        return self.bus.get(name, rid)\n'
        '    def tick(self, rid):\n'
        '        a = self.bus.get("llm.occupancy", rid)\n'
        '        b = self.bus.ewma("llm.ew", rid)\n'
        '        c = self.bus.replicas("llm.rep", rid)\n'
        '        d = self._view("llm.via_fwd", rid)\n'
        '        for name in self._EFFECTS:\n'
        '            self.bus.get(name, rid)\n'
        '        return a, b, c, d\n'
        '    def probe(self, rid):\n'
        '        for name in self._LIMIT:\n'
        '            self.bus.get(name, rid)\n'
        '    def modloop(self, rid):\n'
        '        for name in _MOD_SIGS:\n'
        '            self.bus.get(name, rid)\n'
        '    def bad_fwd(self, rid):\n'
        '        return self._view("NotValid.Name", rid)\n'
        '    def _notsig(self, name, rid):\n'
        '        return self.store.get(name, rid)\n'
        '    def use_notsig(self, rid):\n'
        '        return self._notsig("fake.sig", rid)\n'
    )
    signal_pump = (
        '_SIGS = ("mod.one", "mod.two")\n'
        '_MIXED = ("bad.mix", 3)\n'
        '\n'
        'def pump(my_signals, rid):\n'
        '    for s in _SIGS:\n'
        '        my_signals.get(s, rid)\n'
    )
    g = build({"fx/eng.py": signal_engine, "fx/ctl.py": signal_ctl,
               "fx/pump.py": signal_pump})
    assert set(g.signal_published) == {"llm.occupancy", "llm.unread"}
    pub, = g.signal_published["llm.occupancy"]
    assert (pub.path, pub.lineno) == ("fx/eng.py", 3)
    assert [(p, s.lineno) for p, s in g.signal_prefixes] == \
        [("slo.burn.", 4)]
    assert set(g.signal_read) == {
        "llm.occupancy", "llm.ew", "llm.rep", "llm.via_fwd",
        "llm.eff_a", "llm.eff_b", "ctl.mod_sig", "mod.one", "mod.two"}
    assert g.signal_read["llm.via_fwd"][0].lineno == 14
    assert {s.lineno for s in g.signal_read["llm.eff_a"]} == {16}
    assert g.signal_read["ctl.mod_sig"][0].lineno == 23
    assert g.signal_read["mod.one"][0] == mod.Site("fx/pump.py", 6)
    # only all-string tuples are consts (the mixed one must not index)
    assert g.module_consts["fx/pump.py"] == {"_SIGS": ("mod.one",
                                                       "mod.two")}

    # ---- FaultPlane: the FAULT_POINTS literal counts only in a file
    # named faults.py; fault_point("name") sites count bare or dotted
    faults_mod = 'FAULT_POINTS = ("db.write", "rpc.send")\n'
    fault_user = (
        'def crash(plane):\n'
        '    fault_point("db.write")\n'
        '    plane.fault_point("rpc.send")\n'
    )
    g = build({"fx/observability/faults.py": faults_mod,
               "fx/db.py": fault_user})
    assert set(g.fault_points) == {"db.write", "rpc.send"}
    assert g.fault_points["db.write"] == mod.Site(
        "fx/observability/faults.py", 1)
    assert {n: [s.lineno for s in sites]
            for n, sites in g.fault_calls.items()} == {
        "db.write": [2], "rpc.send": [3]}
    g = build({"fx/other.py": faults_mod})
    assert g.fault_points == {}
    assert g.module_consts["fx/other.py"]["FAULT_POINTS"] == \
        ("db.write", "rpc.send")

    # ---- Prometheus metrics: declared only inside *Registry* classes;
    # labels from the positional list or the labelnames keyword
    metrics_src = (
        'class MeterRegistry:\n'
        '    def __init__(self):\n'
        '        self.tpot = Histogram("llm_tpot_s", "h",\n'
        '                              ["tenant", "phase"])\n'
        '        self.codes = Counter("http_total", "h",\n'
        '                             labelnames=("code",))\n'
        '        self.plain = Gauge("up", "h")\n'
        '        self.notmetric = dict()\n'
        '        self.version = "1.0"\n'
        '        self.weird = Counter(NAME_CONST, "h")\n'
        '        self.num = Gauge(7, "h")\n'
        '        self.empty = Counter()\n'
        '\n'
        'class Helper:\n'
        '    def __init__(self):\n'
        '        self.stray = Counter("stray_total", "h")\n'
    )
    g = build({"fx/metrics.py": metrics_src})
    assert set(g.metrics) == {"tpot", "codes", "plain"}
    assert g.metrics["tpot"].labels == ("tenant", "phase")
    assert g.metrics["tpot"].name == "llm_tpot_s"
    assert g.metrics["tpot"].lineno == 3
    assert g.metrics["codes"].labels == ("code",)
    assert g.metrics["plain"].labels == ()

    # ---- Config knobs: Settings fields only in config.py (private and
    # model_config skipped), EngineConfig fields anywhere; attr_reads
    # indexes plain attributes AND getattr/hasattr string literals
    config_src = (
        'class Settings:\n'
        '    alpha: int = 1\n'
        '    ghost_knob: int = 2\n'
        '    _hidden: int = 3\n'
        '    model_config: dict = {}\n'
        '\n'
        'class EngineConfig:\n'
        '    pages: int = 8\n'
    )
    reader_src = (
        'def use(cfg):\n'
        '    if hasattr(cfg, "maybe_knob"):\n'
        '        return cfg.alpha + getattr(cfg, "opt_knob", 0)\n'
        '    return 0\n'
    )
    g = build({"fx/config.py": config_src, "fx/reader.py": reader_src})
    assert set(g.settings_fields) == {"alpha", "ghost_knob"}
    assert g.settings_fields["alpha"] == mod.Site("fx/config.py", 2)
    assert set(g.engine_fields) == {"pages"}
    assert g.attr_reads.get("alpha") == {"fx/reader.py"}
    assert g.attr_reads.get("maybe_knob") == {"fx/reader.py"}
    assert g.attr_reads.get("opt_knob") == {"fx/reader.py"}
    assert "ghost_knob" not in g.attr_reads
    g = build({"fx/not_config.py": config_src})
    assert g.settings_fields == {} and set(g.engine_fields) == {"pages"}

    # ---- Locks, classes, call structure
    locks_src = (
        'import threading\n'
        'import asyncio\n'
        'from os import path\n'
        '\n'
        '_IO_LOCK = threading.Lock()  # lint: lock[io]\n'
        '\n'
        'class Pool:\n'
        '    def __init__(self, clamp=None):\n'
        '        self._sched_lock = threading.Lock()'
        '  # lint: lock[sched]\n'
        '        self._stats_lock = threading.RLock()\n'
        '        self._gate = asyncio.Lock()\n'
        '        self._clamp = clamp or TenantClamp()\n'
        '    def grab(self):\n'
        '        with self._sched_lock:\n'
        '            self._note()\n'
        '    def _note(self):\n'
        '        pass\n'
    )
    g = build({"fx/pool.py": locks_src})
    assert set(g.locks) == {"pool.py:_IO_LOCK", "Pool._sched_lock",
                            "Pool._stats_lock", "Pool._gate"}
    io_lock = g.locks["pool.py:_IO_LOCK"]
    assert (io_lock.context, io_lock.kind, io_lock.lineno) == \
        ("io", "threading", 5)
    sched = g.locks["Pool._sched_lock"]
    assert (sched.context, sched.kind, sched.lineno) == \
        ("sched", "threading", 9)
    assert g.locks["Pool._stats_lock"].kind == "rlock"
    assert g.locks["Pool._gate"].kind == "asyncio"
    info = g.classes[("fx/pool.py", "Pool")]
    assert set(info.methods) == {"__init__", "grab", "_note"}
    assert info.attr_types == {"_clamp": "TenantClamp"}
    assert g.class_of_attr("fx/pool.py", "Pool", "_clamp") == "TenantClamp"
    assert g.class_of_attr("fx/pool.py", "Pool", "_gate") is None
    assert g.self_calls[("fx/pool.py", "Pool", "grab")] == {"_note"}
    assert g.functions[("fx/pool.py", "Pool.grab")] == 13
    assert g.imports["fx/pool.py"] == {"threading", "asyncio", "os"}

    # ---- find_class: simple name resolves only when unambiguous
    dup = 'class Dup:\n    pass\n'
    uniq = 'class Uniq:\n    pass\n'
    g = build({"fx/a.py": dup + uniq, "fx/b.py": dup})
    assert g.find_class("Uniq").path == "fx/a.py"
    assert g.find_class("Dup") is None
    assert g.find_class("Missing") is None
    assert sorted(g.class_index["Dup"]) == [("fx/a.py", "Dup"),
                                            ("fx/b.py", "Dup")]

    # ---- docs: in-memory fixture paths (not on disk) discover None;
    # an explicit docs_text (even empty) passes through verbatim; a
    # real tree finds the docs/ sibling, all *.md files sorted
    assert build({"fx/a.py": "x = 1\n"}).docs_text is None
    assert build({"fx/a.py": "x = 1\n"},
                 docs_text="alpha knob").docs_text == "alpha knob"
    assert build({"fx/a.py": "x = 1\n"}, docs_text="").docs_text == ""
    with tempfile.TemporaryDirectory() as tmp:
        root = _Path(tmp)
        (root / "proj" / "pkg").mkdir(parents=True)
        # a docs/ dir with no .md files does not count — the walk keeps
        # climbing to the real one
        (root / "proj" / "pkg" / "docs").mkdir()
        (root / "proj" / "docs").mkdir()
        (root / "proj" / "docs" / "a.md").write_text("ALPHA")
        (root / "proj" / "docs" / "b.md").write_text("BETA")
        mod_path = root / "proj" / "pkg" / "mod.py"
        mod_path.write_text("x = 1\n")
        ctx = FileContext.from_source("x = 1\n", mod_path.as_posix())
        assert mod.ProjectGraph.build([ctx]).docs_text == "ALPHA\nBETA"

    # ---- dump(): the debug snapshot carries every registry
    g = build({"fx/server.py": rpc_server, "fx/eng.py": signal_engine,
               "fx/metrics.py": metrics_src})
    d = g.dump()
    assert d["rpc_registered"] == ["pool.kw", "pool.status", "pool.tail"]
    assert d["signal_published"] == ["llm.occupancy", "llm.unread"]
    assert d["signal_prefixes"] == ["slo.burn."]
    assert d["metrics"] == {"tpot": ["tenant", "phase"],
                            "codes": ["code"], "plain": []}


TARGETS: dict[str, MutationTarget] = {
    "jsonrpc": MutationTarget(
        rel_path="jsonrpc.py",
        module_name="mcp_context_forge_tpu.jsonrpc",
        package="mcp_context_forge_tpu",
        oracle=jsonrpc_oracle,
    ),
    "role_resolver": MutationTarget(
        rel_path="services/role_service.py",
        module_name="mcp_context_forge_tpu.services.role_service",
        package="mcp_context_forge_tpu.services",
        oracle=role_resolver_oracle,
        class_name="RoleGrantResolver",
    ),
    "auth_context": MutationTarget(
        rel_path="services/auth_service.py",
        module_name="mcp_context_forge_tpu.services.auth_service",
        package="mcp_context_forge_tpu.services",
        oracle=auth_context_oracle,
        class_name="AuthContext",
    ),
    "quantize": MutationTarget(
        rel_path="tpu_local/quantize.py",
        module_name="mcp_context_forge_tpu.tpu_local.quantize",
        package="mcp_context_forge_tpu.tpu_local",
        oracle=lambda mod: (quantize_oracle(mod),
                            _quantize_moe_and_scale_spec(mod)),
    ),
    "page_allocator": MutationTarget(
        rel_path="tpu_local/kv/paged_cache.py",
        module_name="mcp_context_forge_tpu.tpu_local.kv.paged_cache",
        package="mcp_context_forge_tpu.tpu_local.kv",
        oracle=lambda mod: (page_allocator_oracle(mod),
                            _avg_slot_pages_spec(mod),
                            _dirty_tracking_spec(mod),
                            _pregrant_block_spec(mod),
                            _prefix_tier_spec(mod)),
        class_name="PageAllocator",
        # _take_page's `key is not None and _cached.get(key) == page` —
        # register_prefix maintains _page_key[page] == key iff
        # _cached[key] == page, so the second conjunct is purely
        # defensive and And->Or is equivalent under the invariant; and
        # the defensive ref-default in _release_page (allocate/extend/
        # match always set a ref first, so the default is unreachable).
        equivalent_markers=(
            "key is not None and self._cached.get(key) == page",
            "current = self._ref.get(page, 1)"),
    ),
    "fabric_index": MutationTarget(
        rel_path="tpu_local/kv/fabric/index.py",
        module_name="mcp_context_forge_tpu.tpu_local.kv.fabric.index",
        package="mcp_context_forge_tpu.tpu_local.kv.fabric",
        oracle=_fabric_index_spec,
        # the advert size cap is an arbitrary tunable (the spec reads
        # mod.MAX_ADVERT_HASHES, so truncation behavior is pinned at
        # whatever the cap is; nudging the constant by one is
        # behaviorally equivalent)
        equivalent_markers=("MAX_ADVERT_HASHES = 4096",),
    ),
    "eventstream": MutationTarget(
        rel_path="utils/eventstream.py",
        module_name="mcp_context_forge_tpu.utils.eventstream",
        package="mcp_context_forge_tpu.utils",
        oracle=eventstream_oracle,
        # Contract-equivalent mutants (the oracle's contract is "raises
        # EventStreamError"; which check fires is unobservable): the
        # decode_frame short-frame guard (downstream CRC/length checks
        # also raise); prelude-offset shifts (observable only in frames
        # with a >16 MB segment — leading length bytes are 0 below
        # 2^24); the iter_frames fail-fast guard (its removal/loosening
        # still ends in decode_frame or trailing-bytes raising; the
        # 16 MB cap value itself is an arbitrary tunable).
        equivalent_markers=(
            "if len(frame) < _PRELUDE_LEN + _CRC_LEN",
            'raise EventStreamError("frame shorter than prelude")',
            "total = int.from_bytes(frame[0:4]",
            "headers_len = int.from_bytes(frame[4:8]",
            "total = int.from_bytes(buf[0:4]",
            "if total < _PRELUDE_LEN + _CRC_LEN or total > 16",
            'raise EventStreamError(f"implausible frame length',
            "if len(buf) < total",
            # the buffering loop condition `len(buf) >= _PRELUDE_LEN` vs
            # `>`: at exactly prelude-many bytes the loop just waits for
            # the next chunk — frame decoding is unchanged
            "while len(buf) >= _PRELUDE_LEN"),
    ),
    "tool_calls": MutationTarget(
        rel_path="tpu_local/tool_calls.py",
        module_name="mcp_context_forge_tpu.tpu_local.tool_calls",
        package="mcp_context_forge_tpu.tpu_local",
        oracle=tool_calls_oracle,
        # `0 <= start < end` Lt->LtE — find(open) and rfind(close) are
        # different characters, so start == end is unsatisfiable.
        equivalent_markers=("if 0 <= start < end:",),
    ),
    "lint_core": MutationTarget(
        rel_path="tools/lint/core.py",
        module_name="mcp_context_forge_tpu.tools.lint.core",
        package="mcp_context_forge_tpu.tools.lint",
        oracle=lint_core_oracle,
        # `exc.lineno or 0`: the fallback fires only when a SyntaxError
        # carries no line number, which CPython's parser never produces
        # for the sources a lint run feeds it — nudging the constant is
        # unobservable
        equivalent_markers=("exc.lineno or 0",),
    ),
    "lint_project": MutationTarget(
        rel_path="tools/lint/project.py",
        module_name="mcp_context_forge_tpu.tools.lint.project",
        package="mcp_context_forge_tpu.tools.lint",
        oracle=lint_project_oracle,
        # basename via rsplit("/", 1)[-1]: nudging maxsplit only adds
        # splits LEFT of the one [-1] reads — the basename is identical
        equivalent_markers=('ctx.path.rsplit("/", 1)[-1]',),
    ),
    "rate_limiter": MutationTarget(
        rel_path="gateway/middleware.py",
        module_name="mcp_context_forge_tpu.gateway.middleware",
        package="mcp_context_forge_tpu.gateway",
        oracle=rate_limiter_oracle,
        class_name="RateLimiter",
        # the max_buckets DEFAULT — nudging the 100_000 cap by one is
        # behaviorally equivalent (oracle passes explicit caps); and the
        # sweep-trigger compare `now >= _next_sweep` vs `>` differs only
        # at exact monotonic-clock equality (measure zero — the sweep
        # fires one tick later)
        equivalent_markers=("max_buckets: int = 100_000",
                            "now >= self._next_sweep"),
    ),
}
