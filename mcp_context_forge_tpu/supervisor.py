"""Multi-worker process supervisor (reference: gunicorn.config.py +
run-gunicorn.sh — N workers per pod, restart on crash).

Two socket layouts (docs/scaleout.md):

- ``reuse_port=True`` (the scale-out default): every worker binds the
  SAME ``base_port`` with ``SO_REUSEPORT`` — the kernel hashes incoming
  connections across the workers' accept queues, no front LB needed.
  One advertised port, N serving processes.
- ``reuse_port=False`` (legacy): consecutive ports, an external LB
  spreads traffic.

Either way the supervisor runs an embedded coordination hub the workers
share for affinity/leader/bus/RPC/limiter, stamps each worker with its
index + fleet size (fleet metrics aggregation reads them), and restarts
crashed workers with exponential backoff; SIGTERM/SIGINT stop everything.

Run: ``python -m mcp_context_forge_tpu.cli supervise --workers 4``
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import threading
import time

logger = logging.getLogger(__name__)


class Supervisor:
    def __init__(self, workers: int, host: str, base_port: int,
                 hub_port: int | None = None, env: dict | None = None,
                 max_backoff: float = 30.0, reuse_port: bool = True,
                 pin_cpus: bool = False):
        self.workers = workers
        self.host = host
        self.base_port = base_port
        self.hub_port = hub_port
        self.env = env or {}
        self.max_backoff = max_backoff
        self.reuse_port = reuse_port
        # per-worker CPU pinning (Linux sched_setaffinity): worker idx i
        # pins to core i % ncpus, so N workers on an N-core box never
        # migrate onto each other's cores mid-burst. Off by default —
        # pinning on an oversubscribed box (other tenants, fewer cores
        # than workers) HURTS, so the operator opts in (--pin-cpus)
        self.pin_cpus = pin_cpus and hasattr(os, "sched_setaffinity")
        self._procs: dict[int, subprocess.Popen] = {}   # worker idx -> proc
        self._backoff: dict[int, float] = {}
        self._restart_at: dict[int, float] = {}  # idx -> earliest respawn time
        self._healthy_passes: dict[int, int] = {}
        self._hub_proc: subprocess.Popen | None = None
        self._stopping = threading.Event()

    # ------------------------------------------------------------- spawning

    def _worker_env(self, idx: int) -> dict:
        env = {**os.environ, **self.env}
        if self.hub_port is not None:
            # the supervisor owns the hub: workers MUST ride it (an inherited
            # memory/file backend would silently split the coordination plane)
            env["MCPFORGE_BUS_BACKEND"] = "tcp"
            env["MCPFORGE_BUS_TCP_HOST"] = "127.0.0.1"
            env["MCPFORGE_BUS_TCP_PORT"] = str(self.hub_port)
        env["MCPFORGE_WORKER_INDEX"] = str(idx)
        # fleet identity: metrics aggregation + bench captures read these
        env["MCPFORGE_GW_WORKERS"] = str(self.workers)
        if self.workers > 1:
            env.setdefault("MCPFORGE_GW_FLEET_METRICS", "true")
        if self.reuse_port:
            env["MCPFORGE_GW_REUSE_PORT"] = "true"
        return env

    def _pin_worker(self, idx: int, proc: subprocess.Popen) -> None:
        """Pin worker ``idx`` to one core (round-robin over the
        supervisor's own affinity mask). From the parent, post-spawn —
        the worker needs no pinning code and a failed pin (proc already
        died, restricted cgroup) degrades to unpinned, never to a dead
        worker."""
        cpus = sorted(os.sched_getaffinity(0))
        cpu = cpus[idx % len(cpus)]
        try:
            os.sched_setaffinity(proc.pid, {cpu})
            logger.info("supervisor: pinned worker %d (pid %d) to cpu %d",
                        idx, proc.pid, cpu)
        except OSError as exc:
            logger.warning("supervisor: could not pin worker %d: %s",
                           idx, exc)

    def _spawn_worker(self, idx: int) -> subprocess.Popen:
        port = self.base_port if self.reuse_port else self.base_port + idx
        logger.info("supervisor: starting worker %d on %s:%d%s", idx,
                    self.host, port,
                    " (SO_REUSEPORT)" if self.reuse_port else "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "mcp_context_forge_tpu.cli", "serve",
             "--host", self.host, "--port", str(port)],
            env=self._worker_env(idx))
        if self.pin_cpus:
            self._pin_worker(idx, proc)
        return proc

    def _spawn_hub(self) -> subprocess.Popen:
        logger.info("supervisor: starting coordination hub on :%d",
                    self.hub_port)
        env = {**os.environ, **self.env}
        secret = env.get("MCPFORGE_BUS_TCP_SECRET") or env.get(
            "MCPFORGE_JWT_SECRET_KEY", "")
        return subprocess.Popen(
            [sys.executable, "-m", "mcp_context_forge_tpu.coordination.hub",
             "--host", "127.0.0.1", "--port", str(self.hub_port),
             "--secret", secret],
            env=env)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self.hub_port is not None:
            self._hub_proc = self._spawn_hub()
            time.sleep(0.3)
        for idx in range(self.workers):
            self._procs[idx] = self._spawn_worker(idx)
            self._backoff[idx] = 0.5

    def stop(self) -> None:
        self._stopping.set()
        for proc in list(self._procs.values()) + (
                [self._hub_proc] if self._hub_proc else []):
            if proc.poll() is None:
                proc.terminate()
        deadline = time.time() + 10
        for proc in list(self._procs.values()) + (
                [self._hub_proc] if self._hub_proc else []):
            remaining = max(0.1, deadline - time.time())
            try:
                proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                proc.kill()

    # a worker must survive this many reap passes before its backoff resets
    # (a single healthy poll between crashes must not defeat the escalation)
    HEALTHY_RESET_PASSES = 10

    def reap_once(self) -> None:
        """One supervision pass: restart dead workers whose backoff deadline
        has arrived. Never sleeps — one crash-looping worker must not stall
        supervision of the others or of the hub."""
        now = time.monotonic()
        for idx, proc in list(self._procs.items()):
            code = proc.poll()
            if code is None:
                self._healthy_passes[idx] = self._healthy_passes.get(idx, 0) + 1
                if self._healthy_passes[idx] >= self.HEALTHY_RESET_PASSES:
                    self._backoff[idx] = 0.5
                continue
            if self._stopping.is_set():
                continue
            self._healthy_passes[idx] = 0
            deadline = self._restart_at.get(idx)
            if deadline is None:
                delay = self._backoff.get(idx, 0.5)
                self._restart_at[idx] = now + delay
                self._backoff[idx] = min(delay * 2, self.max_backoff)
                logger.warning("supervisor: worker %d exited rc=%s; restart"
                               " in %.1fs", idx, code, delay)
            elif now >= deadline:
                del self._restart_at[idx]
                self._procs[idx] = self._spawn_worker(idx)
        if (self._hub_proc is not None and self._hub_proc.poll() is not None
                and not self._stopping.is_set()):
            logger.warning("supervisor: hub exited rc=%s; restarting",
                           self._hub_proc.returncode)
            self._hub_proc = self._spawn_hub()

    def run_forever(self) -> None:  # pragma: no cover - signal-driven loop
        def _on_signal(signum, frame):
            self.stop()
            raise SystemExit(0)

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)
        self.start()
        while not self._stopping.is_set():
            self.reap_once()
            time.sleep(1.0)
