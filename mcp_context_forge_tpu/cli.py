"""CLI entry point (reference: mcpgateway/cli.py uvicorn launcher).

Subcommands: serve (default), token (mint an admin JWT), export/import
(config snapshot — wired when export_service lands)."""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="mcpforge",
                                     description="TPU-native MCP gateway")
    sub = parser.add_subparsers(dest="command")

    serve = sub.add_parser("serve", help="run the gateway")
    serve.add_argument("--host", default=None)
    serve.add_argument("--port", type=int, default=None)

    token = sub.add_parser("token", help="mint a JWT for an email")
    token.add_argument("email")
    token.add_argument("--expires-minutes", type=int, default=60)

    sub.add_parser("version", help="print version")

    args = parser.parse_args(argv)
    command = args.command or "serve"

    if command == "version":
        from . import __version__
        print(__version__)
        return 0

    from .config import get_settings
    settings = get_settings()

    if command == "token":
        from .utils import jwt
        print(jwt.create_token({"sub": args.email}, settings.jwt_secret_key,
                               settings.jwt_algorithm,
                               expires_minutes=args.expires_minutes,
                               audience=settings.jwt_audience,
                               issuer=settings.jwt_issuer))
        return 0

    if command == "serve":
        if args.host:
            settings = settings.model_copy(update={"host": args.host})
        if args.port:
            settings = settings.model_copy(update={"port": args.port})
        from .gateway.app import run
        run(settings)
        return 0

    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
