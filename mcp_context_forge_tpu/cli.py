"""CLI entry point (reference: mcpgateway/cli.py uvicorn launcher).

Subcommands: serve (default), token (mint an admin JWT), export/import
(config snapshot — wired when export_service lands)."""

from __future__ import annotations

import argparse
import os
import sys


def _pin_jax_platform() -> None:
    """Honor MCPFORGE_JAX_PLATFORM before any backend init.

    Site hooks that force a hardware PJRT plugin can override the plain
    ``JAX_PLATFORMS`` env var; ``jax.config.update`` wins over both, so an
    operator can pin ``cpu`` to serve through a dead/absent accelerator
    runtime (pairs with the engine's init watchdog)."""
    platform = os.environ.get("MCPFORGE_JAX_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def main(argv: list[str] | None = None) -> int:
    _pin_jax_platform()
    parser = argparse.ArgumentParser(prog="mcpforge",
                                     description="TPU-native MCP gateway")
    sub = parser.add_subparsers(dest="command")

    serve = sub.add_parser("serve", help="run the gateway")
    serve.add_argument("--host", default=None)
    serve.add_argument("--port", type=int, default=None)

    supervise = sub.add_parser(
        "supervise", help="run N worker processes + coordination hub "
                          "(reference: gunicorn multi-worker)")
    supervise.add_argument("--workers", type=int, default=2)
    supervise.add_argument("--host", default=None)
    supervise.add_argument("--port", type=int, default=None,
                           help="shared SO_REUSEPORT port (default), or the "
                                "base port with --port-per-worker")
    supervise.add_argument("--hub-port", type=int, default=None,
                           help="coordination hub port (default: base port-1)")
    supervise.add_argument("--no-hub", action="store_true",
                           help="workers use an external bus (no embedded hub)")
    supervise.add_argument("--port-per-worker", action="store_true",
                           help="legacy layout: worker i listens on port+i "
                                "behind an external LB instead of one "
                                "SO_REUSEPORT socket")
    supervise.add_argument("--pin-cpus", action="store_true",
                           help="pin worker i to cpu i%%ncpus "
                                "(sched_setaffinity; Linux only, opt-in — "
                                "helps only when workers <= free cores)")

    token = sub.add_parser("token", help="mint a JWT for an email")
    token.add_argument("email")
    token.add_argument("--expires-minutes", type=int, default=60)

    sub.add_parser("version", help="print version")

    args = parser.parse_args(argv)
    command = args.command or "serve"

    if command == "version":
        from . import __version__
        print(__version__)
        return 0

    from .config import get_settings
    settings = get_settings()

    if command == "token":
        from .utils import jwt
        print(jwt.create_token({"sub": args.email}, settings.jwt_secret_key,
                               settings.jwt_algorithm,
                               expires_minutes=args.expires_minutes,
                               audience=settings.jwt_audience,
                               issuer=settings.jwt_issuer))
        return 0

    if command == "serve":
        if args.host:
            settings = settings.model_copy(update={"host": args.host})
        if args.port:
            settings = settings.model_copy(update={"port": args.port})
        from .gateway.app import run
        run(settings)
        return 0

    if command == "supervise":
        from .supervisor import Supervisor
        base_port = args.port or settings.port
        supervisor = Supervisor(
            workers=args.workers, host=args.host or settings.host,
            base_port=base_port,
            hub_port=None if args.no_hub else (args.hub_port or base_port - 1),
            reuse_port=not args.port_per_worker,
            pin_cpus=args.pin_cpus)
        supervisor.run_forever()
        return 0

    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
