"""Replica routing policy for the EnginePool.

Two signals, in order ("A System for Microserving of LLMs",
arXiv:2412.12488 — context-aware routing over disaggregated engines;
xLLM's scheduler makes the same trade):

1. **prefix-cache affinity** — each replica owns its own HBM KV pool
   and prefix cache, so a request whose prompt prefix is resident on
   replica R prefills only its suffix there and the full prompt
   anywhere else. Affinity is scored from BOTH views of residency:
   the replica's own read-only ``allocator.probe_prefix`` (local HBM
   plus, with tiers on, the shared spill store that replica could
   restore from) and the pool-global prefix index
   (``kv/prefix_index.py``) — so a prefix resident only on replica 1's
   HBM raises replica 1's score no matter which replica is examined
   first, and a chain spilled to the pool-shared host/disk tiers counts
   as a hit for EVERY replica (fetch-on-miss restores it at admission,
   so tier hits are affinity-real but placement-neutral: the
   least-outstanding signal below breaks the tie). No page references
   are taken by any probe — pending requests must never pin cache
   pages. An affinity win only counts when it is worth at least one
   full page: sub-page "hits" save nothing (the engine re-buckets them
   away at admission).
2. **least outstanding decode tokens** — among equally-affine replicas,
   route to the one with the least budgeted work (sum over in-flight
   requests of their remaining ``max_tokens``), the pool's proxy for
   time-to-first-slot. Ties break round-robin so cold starts spread.

With ROLES assigned (docs/disaggregation.md), a third signal runs
FIRST: a classed request ("prefill"/"decode" for the phase split, or
any fleet class routed behind the same field) is scored only over
replicas holding that exact role plus the "any" generalists — the
latter carrying a configurable outstanding-token penalty, so an
oversubscribed exact-role tier spills onto idle generalist capacity
but never loses to it at load parity. A class no replica serves falls
back to the full routable set: roles shape placement, they never
refuse capacity.

Priority rides THROUGH the router untouched: admission classes are a
per-replica scheduler concern (the engine's priority-sorted pending
queue), not a placement one — a pool that sent all priority-0 traffic
to one replica would serialize exactly the requests that most want
spare capacity.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Sequence

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..kv.prefix_index import PrefixIndex
    from .pool import EngineReplica


class ReplicaRouter:
    """Scores routable replicas; owns the routing counters the admin
    surface reports. Runs on the gateway loop (submit path)."""

    def __init__(self, affinity: bool = True,
                 index: "PrefixIndex | None" = None,
                 page_size: int = 0,
                 role_penalty_tokens: int = 256) -> None:
        self.affinity_routing = affinity
        self._index = index
        self._page_size = page_size
        self.role_penalty_tokens = max(0, int(role_penalty_tokens))
        self.routed = 0           # lint: thread[pool]
        self.affinity_hits = 0    # lint: thread[pool]
        self.index_hits = 0       # routes the pool index steered  # lint: thread[pool]
        self.role_routed = 0      # classed routes an exact role served  # lint: thread[pool]
        self.role_spills = 0      # classed routes an "any" replica took  # lint: thread[pool]
        self._rr = 0              # round-robin tiebreak cursor  # lint: thread[pool]

    def route(self, replicas: Sequence["EngineReplica"],  # lint: runs-on[pool]  # lint: hot-path
              prompt_ids: list[int],
              route_class: str = "") -> tuple["EngineReplica", bool]:
        """Pick a replica for ``prompt_ids`` among ``replicas`` (already
        filtered to routable ones, non-empty). Returns (replica,
        affinity_hit). On the submit hot path: pure host-side scoring
        (dict walks over the allocator and the pool index), no device
        sync. A single routable replica still scores — the affinity
        accounting must stay truthful when the pool is degraded to one
        survivor. A non-empty ``route_class`` narrows the candidate set
        to exact-role + "any" replicas (module doc), falling back to the
        full set when the class is unserved."""
        candidates: Sequence["EngineReplica"] = replicas
        if route_class:
            narrowed = [r for r in replicas
                        if r.role in (route_class, "any")]
            if narrowed:
                candidates = narrowed
        choice, hit = self._score(candidates, prompt_ids, route_class)
        self.routed += 1
        if hit:
            self.affinity_hits += 1
        if route_class:
            if choice.role == route_class:
                self.role_routed += 1
            elif choice.role == "any":
                self.role_spills += 1
        return choice, hit

    def _score(self, replicas: Sequence["EngineReplica"],
               prompt_ids: list[int],
               route_class: str = "") -> tuple["EngineReplica", bool]:
        best = None
        best_key = None
        best_hist = 0
        chain = None
        if self.affinity_routing and self._index is not None \
                and self._page_size > 0:
            chain = self._index.chain_locations(prompt_ids, self._page_size)
            if not any(hbm or tiered for hbm, tiered in chain):
                chain = None  # nothing indexed: skip the per-replica fold
        best_from_index = False
        self._rr += 1
        for i, replica in enumerate(replicas):
            hist = 0
            from_index = False
            if self.affinity_routing:
                engine = replica.engine
                if engine.config.prefix_cache:
                    hist = engine.allocator.probe_prefix(prompt_ids)
                    if chain is not None:
                        # pool-global view: pages resident on THIS
                        # replica's HBM or restorable from a shared tier
                        idx_hist = self._index.reachable_tokens(
                            chain, replica.id, self._page_size)
                        if idx_hist > hist:
                            hist = idx_hist
                            from_index = True
                    if hist < engine.config.page_size:
                        hist = 0  # sub-page match saves no prefill
            # max affinity, then min outstanding tokens (generalists pay
            # the role penalty so exact-role replicas win at load parity
            # while an oversubscribed tier still spills), then round-robin
            load = replica.outstanding_tokens()
            if route_class and replica.role != route_class:
                load += self.role_penalty_tokens
            key = (-hist, load, (i + self._rr) % len(replicas))
            if best_key is None or key < best_key:
                best, best_key, best_hist = replica, key, hist
                best_from_index = from_index and hist > 0
        if best_from_index:
            self.index_hits += 1
        return best, best_hist > 0

    def counters(self) -> dict[str, int]:
        return {"routed": self.routed, "affinity_hits": self.affinity_hits,
                "index_hits": self.index_hits,
                "role_routed": self.role_routed,
                "role_spills": self.role_spills}
