"""Engine replica pool: affinity-routed multi-replica serving tier with
failover and rolling reload (docs/serving_pool.md)."""

from .health import HealthMonitor
from .pool import EnginePool, EngineReplica, PoolRecord, partition_devices
from .router import ReplicaRouter

__all__ = ["EnginePool", "EngineReplica", "PoolRecord", "HealthMonitor",
           "ReplicaRouter", "partition_devices"]
