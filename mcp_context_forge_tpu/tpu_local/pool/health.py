"""Replica health monitoring for the EnginePool.

Two failure modes, two signals (both read-only, both host-side):

- **crashed** — the dispatch thread died (device fault without
  ``auto_restart``, or restarts exhausted): ``engine.dispatch_alive()``
  goes false. The engine's own ``_fail_outstanding`` already terminated
  every stream with ``finish_reason="error"``, so the pool's per-request
  pumps see the terminals and requeue; the monitor's job is to mark the
  replica dead so the router stops sending it new work, and to catch any
  record whose pump raced the crash.
- **wedged** — the thread is alive but stuck inside a device call (dead
  TPU tunnel, post-warmup runtime fault): the dispatch-loop heartbeat
  goes stale while the replica still holds in-flight work. An IDLE
  engine also beats (the idle wait is bounded at 50 ms), so staleness
  is only read against replicas with outstanding requests — and only
  against WARMED engines. On an unwarmed engine any dispatch, first or
  mid-traffic (a new batch width, a bigger ctx bucket), may
  legitimately sit in an XLA compile longer than any sane heartbeat
  bar, and killing a compiling replica cascades: its work requeues onto
  an equally unwarmed survivor that compiles the same shapes. A warmed
  engine has no compiles left (the grid is precompiled under the
  traffic cache key), so staleness there is a genuine stall. Unwarmed
  pools keep crash detection only — run ``tpu_local_warmup`` with
  pools (docs/serving_pool.md).

On detection the monitor kills the engine (signal, no join — a wedged
thread must not delay failover), marks the replica dead, and asks the
pool to requeue its in-flight requests onto healthy replicas.

Runs as an asyncio task on the gateway loop — all pool state stays
single-threaded (the ``thread[pool]`` lint boundary); only the engines'
own dispatch threads are separate, and the monitor touches them through
the read-only liveness API + kill().
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .pool import EnginePool

logger = logging.getLogger(__name__)


class HealthMonitor:
    """Periodic liveness sweep over the pool's replicas."""

    def __init__(self, pool: "EnginePool", interval_s: float = 0.5,
                 heartbeat_timeout_s: float = 10.0) -> None:
        self.pool = pool
        self.interval_s = max(0.01, interval_s)
        self.heartbeat_timeout_s = max(0.05, heartbeat_timeout_s)
        self._task: asyncio.Task | None = None
        self.sweeps = 0           # lint: thread[pool]
        self.failures = 0         # lint: thread[pool]

    async def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="engine-pool-health")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:  # lint: runs-on[pool]
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                self.sweep()
            except Exception:  # the monitor must outlive a bad sweep
                logger.exception("engine pool health sweep failed")

    def sweep(self) -> None:  # lint: runs-on[pool]
        """One liveness pass; synchronous so tests can drive it directly."""
        self.sweeps += 1
        for replica in self.pool.replicas:
            if replica.state not in ("ready", "draining"):
                continue
            verdict = self.verdict(replica)
            if verdict is not None:
                self.failures += 1
                logger.error("engine pool: replica %s (role %s) %s — "
                             "failing over", replica.id, replica.role,
                             verdict)
                self.pool.fail_replica(replica, reason=verdict)

    def verdict(self, replica) -> str | None:
        """None = healthy; otherwise a short reason string."""
        engine = replica.engine
        if not engine.dispatch_alive():
            return "dispatch thread dead"
        if replica.outstanding and engine.warmed:
            # wedge detection is armed only on WARMED engines: on an
            # unwarmed one ANY dispatch — first or mid-traffic (a new
            # batch width, a bigger ctx bucket) — may legitimately sit in
            # an XLA compile longer than the heartbeat bar, and killing a
            # compiling replica requeues its work onto an equally
            # unwarmed survivor that compiles the same shapes: a
            # monitor-induced cascade. A warmed engine has no compiles
            # left (the grid is precompiled under the traffic cache key),
            # so staleness there is a genuine stall. Unwarmed pools keep
            # crash detection (dispatch_alive, above) only — run
            # tpu_local_warmup with pools (docs/serving_pool.md).
            age = engine.heartbeat_age()
            step_age = engine.last_step_age()
            if step_age is None:
                # no traffic step retired yet: a stale heartbeat is a
                # wedge (dead tunnel before the first step), and without
                # this arm the request would hang forever (step_age never
                # becomes non-None on a replica that cannot retire a
                # step).
                if age > self.heartbeat_timeout_s:
                    return (f"wedged: heartbeat stale {age:.1f}s before "
                            f"first step with "
                            f"{len(replica.outstanding)} in-flight")
            # both signals must agree once the replica has proven it can
            # retire steps
            elif (age > self.heartbeat_timeout_s
                    and step_age > self.heartbeat_timeout_s):
                return (f"wedged: heartbeat stale {age:.1f}s with "
                        f"{len(replica.outstanding)} in-flight")
        return None
