"""EnginePool: an affinity-routed multi-replica serving tier.

One ``TPUEngine`` is one mesh, one dispatch thread, one failure domain.
The pool owns N of them — device-subset meshes carved out of
``jax.devices()`` (N full-overlap CPU replicas in tests) — behind the
same submit/generate surface the provider already speaks, adding what a
single replica cannot have:

- **routing** (router.py): prefix-cache affinity first, then least
  outstanding decode tokens, per-priority admission carried through to
  each replica's own scheduler;
- **failover** (health.py): a crashed or wedged replica's in-flight
  requests REQUEUE onto healthy replicas as continuations — the new
  prompt is (original prompt + tokens already emitted), so consumers
  see every token exactly once and greedy streams continue
  byte-identically. Composes with the engine's once-only admission
  guard: requeued shadows carry ``queue_observed=True`` so the logical
  request's queue-wait is observed exactly once;
- **drain/reload**: rolling checkpoint hot-swap per replica
  (``drain -> swap weights -> readmit``) while the rest of the pool
  keeps serving;
- **disaggregated prefill/decode** (docs/disaggregation.md): replicas
  carry ROLES (``prefill`` / ``decode`` / ``any``) and the router
  classes each admission by prompt length (or an explicit
  ``route_class``). A prefill-classed request lands on a prefill
  replica capped at ONE decode token, its prompt KV chain is exported
  through the pool-shared spill tiers, verified page-by-page against
  the token content (the same verify-before-serve gate admission
  restores ride), and the request continues on a decode replica as a
  pool-shadow continuation — the exact mechanism failover already
  uses, so greedy streams stay byte-identical across the hop. ANY
  failed step degrades to decode-in-place on the prefill replica;
  migration never loses a stream.

Requests are never handed to an engine directly: the pool submits a
*shadow* request and pumps its stream into the client's, which is the
interception point failover needs (the engine's terminal "error" post
must not reach the consumer when a survivor can finish the request).

All pool state lives on the gateway's asyncio loop (the ``thread[pool]``
lint context); engines' dispatch threads are reached only through their
thread-safe submit/kill/liveness surfaces.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable, Sequence

from ...observability.logging import trace_extra
from ..engine import EngineConfig, EngineStats, GenRequest, TPUEngine, probe_devices
from ..parallel import mesh_shape_from_string
from .health import HealthMonitor
from .router import ReplicaRouter

logger = logging.getLogger(__name__)

#: legal replica roles (docs/disaggregation.md). "prefill"/"decode" are
#: the phase split; "any" is the generalist default every pool starts
#: with. The field is deliberately a plain string so future fleet
#: classes (model-size tiers, tenant SLO classes) ride the same router
#: narrowing without a schema change.
REPLICA_ROLES = ("prefill", "decode", "any")


def partition_devices(devices: list, n: int) -> list[list]:
    """Split the device list into n replica meshes.

    With at least n devices each replica gets an equal contiguous slice
    (remainder devices are dropped with a warning — a 3-replica pool on
    8 chips serves 2+2+2 and idles 2; pick divisors). With fewer devices
    than replicas (CPU tests, single-chip dev boxes) every replica runs
    the FULL set: correctness-identical, throughput shared."""
    if n <= 1:
        return [list(devices)]
    if len(devices) >= n:
        per = len(devices) // n
        dropped = len(devices) - per * n
        if dropped:
            logger.warning(
                "engine pool: %d device(s) idle (%d devices / %d replicas)",
                dropped, len(devices), n)
        return [list(devices[i * per:(i + 1) * per]) for i in range(n)]
    logger.info("engine pool: %d replicas sharing %d device(s) "
                "(test/dev topology)", n, len(devices))
    return [list(devices) for _ in range(n)]


@dataclass
class PoolRecord:
    """One logical client request as the pool tracks it: the client-facing
    GenRequest (never submitted to any engine) plus the engine-facing
    shadow currently serving it."""
    request: GenRequest
    shadow: GenRequest
    replica: "EngineReplica"
    attempts: int = 1            # dispatches so far (1 = never requeued)
    pump: asyncio.Task | None = None
    done: bool = False
    # disaggregation: this shadow is the one-token PREFILL leg of a
    # migration — its "length" terminal means "hand off to a decode
    # replica", not "budget spent" (docs/disaggregation.md)
    migrate_leg: bool = False


class EngineReplica:
    """One engine plus the pool's view of it."""

    STATES = ("ready", "draining", "reloading", "dead")

    def __init__(self, rid: str, index: int, engine: TPUEngine,
                 role: str = "any") -> None:
        self.id = rid
        self.index = index
        self.engine = engine
        self.state = "ready"
        self.role = role
        self.outstanding: dict[str, PoolRecord] = {}
        self.routed = 0
        self.requeued_off = 0
        self.reloads = 0
        self.failures = 0
        self.last_failure = ""
        self.migrations_out = 0   # prefill legs this replica handed off
        self.migrations_in = 0    # decode continuations it received

    def outstanding_tokens(self) -> int:
        """Budgeted work still owed: the router's least-loaded signal."""
        return sum(max(0, rec.request.max_tokens - len(rec.request.generated))
                   for rec in self.outstanding.values())

    def status(self) -> dict[str, Any]:
        engine = self.engine
        stats = engine.stats
        return {
            "id": self.id,
            "state": self.state,
            "role": self.role,
            "model": engine.config.model,
            "mesh_devices": int(engine.mesh.size),
            "dispatch_alive": engine.dispatch_alive(),
            "heartbeat_age_s": round(engine.heartbeat_age(), 3),
            # occupancy: slots carrying work right now vs capacity
            "occupancy": len(engine._running) + len(engine._chunking),
            "max_batch": engine.config.max_batch,
            "outstanding": len(self.outstanding),
            "outstanding_tokens": self.outstanding_tokens(),
            "kv_pages_in_use": engine.allocator.pages_in_use,
            "queue_depth": stats.queue_depth,
            "requests": stats.requests,
            "completion_tokens": stats.completion_tokens,
            "decode_steps": stats.decode_steps,
            "engine_restarts": stats.engine_restarts,
            "routed": self.routed,
            "requeued_off": self.requeued_off,
            "migrations_out": self.migrations_out,
            "migrations_in": self.migrations_in,
            "reloads": self.reloads,
            "failures": self.failures,
            "last_failure": self.last_failure,
            # mid-traffic XLA compiles (compile_events.py): serving-stage
            # count > 0 on a warmed replica is the PR-5 catastrophe — the
            # health monitor's wedge bar assumes it stays 0
            "xla_compiles": engine.compile_stats(),
            # live cost-model roofline over the recent decode window
            "roofline": engine.roofline_snapshot(),
            # tiered prefix cache: this replica's per-tier hit split +
            # spill/restore counters (None when tiers and index are off)
            "prefix_tiers": engine.tier_stats(),
        }


class EnginePool:
    """N TPUEngine replicas behind the single-engine serving surface."""

    def __init__(self, config: EngineConfig, replicas: int = 2,
                 tracer=None, metrics=None,
                 affinity_routing: bool = True,
                 health_interval_s: float = 0.5,
                 heartbeat_timeout_s: float = 10.0,
                 requeue_max: int = 2,
                 devices: list | None = None,
                 engine_factory: Callable[..., TPUEngine] | None = None,
                 ledger=None, signals=None,
                 roles: str | Sequence[str] | None = None,
                 disagg_prompt_tokens: int = 64,
                 role_penalty_tokens: int = 256):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.config = config
        self.tracer = tracer
        self.metrics = metrics
        # one live-signal bus shared by every replica (and every
        # reload-rebuilt engine): per-replica aggregates the serving
        # controller consumes must survive hot-swap
        self.signals = signals
        # one tenant ledger shared by every replica (and every rebuilt
        # engine a reload produces): per-tenant token accounting must
        # survive failover and hot-swap with nothing lost or double-billed
        self.ledger = ledger
        # pool-global prefix plane (docs/kv_tiering.md): ONE index maps
        # hashed prefix chains -> (replica | tier) locations — replicas
        # publish their HBM registrations into it and the router scores
        # it as affinity — and, with prefix_tiers on, ONE spill store is
        # shared by every replica so admission can fetch-on-miss: a
        # prefix prefilled (then evicted) on any replica restores into
        # the admitting replica's own HBM. Both survive reload-rebuilt
        # engines (content-addressed by token chain, not replica state).
        self.prefix_index = None
        self.tier_store = None
        if config.prefix_cache:
            from ..kv.prefix_index import PrefixIndex
            self.prefix_index = PrefixIndex()
            if config.prefix_tiers:
                from ..kv.fabric.object_store import object_store_or_none
                from ..kv.tiers import TieredPageStore
                self.tier_store = TieredPageStore(
                    host_bytes=config.tier_host_bytes,
                    disk_bytes=config.tier_disk_bytes,
                    disk_dir=config.tier_disk_dir,
                    index=self.prefix_index, metrics=metrics,
                    io_retry_max=config.tier_io_retry_max,
                    io_retry_backoff_ms=config.tier_io_retry_backoff_ms,
                    object_store=object_store_or_none(
                        config.tier_object_url),
                    object_namespace=config.fabric_namespace)
        self.requeue_max = max(0, requeue_max)
        self._factory = engine_factory or (
            lambda cfg, tracer, metrics, devices, ledger=None,
            tier_store=None, prefix_index=None: TPUEngine(
                cfg, tracer=tracer, metrics=metrics, devices=devices,
                ledger=ledger, tier_store=tier_store,
                prefix_index=prefix_index))
        if devices is None:
            devices = probe_devices(config.init_timeout_s)
        self._device_sets = partition_devices(devices, replicas)
        # an explicit tpu_local_mesh_shape is sized for the FULL machine;
        # replicas get a device subset, so the spec would fail every
        # per-replica make_mesh (e.g. "1x8" on a 2-replica v5e-8 pool
        # where each replica holds 4 chips). Fall back to the auto mesh
        # (1 x subset) rather than refusing to boot.
        self._mesh_shape = config.mesh_shape
        if self._mesh_shape and replicas > 1:
            per = len(self._device_sets[0])
            try:
                mesh_shape_from_string(self._mesh_shape, per)
            except ValueError:
                logger.warning(
                    "engine pool: mesh shape %r does not fit the %d "
                    "device(s) each of %d replicas receives — using the "
                    "auto (1, %d) mesh per replica",
                    self._mesh_shape, per, replicas, per)
                self._mesh_shape = ""
        # disaggregation (docs/disaggregation.md): per-replica roles,
        # assignable statically here (comma string from config or a
        # sequence) and dynamically over set_role / the admin surface /
        # the BusRpc lease plane. Short lists pad with "any"; bad role
        # names refuse to boot rather than silently routing everything.
        role_list: list[str] = []
        if roles:
            parts = (roles.split(",") if isinstance(roles, str)
                     else list(roles))
            role_list = [str(p).strip().lower() for p in parts
                         if str(p).strip()]
            for role in role_list:
                if role not in REPLICA_ROLES:
                    raise ValueError(
                        f"unknown replica role {role!r} "
                        f"(roles are {list(REPLICA_ROLES)})")
        self.disagg_prompt_tokens = max(1, int(disagg_prompt_tokens))
        self.replicas: list[EngineReplica] = []
        for i in range(replicas):
            self.replicas.append(
                EngineReplica(str(i), i, self._build_engine(i),
                              role=(role_list[i] if i < len(role_list)
                                    else "any")))
        self.router = ReplicaRouter(affinity=affinity_routing,
                                    index=self.prefix_index,
                                    page_size=config.page_size,
                                    role_penalty_tokens=role_penalty_tokens)
        self.health = HealthMonitor(self, interval_s=health_interval_s,
                                    heartbeat_timeout_s=heartbeat_timeout_s)
        self.tokenizer = self.replicas[0].engine.tokenizer
        self.requeues = 0            # lint: thread[pool]
        # migration accounting (conservation gate: pages spilled ==
        # pages restored + pages degraded-in-place — pinned in tests)
        self.migrations = {"ok": 0, "degraded": 0}        # lint: thread[pool]
        self.migration_pages = {"spilled": 0, "restored": 0,
                                "degraded": 0}            # lint: thread[pool]
        self.migration_bytes = 0     # lint: thread[pool]
        self._started = False        # lint: thread[pool]
        self._stopping = False       # lint: thread[pool]
        self._set_up_gauges()

    def _build_engine(self, index: int) -> TPUEngine:
        cfg = dataclasses.replace(self.config, replica_id=str(index),
                                  mesh_shape=self._mesh_shape)
        engine = self._factory(cfg, self.tracer, self.metrics,
                               self._device_sets[index], ledger=self.ledger,
                               tier_store=self.tier_store,
                               prefix_index=self.prefix_index)
        if self.signals is not None:
            engine.signals = self.signals
        return engine

    # --------------------------------------------------------------- lifecycle

    async def start(self) -> None:  # lint: runs-on[pool]
        if self._started:
            return
        self._started = True
        self._stopping = False
        for replica in self.replicas:
            if replica.state == "ready":
                await replica.engine.start()
        await self.health.start()

    async def stop(self) -> None:  # lint: runs-on[pool]
        self._stopping = True
        self._started = False
        await self.health.stop()
        for replica in self.replicas:
            try:
                await replica.engine.stop()
            except Exception:
                logger.exception("engine pool: replica %s stop failed",
                                 replica.id)
        # the shared spill store outlives every replica engine (reloads
        # rebuild engines against it); close it only with the pool
        if self.tier_store is not None:
            self.tier_store.close()

    def warmup(self, mode: str | None = None) -> None:
        """Precompile every replica's shape grid (bench/boot path)."""
        for replica in self.replicas:
            replica.engine.warmup(mode)

    # -------------------------------------------------------------- submission

    async def submit(self, request: GenRequest) -> GenRequest:  # lint: runs-on[pool]
        """Route and dispatch one request; same contract as
        TPUEngine.submit (tokens arrive on request.stream, None-terminated,
        finish_reason filled)."""
        await self._dispatch(request, attempts=1)
        return request

    async def generate(self, prompt_ids: list[int],
                       **kwargs) -> AsyncIterator[int]:  # lint: runs-on[pool]
        from ...utils.ids import new_id
        request = GenRequest(request_id=new_id(), prompt_ids=prompt_ids,
                             **kwargs)
        await self.submit(request)
        while True:
            token = await request.stream.get()
            if token is None:
                break
            yield token

    def cancel(self, request_id: str) -> bool:  # lint: runs-on[pool]
        """Cancel a logical request wherever the router placed it. The
        record is keyed by the CLIENT-facing id; the engine is told the
        shadow's id (which carries a ``~rN`` suffix after a requeue), so
        post-failover requests stay cancellable by their original id.
        The engine posts the ``cancelled`` terminal through the normal
        stream path, which the pump forwards to the client."""
        for replica in self.replicas:
            record = replica.outstanding.get(request_id)
            if record is not None:
                return replica.engine.request_cancel(
                    record.shadow.request_id)
        return False

    def _routable(self) -> list[EngineReplica]:
        return [r for r in self.replicas if r.state == "ready"]

    # ------------------------------------------------------------------- roles

    @property
    def roles_active(self) -> bool:
        """True once any replica holds a non-generalist role — the gate
        on classification and migration (a uniform pool routes exactly
        as it did before roles existed)."""
        return any(r.role != "any" for r in self.replicas)

    def set_role(self, rid: str, role: str) -> dict[str, Any]:  # lint: runs-on[pool]
        """Reassign one replica's role live (admin surface / lease
        plane). Routing-only state: nothing needs draining — in-flight
        work finishes where it runs; only FUTURE admissions see the new
        narrowing."""
        replica = self._replica(rid)
        role = str(role).strip().lower()
        if role not in REPLICA_ROLES:
            raise ValueError(f"role must be one of {list(REPLICA_ROLES)}, "
                             f"got {role!r}")
        if replica.role != role:
            logger.info("engine pool: replica %s role %s -> %s",
                        rid, replica.role, role)
            replica.role = role
        return replica.status()

    def _classify(self, request: GenRequest) -> str:
        """The admission's route class. An explicit ``route_class`` on
        the request wins (the fleet-class hook); otherwise prompt length
        splits the phase: long prompts are prefill-heavy, short ones
        (chat turns, continuations) are decode-heavy."""
        if not self.roles_active:
            return ""
        if request.route_class:
            return request.route_class
        return ("prefill"
                if len(request.prompt_ids) >= self.disagg_prompt_tokens
                else "decode")

    def _migration_eligible(self, request: GenRequest, attempts: int,
                            replica: EngineReplica) -> bool:
        """Should this dispatch run as a one-token prefill leg that
        hands off to a decode replica? Only a FIRST dispatch (a requeued
        continuation already carries generated tokens and re-migrating
        it re-pays the hop for no TTFT win), only on an actual prefill
        replica (a spill onto "any" can just decode in place), only
        with the shared tiers to carry the pages, at least one full
        page to carry, more than one token still owed, and somewhere
        decode-capable to land."""
        return (attempts == 1 and not request.generated
                and replica.role == "prefill"
                and self.tier_store is not None
                and request.max_tokens > 1
                and len(request.prompt_ids) >= self.config.page_size
                and any(r is not replica and r.state == "ready"
                        and r.role in ("decode", "any")
                        for r in self.replicas))

    # ---------------------------------------------------------------- dispatch

    async def _dispatch(self, request: GenRequest, attempts: int,
                        pin: EngineReplica | None = None
                        ) -> EngineReplica | None:
        """Pick a replica, submit the shadow, start the pump. Retries
        across replicas when a submit itself fails (racing a crash).
        Returns the replica the request landed on (None = capacity
        exhausted, stream terminated "unavailable"). A non-None ``pin``
        is tried FIRST (the migration path's chosen decode target, or
        its decode-in-place degrade) and never re-classified or
        re-migrated — a pin that refuses falls back to normal routing
        so a dying target can never strand the stream."""
        last_error: Exception | None = None
        route_class = "" if pin is not None else self._classify(request)
        for _ in range(len(self.replicas) + (1 if pin is not None else 0)):
            if pin is not None and pin.state == "ready":
                replica, affinity_hit = pin, False
            else:
                routable = self._routable()
                if not routable:
                    break
                replica, affinity_hit = self.router.route(
                    routable, request.prompt_ids, route_class)
            migrate_leg = (pin is None and route_class == "prefill"
                           and self._migration_eligible(request, attempts,
                                                        replica))
            shadow = self._make_shadow(request, attempts,
                                       cap=1 if migrate_leg else 0)
            record = PoolRecord(request=request, shadow=shadow,
                                replica=replica, attempts=attempts,
                                migrate_leg=migrate_leg)
            try:
                await replica.engine.submit(shadow)
            except RuntimeError as exc:
                # dispatch thread died between the health sweep and now:
                # mark it so the router stops offering it, try the next
                last_error = exc
                self.fail_replica(replica, reason="submit refused: "
                                  f"{exc}")
                if replica is pin:
                    pin = None  # fall back to normal routing
                continue
            if replica.state == "dead":
                # the health sweep failed the replica while submit awaited
                # backpressure and has already swept its outstanding map —
                # registering now would park the record on a corpse no
                # sweep revisits. Abandon the shadow (the dead engine's
                # terminal lands in it unobserved) and route a fresh one.
                last_error = RuntimeError(
                    f"replica {replica.id} died during submit")
                if replica is pin:
                    pin = None
                continue
            replica.routed += 1
            replica.outstanding[request.request_id] = record
            record.pump = asyncio.get_running_loop().create_task(
                self._pump(record), name=f"pool-pump-{request.request_id}")
            m = self.metrics
            if m is not None:
                m.llm_pool_routed.labels(
                    replica=replica.id,
                    affinity="hit" if affinity_hit else "miss").inc()
                m.llm_pool_outstanding.labels(replica=replica.id).set(
                    len(replica.outstanding))
            return replica
        # no replica could take it: this is CAPACITY loss, not a broken
        # request — terminate with the "unavailable" reason the serving
        # surface maps to a clean 503 + Retry-After (backpressure-header
        # contract, docs/resilience.md) instead of a bare error
        logger.error("engine pool: no routable replica for %s (%s)",
                     request.request_id, last_error,
                     extra=trace_extra(request.trace_ctx))
        if request.finish_reason is None:
            request.finish_reason = "unavailable"
        request.stream.put_nowait(None)
        return None

    def _make_shadow(self, request: GenRequest, attempts: int,
                     cap: int = 0) -> GenRequest:
        """The engine-facing request. On a requeue the prompt is the
        CONTINUATION — original prompt plus every token already delivered
        — so the survivor resumes where the failed replica stopped and
        nothing is emitted twice; ``queue_observed`` rides the engine's
        once-only guard so the logical request's queue phase is observed
        exactly once across attempts. A non-zero ``cap`` bounds the
        shadow's budget below the logical request's remainder: the
        migration prefill leg runs with cap=1 (prefill + first token,
        then hand off)."""
        suffix = "" if attempts == 1 else f"~r{attempts - 1}"
        budget = max(1, request.max_tokens - len(request.generated))
        if cap:
            budget = min(budget, cap)
        return GenRequest(
            request_id=f"{request.request_id}{suffix}",
            prompt_ids=list(request.prompt_ids) + list(request.generated),
            max_tokens=budget,
            temperature=request.temperature,
            top_k=request.top_k,
            top_p=request.top_p,
            stop_ids=request.stop_ids,
            priority=request.priority,
            created=request.created,
            # billing identity must ride EVERY shadow, including requeued
            # continuations — a failover must not turn a tenant's tail
            # tokens into unattributed work (token-conservation gate)
            tenant=request.tenant,
            trace_ctx=request.trace_ctx,
            queue_observed=attempts > 1,
            # once-only TTFT/llm.prefill: if the failed attempt already
            # delivered a first token, the logical request's TTFT has
            # been observed — the continuation must not observe a second
            # sample spanning the failed attempt + failover
            ttft_observed=len(request.generated) > 0,
        )

    async def _pump(self, record: PoolRecord) -> None:
        """Forward the shadow's tokens to the client stream; on the
        terminal, either finish the client or hand the record to the
        failover path. Cancelled (without side effects) when the health
        monitor takes over a failed replica's records."""
        shadow = record.shadow
        request = record.request
        while True:
            token = await shadow.stream.get()
            if token is None:
                break
            request.generated.append(token)
            request.stream.put_nowait(token)
        await self._on_shadow_done(record)

    async def _on_shadow_done(self, record: PoolRecord) -> None:
        replica = record.replica
        request = record.request
        replica.outstanding.pop(request.request_id, None)
        if self.metrics is not None:
            self.metrics.llm_pool_outstanding.labels(
                replica=replica.id).set(len(replica.outstanding))
        reason = record.shadow.finish_reason or "stop"
        if reason == "error" and not self._stopping:
            # the engine only posts "error" terminals from its crash /
            # fail-outstanding paths — treat it as replica evidence, then
            # try to finish the request elsewhere
            if not record.replica.engine.dispatch_alive():
                self.fail_replica(replica,
                                  reason="stream error + dead dispatch")
            await self._requeue(record)
            return
        if (record.migrate_leg and reason == "length"
                and not self._stopping
                and request.finish_reason is None
                and len(request.generated) < request.max_tokens):
            # the one-token prefill leg retired its cap, not the
            # request's budget: hand the KV chain to a decode replica.
            # (A "stop" terminal here means the first token really
            # finished the request — it falls through as a normal
            # terminal, nothing to migrate.)
            await self._migrate(record)
            return
        record.done = True
        if request.finish_reason is None:
            request.finish_reason = reason
        request.stream.put_nowait(None)

    # --------------------------------------------------------------- migration

    async def _migrate(self, record: PoolRecord) -> None:
        """The prefill->decode hop (docs/disaggregation.md): export the
        prompt's KV chain through the pool-shared spill tiers at the
        source engine's drain barrier, verify every page against its
        token content (the same verify-before-serve gate admission
        restores use — a corrupt payload degrades to a MISS, never a
        wrong page), then continue the request on a decode replica as a
        pool-shadow continuation. ANY failed step decodes in place on
        the prefill replica instead; the stream never dies to a
        migration. Conservation: every spilled page is counted restored
        (hop landed on the target) or degraded (anything else) —
        spilled == restored + degraded, pinned in tests."""
        from ...observability.faults import fault_point
        from ..kv.prefix_index import chain_pages
        request = record.request
        src = record.replica
        started = time.time()
        page_size = self.config.page_size
        expected = len(request.prompt_ids) // page_size
        spilled = 0
        moved_bytes = 0
        corrupt = False
        target: EngineReplica | None = None
        failure = ""
        try:
            # fault point pool.migrate (docs/resilience.md): error fails
            # the hop (degrade to decode-in-place), latency stretches it
            # (the slow-migration chaos arm), corrupt mangles the chain
            # identity below so verify-before-serve rejects the payload.
            act = fault_point("pool.migrate", scope=request.request_id)
            if act is not None:
                if act.kind == "corrupt":
                    corrupt = True
                else:
                    await act.async_apply()
            # 1) export: the source engine copies the prompt chain's
            # resident pages into the shared store at its dispatch-loop
            # drain barrier (quiesced device state, same seam reload's
            # spill-on-drain uses). COPY, not move — on any later
            # failure the pages are still resident for decode-in-place.
            spilled = await asyncio.wait_for(
                asyncio.wrap_future(
                    src.engine.request_chain_export(request.prompt_ids)),
                timeout=30.0)
            if spilled < expected:
                raise RuntimeError(
                    f"chain export covered {spilled}/{expected} pages")
            # 2) verify-before-serve, pool-side: walk the exported chain
            # through the store's payload gate with the token content we
            # KNOW the decode replica will request. An injected corrupt
            # mangles the first page's expected chunk, so the store's
            # comparison fails exactly as a real collision would — the
            # entry is dropped and the migration degrades.
            steps = chain_pages(request.prompt_ids, page_size)
            if corrupt and steps:
                key_hash, parent, chunk = steps[0]
                steps[0] = (key_hash, parent, (chunk[0] + 1,) + chunk[1:])
            verified, moved_bytes = self.tier_store.verify_chain(steps)
            if verified < expected:
                raise RuntimeError(
                    f"verify-before-serve passed {verified}/{expected} "
                    f"pages")
            # 3) pick the decode target: role-aware routing over the
            # decode-capable survivors (never the source), scored on the
            # continuation prompt so tier affinity counts.
            candidates = [r for r in self._routable()
                          if r is not src and r.role in ("decode", "any")]
            if not candidates:
                raise RuntimeError("no decode-capable target replica")
            target, _ = self.router.route(
                candidates,
                list(request.prompt_ids) + list(request.generated),
                route_class="decode")
        except Exception as exc:  # FaultError included: degrade, never die
            failure = str(exc)
            target = None
        if target is None:
            logger.warning(
                "engine pool: migration of %s degrading to "
                "decode-in-place on replica %s (%s)", request.request_id,
                src.id, failure or "no target",
                extra=trace_extra(request.trace_ctx))
        # 4) continue as a pool-shadow continuation (the requeue
        # contract: prompt + generated, once-only TTFT/queue guards) —
        # pinned to the chosen target, or to the source for the
        # decode-in-place degrade. A pin that refuses falls back to
        # normal routing inside _dispatch; a lost stream is impossible
        # short of total pool capacity loss ("unavailable" terminal).
        landed = await self._dispatch(request, attempts=record.attempts + 1,
                                      pin=target if target is not None
                                      else src)
        outcome = ("ok" if target is not None and landed is target
                   else "degraded")
        self.migrations[outcome] += 1
        self.migration_pages["spilled"] += spilled
        self.migration_pages[
            "restored" if outcome == "ok" else "degraded"] += spilled
        self.migration_bytes += moved_bytes
        if outcome == "ok":
            src.migrations_out += 1
            landed.migrations_in += 1
        to_id = landed.id if landed is not None else src.id
        m = self.metrics
        if m is not None:
            m.llm_pool_migrations.labels(src.id, to_id, outcome).inc()
            m.llm_pool_migration_seconds.observe(time.time() - started)
            if spilled:
                m.llm_pool_migration_pages.labels("spilled").inc(spilled)
                m.llm_pool_migration_pages.labels(
                    "restored" if outcome == "ok" else "degraded"
                ).inc(spilled)
            if moved_bytes:
                m.llm_pool_migration_bytes.inc(moved_bytes)
        if self.tracer is not None and request.trace_ctx is not None:
            # the hop as a span: joins the prefill replica's llm.* spans
            # to the decode replica's in ONE trace (span-stitch contract)
            try:
                attrs = {"llm.from_replica": src.id,
                         "llm.to_replica": to_id,
                         "llm.pages": spilled,
                         "llm.outcome": outcome}
                if failure:
                    attrs["llm.failure"] = failure[:200]
                if request.tenant:
                    attrs["llm.tenant"] = request.tenant
                self.tracer.emit_span("pool.migrate", started, time.time(),
                                      trace_ctx=request.trace_ctx,
                                      attributes=attrs)
            except Exception:
                pass  # telemetry must never break the hop

    # ---------------------------------------------------------------- failover

    def fail_replica(self, replica: EngineReplica,
                     reason: str = "") -> None:  # lint: runs-on[pool]
        """Take a replica out of rotation and requeue its in-flight
        requests. Idempotent; called by the health monitor (wedge/crash
        sweep) and the submit/pump paths (stream evidence)."""
        if replica.state == "dead":
            return
        replica.state = "dead"
        replica.failures += 1
        replica.last_failure = reason or "failed"
        logger.error("engine pool: replica %s marked dead (%s)",
                     replica.id, replica.last_failure)
        if self.metrics is not None:
            self.metrics.llm_pool_replica_up.labels(replica=replica.id).set(0)
        # signal, never join: a wedged dispatch thread must not delay the
        # requeue, and a zombie that later revives exits at its next loop
        # check (its late emissions land in abandoned shadow streams)
        replica.engine.kill()
        client = getattr(replica.engine, "_tier_client", None)
        if client is not None:
            # the dead engine's HBM pages are unreachable — forget its
            # prefix-index entries so affinity scoring can't chase
            # ghosts (pages already SPILLED are content-addressed in the
            # shared store and keep serving every survivor)
            client.drop_replica()
        survivors = self._take_over_records(replica)
        if survivors:
            asyncio.get_running_loop().create_task(
                self._requeue_batch(survivors),
                name=f"pool-requeue-{replica.id}")

    def _take_over_records(self, replica: EngineReplica
                           ) -> list[PoolRecord]:  # lint: runs-on[pool]
        """Detach a replica's in-flight records from it: cancel the pumps,
        forward whatever each shadow stream already holds (tokens the
        consumer must not lose OR see twice), deliver any terminal that
        raced the takeover, and return the records that still need a
        home. Used by the failover sweep and by reload when a drain
        times out with work still in flight."""
        records = list(replica.outstanding.values())
        replica.outstanding.clear()
        if self.metrics is not None:
            self.metrics.llm_pool_outstanding.labels(
                replica=replica.id).set(0)
        survivors: list[PoolRecord] = []
        for record in records:
            if record.pump is not None:
                record.pump.cancel()
            finished = self._drain_shadow(record)
            if finished and (record.shadow.finish_reason or "stop") \
                    != "error":
                # the shadow actually completed (terminal raced the
                # takeover): deliver it, nothing to requeue
                record.done = True
                if record.request.finish_reason is None:
                    record.request.finish_reason = \
                        record.shadow.finish_reason or "stop"
                record.request.stream.put_nowait(None)
                continue
            survivors.append(record)
        return survivors

    def _drain_shadow(self, record: PoolRecord) -> bool:
        """Forward whatever the failed replica already emitted into the
        shadow stream (tokens the consumer must not lose OR see twice),
        returning True if the terminal None was present."""
        while True:
            try:
                token = record.shadow.stream.get_nowait()
            except asyncio.QueueEmpty:
                return False
            if token is None:
                return True
            record.request.generated.append(token)
            record.request.stream.put_nowait(token)

    async def _requeue_batch(self, records: list[PoolRecord]) -> None:
        for record in records:
            await self._requeue(record)

    async def _requeue(self, record: PoolRecord) -> None:
        from ...observability.faults import FaultError, fault_point
        request = record.request
        if record.done or request.finish_reason is not None:
            return
        old = record.replica
        if len(request.generated) >= request.max_tokens:
            # the failed replica had already emitted the full budget
            record.done = True
            request.finish_reason = "length"
            request.stream.put_nowait(None)
            return
        if (self._stopping or record.attempts - 1 >= self.requeue_max
                or not self._routable()):
            # requeue budget spent / nowhere to go: the stream ends with
            # "unavailable" — the provider raises LLMUnavailable and the
            # HTTP surface answers 503 + Retry-After (clean terminal,
            # never a bare mid-stream error; pinned in the pool tests)
            record.done = True
            request.finish_reason = "unavailable"
            request.stream.put_nowait(None)
            return
        # fault point pool.requeue (docs/resilience.md): an injected
        # error fails THIS failover hop the same way a spent budget
        # does; latency delays the continuation (the chaos matrix's
        # slow-failover arm). Unarmed: one dict miss.
        act = fault_point("pool.requeue", scope=request.request_id)
        if act is not None:
            try:
                await act.async_apply()
            except FaultError:
                record.done = True
                request.finish_reason = "unavailable"
                request.stream.put_nowait(None)
                return
        self.requeues += 1
        # counted here — not in fail_replica — so the status card's
        # requeued_off and mcpforge_llm_pool_requeues_total agree no
        # matter which path (health sweep or pump error terminal)
        # triggered the requeue
        old.requeued_off += 1
        if self.metrics is not None:
            self.metrics.llm_pool_requeues.labels(replica=old.id).inc()
        # trace correlation: the failover line joins to the request's
        # OTel trace in the JSON/ring logs (observability/logging.py)
        logger.warning("engine pool: requeueing %s off replica %s "
                       "(%d tokens already delivered)", request.request_id,
                       old.id, len(request.generated),
                       extra=trace_extra(request.trace_ctx))
        requeued_at = time.time()
        await self._dispatch(request, attempts=record.attempts + 1)
        if self.tracer is not None and request.trace_ctx is not None:
            # the failover hop as a span: joins the killed replica's
            # llm.* spans to the successor's in ONE trace, tenant
            # intact — the forensics waterfall renders the hop instead
            # of two disconnected half-requests
            try:
                attrs = {"llm.from_replica": old.id,
                         "llm.attempt": record.attempts + 1,
                         "llm.tokens_delivered": len(request.generated)}
                if request.tenant:
                    attrs["llm.tenant"] = request.tenant
                self.tracer.emit_span("pool.requeue", requeued_at,
                                      time.time(),
                                      trace_ctx=request.trace_ctx,
                                      attributes=attrs)
            except Exception:
                pass  # telemetry must never break failover

    # ------------------------------------------------------------ drain/reload

    def _replica(self, rid: str) -> EngineReplica:
        for replica in self.replicas:
            if replica.id == rid:
                return replica
        raise KeyError(f"no replica {rid!r} "
                       f"(have {[r.id for r in self.replicas]})")

    async def drain(self, rid: str,  # lint: runs-on[pool]
                    timeout_s: float = 60.0) -> dict[str, Any]:
        """Stop routing new work to the replica and wait for its in-flight
        requests to finish on it. Idempotent; ``undrain`` reverses."""
        replica = self._replica(rid)
        if replica.state == "ready":
            replica.state = "draining"
            if self.metrics is not None:
                self.metrics.llm_pool_replica_up.labels(
                    replica=replica.id).set(0)
        deadline = time.monotonic() + max(0.0, timeout_s)
        while replica.outstanding and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        status = replica.status()
        status["drained"] = not replica.outstanding
        return status

    async def undrain(self, rid: str) -> dict[str, Any]:  # lint: runs-on[pool]
        """Readmit a drained (or draining) replica to the router."""
        replica = self._replica(rid)
        if replica.state != "draining":
            raise ValueError(
                f"replica {rid} is {replica.state}, not draining")
        replica.state = "ready"
        if self.metrics is not None:
            self.metrics.llm_pool_replica_up.labels(replica=replica.id).set(1)
        return replica.status()

    async def reload(self, rid: str,  # lint: runs-on[pool]
                     timeout_s: float = 60.0) -> dict[str, Any]:
        """Rolling weight hot-swap: drain -> rebuild the engine (fresh
        checkpoint read from ``config.checkpoint``) -> readmit. The rest
        of the pool serves throughout; a dead replica can be reloaded
        too (that IS its recovery path)."""
        replica = self._replica(rid)
        if replica.state == "reloading":
            raise ValueError(f"replica {rid} is already reloading")
        was_dead = replica.state == "dead"
        if not was_dead:
            await self.drain(rid, timeout_s=timeout_s)
            if replica.outstanding:
                # the drain timed out with generations still running.
                # engine.stop() would terminate them with
                # finish_reason="cancelled" — a truncated stream for the
                # client — while the rest of the pool could finish them
                # exactly as the wedge/crash path does: hand the
                # stragglers to the survivors as continuations. (The
                # replica is already off the router: "draining".)
                stragglers = self._take_over_records(replica)
                if stragglers:
                    logger.warning(
                        "engine pool: reload of replica %s requeueing %d "
                        "request(s) the drain window did not cover",
                        rid, len(stragglers))
                    await self._requeue_batch(stragglers)
        replica.state = "reloading"
        try:
            await replica.engine.stop()
        except Exception:
            logger.exception("engine pool: replica %s stop during reload "
                             "failed (continuing with rebuild)", rid)
        # a kill()ed engine was never joined (stop() returns immediately
        # once _started is false) and its zombie thread pins the old
        # params + KV pool on the replica's devices; give it a bounded
        # window to exit before committing a second footprint to the
        # same HBM (docs/serving_pool.md, reload section)
        thread = getattr(replica.engine, "_thread", None)
        if thread is not None and thread.is_alive():
            await asyncio.to_thread(thread.join, min(max(timeout_s, 0.0), 30.0))
            if thread.is_alive():
                logger.warning(
                    "engine pool: replica %s dispatch thread is still "
                    "wedged; rebuilding anyway — device memory may be "
                    "double-committed until it exits", rid)
        # spill-on-drain (docs/resilience.md): with the dispatch thread
        # quiesced and the old engine's device state still intact, copy
        # its ref==0 resident prefix pages into the pool-shared spill
        # store — the rebuilt engine (and every sibling) then restores
        # the prefix corpus by fetch-on-miss instead of losing it with
        # the torn-down HBM pool. A dead/wedged engine is skipped: its
        # device state is suspect and must not poison the shared tiers.
        thread_quiesced = thread is None or not thread.is_alive()
        if not was_dead and thread_quiesced \
                and self.tier_store is not None:
            try:
                spilled = await asyncio.to_thread(
                    replica.engine.spill_prefix_pages)
                if spilled:
                    logger.info("engine pool: reload of replica %s "
                                "spilled %d resident prefix page(s)",
                                rid, spilled)
            except Exception:
                logger.exception("engine pool: spill-on-drain failed for "
                                 "replica %s (continuing with rebuild)",
                                 rid)
        try:
            # engine construction compiles + loads weights: off the loop
            engine = await asyncio.to_thread(self._build_engine,
                                             replica.index)
        except Exception:
            replica.state = "dead"
            if self.metrics is not None:
                self.metrics.llm_pool_replica_up.labels(
                    replica=replica.id).set(0)
            raise
        replica.engine = engine
        if self._started:
            await engine.start()
        replica.state = "ready"
        replica.reloads += 1
        if self.metrics is not None:
            self.metrics.llm_pool_reloads.labels(replica=replica.id).inc()
            self.metrics.llm_pool_replica_up.labels(replica=replica.id).set(1)
        logger.info("engine pool: replica %s reloaded%s", rid,
                    " (was dead)" if was_dead else "")
        return replica.status()

    # ------------------------------------------------------------- aggregation

    @property
    def stats(self) -> EngineStats:
        """Aggregated scheduler counters across replicas (the facade the
        bench and stats surfaces read; recomputed per access)."""
        total = EngineStats()
        for replica in self.replicas:
            stats = replica.engine.stats
            for name, value in vars(stats).items():
                setattr(total, name, getattr(total, name) + value)
        return total

    def kv_pages_in_use(self) -> int:
        return sum(r.engine.allocator.pages_in_use for r in self.replicas)

    def kv_bytes_in_use(self) -> int:
        return sum(r.engine.kv_bytes_in_use() for r in self.replicas)

    def device_idle_fraction(self) -> float:
        fracs = [r.engine.device_idle_fraction() for r in self.replicas]
        return sum(fracs) / len(fracs) if fracs else 0.0

    def status(self) -> dict[str, Any]:
        """The /admin/engine/pool payload: per-replica health, occupancy,
        and routing/failover counters."""
        return {
            "replicas": [r.status() for r in self.replicas],
            "router": {**self.router.counters(),
                       "affinity_routing": self.router.affinity_routing},
            "prefix_tiers": {
                "enabled": self.tier_store is not None,
                "store": (self.tier_store.stats()
                          if self.tier_store is not None else None),
                "index": (self.prefix_index.stats()
                          if self.prefix_index is not None else None),
            },
            "roles": {
                "active": self.roles_active,
                "assignment": {r.id: r.role for r in self.replicas},
                "disagg_prompt_tokens": self.disagg_prompt_tokens,
            },
            "migrations": {
                **self.migrations,
                "pages": dict(self.migration_pages),
                "bytes": self.migration_bytes,
            },
            "requeues": self.requeues,
            "requeue_max": self.requeue_max,
            "health": {
                "sweeps": self.health.sweeps,
                "failures": self.health.failures,
                "interval_s": self.health.interval_s,
                "heartbeat_timeout_s": self.health.heartbeat_timeout_s,
            },
        }

    def _set_up_gauges(self) -> None:
        if self.metrics is None:
            return
        for replica in self.replicas:
            self.metrics.llm_pool_replica_up.labels(replica=replica.id).set(1)
            self.metrics.llm_pool_outstanding.labels(replica=replica.id).set(0)
