"""Closed-loop serving controller: the signal plane starts steering.

ROADMAP item 1's second half. The stack measures everything — live
MFU/roofline, flight-recorder phase vectors, tenant SLO burn, queue-wait
and TTFT percentiles — but until now every serving knob (superstep K,
batch-bucket widths, spec decode, shed bars) was frozen config. This
module consumes the live :class:`~..observability.signals.SignalBus` and
retunes four knobs inside hard safety rails:

- **Adaptive superstep K** (per replica): queue-wait p95 past
  ``queue_wait_high_ms`` steps K DOWN one warmed ladder rung (drain
  barriers come closer together, admission latency falls); queue-wait
  under ``queue_wait_low_ms`` with device idle fraction past
  ``idle_frac_high`` steps K UP (host-dispatch-bound — fuse more).
  Moves land ONLY at engine drain barriers on pre-warmed executables
  (:meth:`TPUEngine.request_knobs` rejects unwarmed rungs), so greedy
  parity holds and a knob move can never compile mid-traffic.
- **Batch-width floor** (per replica): the live occupancy histogram's
  p95 picks the smallest warmed bucket the engine may shrink to —
  shrink/re-grow churn (each re-homes the donated KV pool) stops when
  load says the burst will return.
- **Spec decode on/off** (per replica): measured acceptance (extra
  tokens per row per verify dispatch) below ``spec_accept_off`` turns
  drafting off; a stale acceptance signal after ``reprobe_after_s``
  turns it back on to re-measure (acceptance is unobservable while off).
- **Dynamic shed bars** (gateway scope): SLO burn rate past
  ``burn_high`` tightens ``OverloadShedder.shed_at`` toward
  ``shed_floor``; burn under ``burn_low`` relaxes it back toward the
  static configured bar. A vacuous burn (empty first window, or the
  target sits above the histogram's top finite bucket) HOLDS — the
  controller never acts on a number the evaluator labeled unmeasurable.

Anti-flap machinery: per-(replica, knob) cooldown; direction-reversal
hysteresis (reversing the previous move requires the trigger to clear
its threshold by an extra ``hysteresis`` margin); staleness guards (a
dead replica's last breath is not a signal).

Every decision is an observable event (docs/controller.md "Audit
ring"): a bounded ring row carrying the triggering signal snapshot and
— after ``eval_window_s`` — the observed effect; a
``mcpforge_controller_decisions_total{knob,direction}`` count; the
``mcpforge_controller_knob{knob,replica}`` posture gauges; and a
parentless ``controller.decision`` span stitched into the trace store.
``safe_mode`` records every decision it WOULD have made without
actuating; ``controller_enabled=false`` never constructs this object
at all — frozen-config behavior stays bit-identical.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, Callable

from ..observability.signals import GATEWAY_REPLICA, SignalBus

logger = logging.getLogger(__name__)

# ring row schema version (admin surface consumers pin on this)
RING_SCHEMA = 1


class ServingController:
    """Feedback controller over the live signal bus.

    ``engines_fn`` returns the CURRENT list of engine-like objects
    (``.config.replica_id``, ``.request_knobs()``, ``.knob_state()``) —
    a callable so pool reloads/scale-outs are picked up per tick.
    ``tick()`` is synchronous and deterministic given the bus contents
    (tests drive it directly with an injected clock); ``start()`` runs
    it on the gateway loop every ``tick_s``.
    """

    def __init__(self, bus: SignalBus,
                 engines_fn: Callable[[], list[Any]],
                 shedder: Any = None,
                 slo_evaluator: Any = None,
                 metrics: Any = None,
                 tracer: Any = None,
                 *,
                 enabled: bool = True,
                 safe_mode: bool = False,
                 tick_s: float = 1.0,
                 cooldown_s: float = 10.0,
                 eval_window_s: float = 5.0,
                 hysteresis: float = 0.1,
                 ring_size: int = 256,
                 queue_wait_high_ms: float = 500.0,
                 queue_wait_low_ms: float = 50.0,
                 idle_frac_high: float = 0.35,
                 spec_accept_off: float = 0.5,
                 spec_accept_on: float = 1.0,
                 burn_high: float = 1.0,
                 burn_low: float = 0.25,
                 shed_floor: float = 0.5,
                 shed_step: float = 0.05,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.bus = bus
        self.engines_fn = engines_fn
        self.shedder = shedder
        self.slo = slo_evaluator
        self.metrics = metrics
        self.tracer = tracer
        self.enabled = enabled
        self.safe_mode = bool(safe_mode)
        self.tick_s = max(0.05, float(tick_s))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.eval_window_s = max(self.tick_s, float(eval_window_s))
        self.hysteresis = max(0.0, float(hysteresis))
        self.queue_wait_high_ms = float(queue_wait_high_ms)
        self.queue_wait_low_ms = float(queue_wait_low_ms)
        self.idle_frac_high = float(idle_frac_high)
        self.spec_accept_off = float(spec_accept_off)
        self.spec_accept_on = float(spec_accept_on)
        self.burn_high = float(burn_high)
        self.burn_low = float(burn_low)
        self.shed_floor = min(max(float(shed_floor), 0.0), 1.0)
        self.shed_step = max(0.001, float(shed_step))
        self._clock = clock
        # signals older than this are dead — hold, don't steer on them
        self.stale_after_s = max(3.0 * self.tick_s, self.eval_window_s)
        # spec re-probe: acceptance is unobservable while drafting is
        # off, so a long-stale acceptance signal re-enables to measure
        self.reprobe_after_s = max(3.0 * self.cooldown_s, 30.0)
        # the static shed bar is the RELAXED ceiling the dynamic bar
        # returns to (captured at construction, before we ever move it)
        self._shed_ceiling = (min(max(float(shedder.shed_at), 0.0), 1.0)
                             if shedder is not None else 1.0)
        # audit ring: bounded, newest at the right
        self._ring: deque[dict[str, Any]] = deque(maxlen=max(8, ring_size))
        self._seq = 0
        # decisions awaiting their post-window effect capture
        self._pending_effects: list[dict[str, Any]] = []
        # per-(replica, knob) anti-flap state
        self._last_move_ts: dict[tuple[str, str], float] = {}
        self._last_direction: dict[tuple[str, str], str] = {}
        self._ticks = 0
        self._held = 0  # ticks where at least one knob held position
        self._task: asyncio.Task | None = None

    # ----------------------------------------------------------- lifecycle

    async def start(self) -> None:
        if self._task is not None or not self.enabled:
            return
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name="serving-controller")

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.tick_s)
            try:
                self.tick()
            except Exception:
                # the control loop must never take the gateway down; a
                # broken tick holds every knob where it is
                logger.exception("serving controller tick failed")

    # ---------------------------------------------------------------- tick

    def tick(self) -> list[dict[str, Any]]:
        """One control iteration: publish SLO burn onto the bus, settle
        due effect captures, then evaluate every knob ladder. Returns
        the decision rows emitted this tick (tests assert on them)."""
        now = self._clock()
        self._ticks += 1
        self._publish_burn()
        self._settle_effects(now)
        decisions: list[dict[str, Any]] = []
        for engine in self.engines_fn() or []:
            try:
                decisions.extend(self._tick_engine(engine, now))
            except Exception:
                logger.exception("controller: engine tick failed")
        decisions.extend(self._tick_shed(now))
        self._refresh_knob_gauges()
        return decisions

    # ------------------------------------------------------- signal inputs

    def _view(self, name: str, replica: str) -> dict[str, Any] | None:
        """Fresh aggregate view or None (absent/stale = hold)."""
        view = self.bus.get(name, replica)
        if view is None or view["age_s"] > self.stale_after_s:
            return None
        return view

    def _publish_burn(self) -> None:
        """Evaluate SLOs under the controller's own consumer window and
        push burn onto the bus — overall, plus one slice per tenant
        class (bounded by the class table). A vacuous burn (empty first
        window with no lifetime data, or every objective's target above
        the histogram buckets) publishes NOTHING: downstream ladders
        then hold by the staleness/absence guard, which is exactly the
        required behavior for a controller facing an unmeasurable SLO."""
        if self.slo is None:
            return
        try:
            report = self.slo.evaluate(consumer="controller")
        except Exception:
            logger.exception("controller: SLO evaluation failed")
            return
        burn = self._burn_from(report)
        if burn is not None:
            self.bus.publish("slo.burn_rate", burn, GATEWAY_REPLICA)
        classes = getattr(self.slo, "tenant_classes", None) or {}
        by_class: dict[str, str] = {}
        for tenant in sorted(classes):
            by_class.setdefault(classes[tenant], tenant)
        for slo_class, tenant in sorted(by_class.items()):
            try:
                sliced = self.slo.evaluate(consumer="controller",
                                           tenant=tenant)
            except Exception:
                continue
            class_burn = self._burn_from(sliced)
            if class_burn is not None:
                self.bus.publish(f"slo.burn_rate.{slo_class}", class_burn,  # lint: allow[signal-name-conformance] per-class burn family for /signals dashboards; the controller steers on the aggregate slo.burn_rate
                                 GATEWAY_REPLICA)

    @staticmethod
    def _burn_from(report: dict[str, Any]) -> float | None:
        """Worst actionable burn rate in an evaluator report, or None
        when every objective is vacuous: no samples at all (first-window
        empty AND no lifetime fallback data), or the target sits above
        the top finite bucket (fraction-over is optimistic fiction)."""
        worst = None
        for obj in report.get("objectives", []):
            if obj.get("target_above_buckets"):
                continue
            if not obj.get("window_samples") and not obj.get("total_samples"):
                continue
            rate = obj.get("burn_rate")
            if rate is None:
                continue
            worst = rate if worst is None else max(worst, rate)
        return worst

    # ---------------------------------------------------------- knob logic

    def _tick_engine(self, engine: Any, now: float) -> list[dict[str, Any]]:
        rid = engine.config.replica_id
        state = engine.knob_state()
        out: list[dict[str, Any]] = []
        move = self._decide_superstep(rid, state, now)
        if move is not None:
            out.append(self._actuate(engine, rid, "superstep", move, now))
        move = self._decide_width_floor(rid, state, now)
        if move is not None:
            out.append(self._actuate(engine, rid, "width_floor", move, now))
        move = self._decide_spec(rid, state, now)
        if move is not None:
            out.append(self._actuate(engine, rid, "spec", move, now))
        return out

    def _cooldown_ok(self, rid: str, knob: str, now: float) -> bool:
        last = self._last_move_ts.get((rid, knob))
        return last is None or (now - last) >= self.cooldown_s

    def _reversal_margin(self, rid: str, knob: str, direction: str) -> float:
        """Multiplier a trigger must clear when the proposed move
        REVERSES the previous one (the anti-flap hysteresis): 1.0 for a
        same-direction or first move, 1 + hysteresis for a reversal."""
        prev = self._last_direction.get((rid, knob))
        if prev is not None and prev != direction:
            return 1.0 + self.hysteresis
        return 1.0

    def _decide_superstep(self, rid: str, state: dict[str, Any],
                          now: float) -> dict[str, Any] | None:
        ladder = [k for k in state.get("warmed_k", []) if k >= 1]
        if len(ladder) < 2 or not self._cooldown_ok(rid, "superstep", now):
            return None
        current = state["superstep"]
        if current not in ladder:
            return None
        idx = ladder.index(current)
        qw = self._view("llm.queue_wait_ms", rid)
        idle = self._view("llm.idle_frac", rid)
        # DOWN: admission waits too long between drain barriers
        if qw is not None and idx > 0:
            margin = self._reversal_margin(rid, "superstep", "down")
            if qw["p95"] > self.queue_wait_high_ms * margin:
                return {"direction": "down", "from": current,
                        "to": ladder[idx - 1],
                        "why": {"llm.queue_wait_ms.p95": qw["p95"],
                                "threshold": self.queue_wait_high_ms
                                * margin}}
        # UP: queue calm and the device is host-dispatch-bound
        if idle is not None and idx < len(ladder) - 1:
            calm = qw is None or qw["p95"] < self.queue_wait_low_ms
            margin = self._reversal_margin(rid, "superstep", "up")
            if calm and idle["ewma"] > self.idle_frac_high * margin:
                return {"direction": "up", "from": current,
                        "to": ladder[idx + 1],
                        "why": {"llm.idle_frac.ewma": idle["ewma"],
                                "llm.queue_wait_ms.p95":
                                    qw["p95"] if qw else None,
                                "threshold": self.idle_frac_high * margin}}
        return None

    def _decide_width_floor(self, rid: str, state: dict[str, Any],
                            now: float) -> dict[str, Any] | None:
        widths = sorted(state.get("warmed_widths", []))
        # a single warmed width means fixed-width serving (batch
        # bucketing off): there is no floor ladder to manage, and asking
        # anyway would fill the audit ring with one hold_rejected per
        # tick (a refusal deliberately does not burn the cooldown)
        if len(widths) < 2 or not self._cooldown_ok(rid, "width_floor", now):
            return None
        occ = self._view("llm.occupancy", rid)
        if occ is None:
            return None
        current = state.get("width_floor", 0)
        max_width = widths[-1]
        # the p95 of live occupancy says where bursts keep landing; a
        # floor below that just buys shrink/re-grow pool re-homes
        need = occ["p95"] * max_width
        target = 0
        if occ["p95"] >= 0.25:
            for w in widths:
                if w >= need:
                    target = w
                    break
            else:
                target = max_width
        if target == current:
            return None
        direction = "up" if target > current else "down"
        if self._reversal_margin(rid, "width_floor", direction) > 1.0 \
                and abs(target - current) <= 0:
            return None
        return {"direction": direction, "from": current, "to": target,
                "why": {"llm.occupancy.p95": occ["p95"],
                        "max_width": max_width}}

    def _decide_spec(self, rid: str, state: dict[str, Any],
                     now: float) -> dict[str, Any] | None:
        if not state.get("spec_built"):
            return None
        if not self._cooldown_ok(rid, "spec", now):
            return None
        enabled = state.get("spec_enabled", False)
        accept = self.bus.get("llm.spec_accept", rid)
        if enabled:
            if accept is None or accept["age_s"] > self.stale_after_s:
                return None  # no evidence yet — keep measuring
            margin = self._reversal_margin(rid, "spec", "off")
            if accept["ewma"] < self.spec_accept_off / margin:
                return {"direction": "off", "from": 1, "to": 0,
                        "why": {"llm.spec_accept.ewma": accept["ewma"],
                                "threshold": self.spec_accept_off / margin}}
            return None
        # off: acceptance can't be observed — re-probe once the last
        # measurement has gone stale enough
        if accept is None or accept["age_s"] >= self.reprobe_after_s \
                or accept["ewma"] >= self.spec_accept_on:
            return {"direction": "on", "from": 0, "to": 1,
                    "why": {"llm.spec_accept.age_s":
                                accept["age_s"] if accept else None,
                            "reprobe_after_s": self.reprobe_after_s}}
        return None

    def _tick_shed(self, now: float) -> list[dict[str, Any]]:
        shedder = self.shedder
        if shedder is None or not getattr(shedder, "enabled", False):
            return []
        if not self._cooldown_ok(GATEWAY_REPLICA, "shed_bar", now):
            return []
        burn = self._view("slo.burn_rate", GATEWAY_REPLICA)
        if burn is None:
            return []  # vacuous/stale burn: hold position (satellite 3)
        current = float(shedder.shed_at)
        target = current
        if burn["ewma"] > self.burn_high * self._reversal_margin(
                GATEWAY_REPLICA, "shed_bar", "down"):
            target = max(self.shed_floor, current - self.shed_step)
        elif burn["ewma"] < self.burn_low / self._reversal_margin(
                GATEWAY_REPLICA, "shed_bar", "up"):
            target = min(self._shed_ceiling, current + self.shed_step)
        if abs(target - current) < 1e-9:
            return []
        move = {"direction": "down" if target < current else "up",
                "from": round(current, 4), "to": round(target, 4),
                "why": {"slo.burn_rate.ewma": burn["ewma"],
                        "burn_high": self.burn_high,
                        "burn_low": self.burn_low}}
        row = self._record(GATEWAY_REPLICA, "shed_bar", move, now,
                           accepted=True)
        if not self.safe_mode:
            shedder.shed_at = target
        return [row]

    # ----------------------------------------------------------- actuation

    def _actuate(self, engine: Any, rid: str, knob: str,
                 move: dict[str, Any], now: float) -> dict[str, Any]:
        """Apply one engine-knob move (unless safe_mode) and record it.
        The engine validates against its warmed grid; a refusal is
        recorded as direction=hold_rejected so the audit trail shows
        the controller ASKED and the rail held."""
        accepted = True
        if not self.safe_mode:
            if knob == "superstep":
                result = engine.request_knobs(superstep=move["to"])
                accepted = result.get("superstep", False)
            elif knob == "width_floor":
                result = engine.request_knobs(width_floor=move["to"])
                accepted = result.get("width_floor", False)
            elif knob == "spec":
                result = engine.request_knobs(
                    spec_enabled=bool(move["to"]))
                accepted = result.get("spec_enabled", False)
        return self._record(rid, knob, move, now, accepted=accepted)

    def _record(self, rid: str, knob: str, move: dict[str, Any],
                now: float, accepted: bool) -> dict[str, Any]:
        self._seq += 1
        direction = move["direction"] if accepted else "hold_rejected"
        if accepted:
            self._last_move_ts[(rid, knob)] = now
            self._last_direction[(rid, knob)] = move["direction"]
        wall = time.time()
        row = {
            "schema": RING_SCHEMA,
            "seq": self._seq,
            "ts": wall,
            "replica": rid,
            "knob": knob,
            "direction": direction,
            "from": move["from"],
            "to": move["to"],
            "actuated": accepted and not self.safe_mode,
            "safe_mode": self.safe_mode,
            # the triggering evidence, verbatim — an audit row must
            # stand alone ("signal snapshot in -> knob delta out")
            "signals": dict(move.get("why") or {}),
            # filled after eval_window_s by _settle_effects
            "effect": None,
        }
        self._ring.append(row)
        watch = self._effect_watch(rid)
        self._pending_effects.append({
            "due": now + self.eval_window_s,
            "row": row,
            "before": watch,
        })
        # bound the pending list the same way the ring is bounded
        if len(self._pending_effects) > self._ring.maxlen:
            self._pending_effects = self._pending_effects[-self._ring.maxlen:]
        if self.metrics is not None:
            try:
                self.metrics.controller_decisions.labels(
                    knob=knob, direction=direction).inc()
            except Exception:
                pass
        if self.tracer is not None:
            # parentless decision span (same pattern as llm.xla_compile):
            # stitched into retained traces by the trace store's
            # controller window so forensics can line a latency shift up
            # against the knob move that caused it
            try:
                self.tracer.emit_span(
                    "controller.decision", wall - 0.001, wall,
                    attributes={
                        "controller.knob": knob,
                        "controller.replica": rid,
                        "controller.direction": direction,
                        "controller.from": str(move["from"]),
                        "controller.to": str(move["to"]),
                        "controller.actuated":
                            bool(accepted and not self.safe_mode),
                    })
            except Exception:
                pass
        return row

    # ------------------------------------------------------ effect capture

    _EFFECT_SIGNALS = ("llm.queue_wait_ms", "llm.ttft_ms",
                       "llm.tokens_per_dispatch", "llm.idle_frac",
                       "llm.step_tokens_per_sec")

    def _effect_watch(self, rid: str) -> dict[str, float]:
        """EWMA snapshot of the outcome signals a decision is judged by."""
        out: dict[str, float] = {}
        scope = (rid,) if rid != GATEWAY_REPLICA else \
            tuple(self.bus.replicas("llm.queue_wait_ms")) or (rid,)
        for name in self._EFFECT_SIGNALS:
            for replica in scope:
                value = self.bus.ewma(name, replica)
                if value is not None:
                    out[f"{name}@{replica}"] = round(value, 4)
        return out

    def _settle_effects(self, now: float) -> None:
        """Fill in the observed post-window effect on due decision rows
        (audit-ring contract: signal snapshot in -> knob delta out ->
        observed effect after the evaluation window)."""
        due = [p for p in self._pending_effects if p["due"] <= now]
        if not due:
            return
        self._pending_effects = [p for p in self._pending_effects
                                 if p["due"] > now]
        for pending in due:
            row = pending["row"]
            after = self._effect_watch(row["replica"])
            effect: dict[str, Any] = {}
            for key, before in pending["before"].items():
                effect[key] = {"before": before,
                               "after": after.get(key)}
            for key, value in after.items():
                if key not in effect:
                    effect[key] = {"before": None, "after": value}
            row["effect"] = effect

    # ------------------------------------------------------- admin surface

    def _refresh_knob_gauges(self) -> None:
        if self.metrics is None:
            return
        try:
            for engine in self.engines_fn() or []:
                rid = engine.config.replica_id
                state = engine.knob_state()
                self.metrics.controller_knob.labels(
                    knob="superstep", replica=rid).set(state["superstep"])
                self.metrics.controller_knob.labels(
                    knob="width_floor", replica=rid).set(
                    state["width_floor"])
                self.metrics.controller_knob.labels(
                    knob="spec", replica=rid).set(
                    1.0 if state["spec_enabled"] else 0.0)
            if self.shedder is not None:
                self.metrics.controller_knob.labels(
                    knob="shed_bar", replica=GATEWAY_REPLICA).set(
                    float(self.shedder.shed_at))
        except Exception:
            pass

    def decisions(self, limit: int = 64) -> list[dict[str, Any]]:
        """Newest-first audit rows (the /admin/controller ring)."""
        rows = list(self._ring)
        rows.reverse()
        return rows[:max(1, limit)]

    def snapshot(self, limit: int = 64) -> dict[str, Any]:
        """Full admin view: posture, ladders, ring, live signal table."""
        knobs: dict[str, Any] = {}
        for engine in self.engines_fn() or []:
            try:
                knobs[engine.config.replica_id] = engine.knob_state()
            except Exception:
                continue
        return {
            "enabled": self.enabled,
            "safe_mode": self.safe_mode,
            "tick_s": self.tick_s,
            "cooldown_s": self.cooldown_s,
            "eval_window_s": self.eval_window_s,
            "hysteresis": self.hysteresis,
            "ticks": self._ticks,
            "shed_bar": (float(self.shedder.shed_at)
                         if self.shedder is not None else None),
            "shed_ceiling": self._shed_ceiling,
            "shed_floor": self.shed_floor,
            "knobs": knobs,
            "decisions": self.decisions(limit),
            "signals": self.bus.snapshot(),
            "bus": self.bus.stats(),
        }
