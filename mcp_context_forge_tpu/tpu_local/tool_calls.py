"""OpenAI ``tools``/``tool_calls`` function-calling wire support.

The reference chat agent drives native function calling through LangGraph
(`/root/reference/mcpgateway/services/mcp_client_chat_service.py:31-37`):
providers receive an OpenAI ``tools`` array and answer with
``message.tool_calls``. For the in-tree engine the LLM is a text model,
so this module is the structured-emission layer:

- ``render_tools_block``: tool definitions rendered into the system
  prompt, Llama-3.1 style (JSON function signatures + an instruction to
  emit a JSON call object — one object, or an array for PARALLEL calls).
- ``parse_tool_calls``: parse generated text back into OpenAI
  ``tool_calls`` entries (``{"id","type","function":{"name","arguments"}}``
  with ``arguments`` as a JSON STRING, per the OpenAI wire shape).

Accepted emission shapes (models vary): ``{"name": ..., "parameters":
{...}}``, ``{"name": ..., "arguments": {...}}``, ``{"tool": ...,
"arguments": {...}}``, any of those inside a JSON array, and an optional
``<|python_tag|>`` prefix (Llama-3.1's tool-call marker).
"""

from __future__ import annotations

import json
from typing import Any

from ..utils.ids import new_id

TOOLS_PROMPT = """You have access to the following functions:

{definitions}

To call a function, respond with ONLY a JSON object:
{{"name": "<function-name>", "parameters": {{...}}}}
To call several functions at once, respond with a JSON array of such objects.
When no function is needed, answer in plain text (never JSON).
"""


def render_tools_block(tools: list[dict[str, Any]]) -> str:
    """System-prompt block for an OpenAI ``tools`` array."""
    definitions = []
    for tool in tools:
        fn = tool.get("function", tool)
        definitions.append(json.dumps({
            "name": fn.get("name", ""),
            "description": fn.get("description", ""),
            "parameters": fn.get("parameters") or {},
        }, separators=(",", ":")))
    return TOOLS_PROMPT.format(definitions="\n".join(definitions))


def _normalize_call(obj: Any) -> dict[str, Any] | None:
    if not isinstance(obj, dict):
        return None
    name = obj.get("name") or obj.get("tool")
    if not isinstance(name, str) or not name:
        return None
    args = obj.get("parameters")
    if args is None:
        args = obj.get("arguments")
    if args is None:
        args = {}
    if not isinstance(args, dict):
        return None
    return {
        "id": f"call_{new_id()[:16]}",
        "type": "function",
        "function": {"name": name,
                     "arguments": json.dumps(args, separators=(",", ":"))},
    }


def parse_tool_calls(text: str) -> list[dict[str, Any]] | None:
    """Tool calls emitted in ``text``, or None when it is a plain answer."""
    stripped = text.strip()
    candidates = [stripped]
    # NOTE: a leading <|python_tag|> marker needs no special-casing — the
    # outermost-JSON-span fallback below starts at the first brace/bracket,
    # which skips any prefix marker (and any prose) identically.
    # models wrap JSON in prose/code fences; try the outermost JSON span too
    for open_ch, close_ch in ("{}", "[]"):
        start = stripped.find(open_ch)
        end = stripped.rfind(close_ch)
        if 0 <= start < end:
            candidates.append(stripped[start:end + 1])
    for candidate in candidates:
        try:
            obj = json.loads(candidate)
        except json.JSONDecodeError:
            continue
        items = obj if isinstance(obj, list) else [obj]
        calls = [_normalize_call(item) for item in items]
        if calls and all(c is not None for c in calls):
            return calls  # type: ignore[return-value]
    return None


def tool_call_message_text(tool_calls: list[dict[str, Any]]) -> str:
    """Render an assistant tool_calls message back to prompt text (the
    model must see its own prior calls in-context on the next turn)."""
    calls = []
    for call in tool_calls:
        fn = call.get("function", {})
        try:
            args = json.loads(fn.get("arguments") or "{}")
        except json.JSONDecodeError:
            args = {}
        calls.append({"name": fn.get("name", ""), "parameters": args})
    payload = calls[0] if len(calls) == 1 else calls
    return json.dumps(payload, separators=(",", ":"))
