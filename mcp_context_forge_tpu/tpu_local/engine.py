"""Continuous-batching inference engine.

The crux component (SURVEY.md §7.2 #1): an asyncio front (request queue,
tokenizer, per-request token streams) bridged to a **dispatch thread** that
owns every device sync, so decode steps never stall the gateway's event
loop (SURVEY.md §7.2 #3 — "one process cannot block the event loop on
jax.device_get"). XLA's static-shape discipline is respected everywhere:

- prefill compiles once per (prefill_batch, bucket) shape — admissions are
  batched up to ``prefill_max_batch`` requests sharing a bucket, so bursts
  amortize the forward pass instead of serializing behind each other;
- decode compiles once for the full [max_batch] slot array — inactive slots
  ride along masked (position 0 into the trash page);
- sampling params are per-slot device arrays, so mixed greedy/temperature
  requests share one compiled step, and the FIRST token is sampled on
  device with the same kernel + engine PRNG as every later token (one
  sampler, one RNG stream).

The engine is a single-owner of its mesh/slice: gateway workers reach it
in-process (single worker) or over the /v1 HTTP surface (multi-worker),
mirroring the reference's session-affinity routing (SURVEY.md §7.1 phase 4).
"""

from __future__ import annotations

import os
import asyncio
import logging
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.faults import fault_point
from ..observability.logging import trace_extra
from .compile_events import (CompileTracker, install_listener,
                             restore_thread, track_thread)
from .kv import PageAllocator, init_kv_state, kv_logical
from .models import MODEL_CONFIGS, LlamaConfig
from .models.llama import (decode_step, init_params, params_logical, prefill,
                           prefill_with_history)
from .parallel import make_mesh, param_specs
from .roofline import (V5E_HBM_GBPS, V5E_PEAK_BF16_TFLOPS, CostRegistry,
                       roofline_fractions)
from .sampling import SamplingParams, sample_tokens
from .tokenizer import load_tokenizer

logger = logging.getLogger(__name__)

# smoothing factor for the tokens-per-dispatch EWMA gauge twin (and the
# signal-bus copy): ~last 10 dispatches dominate, long enough to ride out
# batch-occupancy whipsaw, short enough to track a real load shift
_TPD_EWMA_ALPHA = 0.2


@dataclass
class EngineConfig:
    model: str = "llama3-tiny"
    checkpoint: str = ""
    # identity within an EnginePool (pool/): labels the replica's metrics
    # (TTFT/TPOT/dispatch-gap/KV-bytes) and spans so per-replica SLOs are
    # separable on one dashboard. "0" for a standalone engine.
    replica_id: str = "0"
    max_batch: int = 8              # decode slots
    max_seq_len: int = 2048
    page_size: int = 128
    num_pages: int = 512
    prefill_buckets: tuple[int, ...] = (128, 512, 2048)
    prefill_max_batch: int = 4      # admissions fused into one prefill call
    mesh_shape: str = ""
    dtype: str = "bfloat16"
    max_queue: int = 1024
    attn_impl: str = "auto"
    # sequence-parallel long prefill: prompts > sp_threshold tokens route
    # through ring/ulysses attention over the mesh (SURVEY.md §5.7)
    sp_impl: str = "none"      # none|ring|ulysses
    sp_threshold: int = 1024
    # decode steps fused per device dispatch (lax.scan): amortizes the
    # host<->device sync to 1/k per token; tokens decoded past EOS inside a
    # block are discarded (standard multi-step scheduling waste)
    decode_block: int = 1
    # K-step decode SUPER-STEPS (token-loop fusion, ROADMAP item 1 /
    # SnapStream-style dataflow decoding): one jitted lax.scan runs
    # ``superstep`` decode iterations entirely on device — fused
    # sampling, in-loop paged-KV page append over pre-granted pages, and
    # per-slot budget/EOS/stop masking so finished rows FREEZE on device
    # (no post-EOS KV writes, positions stop advancing) — and the host
    # syncs once per K tokens instead of once per token. Supersedes
    # ``decode_block`` (kept as a back-compat alias; setting both to
    # conflicting values is rejected). Composes with decode_overlap
    # (depth-2 pipeline at super-step granularity) and int8 KV; mutually
    # exclusive with spec_decode like decode_block>1.
    superstep: int = 1
    # depth-2 overlapped decode pipeline: dispatch step N+1 fed by step
    # N's device-resident sampled tokens while step N's results transfer
    # and emit one step behind, so host bookkeeping (emission, EOS
    # checks, page extension) hides behind device execution instead of
    # serializing with it. Drain barriers (admission, chunk completion,
    # batch-width changes, stop/crash) keep token streams identical to
    # the serial path. Ignored when spec_decode is on (the verify step
    # has its own host feedback loop).
    decode_overlap: bool = True
    # seconds to wait for jax backend init before failing fast (0 = forever)
    init_timeout_s: float = 120.0
    # precompile the shape grid at construction (see TPUEngine.warmup)
    warmup: bool = False
    warmup_mode: str = "full"  # full | fast (cold-TPU-friendly subset)
    # persistent XLA compilation cache ('' = disabled)
    compile_cache_dir: str = ""
    # prefix cache: reuse resident KV pages for shared full-page prompt
    # prefixes; only each request's suffix pays prefill (vLLM APC analog)
    prefix_cache: bool = True
    # tiered prefix/KV cache (kv/tiers.py, docs/kv_tiering.md): evicted
    # prefix pages SPILL to a bounded host-RAM store (int8 bytes +
    # per-(layer, kv-head) scales; quantize-on-spill under a bf16 pool)
    # with a disk write-behind tier below it, and admission restores
    # tier-resident chain pages into HBM on match (fetch-on-miss). Under
    # an EnginePool the store + prefix index are POOL-SHARED, so a
    # prefix prefilled on any replica serves a hit on every replica.
    # Requires prefix_cache.
    prefix_tiers: bool = False
    tier_host_bytes: int = 256 * 1024 * 1024   # T1 (host RAM) byte budget
    tier_disk_bytes: int = 1024 * 1024 * 1024  # T2 (disk) byte budget; 0 = off
    tier_disk_dir: str = ""                    # "" = private tempdir
    # spill storage mode for FULL-PRECISION pools: "int8" (default)
    # quantizes on spill — 2-4x cheaper tiers, restored pages carry the
    # same small greedy drift as resident int8 KV — or "" to spill in
    # resident precision (lossless round trip, byte-identical
    # continuations guaranteed). An int8-resident pool always spills its
    # bytes verbatim (bit-exact) regardless of this knob.
    tier_spill_quant: str = "int8"
    # spill-tier disk IO hardening (docs/resilience.md): transient
    # read/writeback errors retry this many times with jittered backoff,
    # then the entry quarantines to a clean MISS
    tier_io_retry_max: int = 2
    tier_io_retry_backoff_ms: float = 10.0
    # cross-host prefix-cache fabric (kv/fabric/, docs/cache_fabric.md):
    # T3 object-store hop below disk — "" = no fabric; the namespace
    # qualifies every blob key (tenant isolation by construction)
    tier_object_url: str = ""
    fabric_namespace: str = "shared"
    # speculative decoding via prompt-lookup (n-gram) drafting: decode is
    # HBM-bandwidth-bound (one full param read per step), so verifying
    # spec_k drafted tokens in ONE step multiplies tokens/step by the
    # accept rate for free bandwidth-wise. Greedy rows only; sampled rows
    # ride the same verify step one token at a time. Mutually exclusive
    # with decode_block > 1. On TPU the verify runs the Pallas paged
    # CHUNK kernel (same enabling conditions as decode); the remaining
    # trade is K x the attention/MLP compute per dispatch, so low accept
    # rates (non-repetitive output) can still lose — enable for
    # repetitive workloads (summaries, extraction, code edits) and watch
    # stats.spec_tokens.
    spec_decode: bool = False
    spec_k: int = 4          # chunk width: 1 input token + spec_k-1 drafts
    spec_ngram: int = 2      # context n-gram length used for lookup
    # weight-only quantization: "" (full precision) or "int8" — halves the
    # resident param footprint AND the per-step HBM traffic (quantize.py;
    # how Llama-3-8B fits a single 16 GB v5e chip)
    quant: str = ""
    # KV-cache quantization: "" (pages in the engine dtype) or "int8" —
    # pages store int8 with per-page, per-kv-head scales
    # (kv/paged_cache.py), halving decode-attention HBM traffic; the
    # Pallas decode kernel dequantizes in VMEM. ``num_pages`` stays
    # denominated in ENGINE-DTYPE pages (a byte budget): at the same HBM
    # bytes an int8 pool holds ~2x the pages, so _init_kv converts.
    kv_quant: str = ""
    # MoE serving formulation override ("" = model default; see
    # models/configs.py moe_impl): dense | grouped | grouped_pallas.
    # moe_block overrides the kernel row-block AND the T·k >= E·block
    # engagement gate (0 = model default) — small models/benches need a
    # smaller block or every dispatch falls back to the dense scan.
    moe_impl: str = ""
    moe_block: int = 0
    # decode batch-width bucketing: size decode arrays by the ACTIVE slot
    # ceiling (pow-2, with slot compaction + shrink hysteresis) instead of
    # max_batch. Wins on sparse/steady loads (fewer wasted rows per step);
    # every width change re-homes the donated KV pool (~a pool copy), so
    # the width starts at max_batch (identical to fixed width until light
    # load is SUSTAINED), pins at max while work is queued, and only
    # shrinks to warmup-compiled widths after batch_shrink_steps
    # consecutive under-width steps. Off by default; enable for
    # latency-sensitive low-concurrency serving.
    batch_buckets: bool = False
    batch_shrink_steps: int = 64
    # idle-boundary width reset: after this long fully idle, the next
    # admission re-sizes from the NEW load instead of inheriting a stale
    # burst width. High enough that inter-wave dips (ms) never trigger
    # the shrink+regrow re-home pair the hysteresis exists to avoid.
    batch_idle_reset_s: float = 2.0
    # device-fault recovery (SURVEY §5.3): a crashed dispatch thread
    # rebuilds the KV pool, re-queues PENDING requests (mid-stream ones
    # fail — silent retry would duplicate emitted tokens) and restarts
    # itself, at most auto_restart_max times. Off by default: tests and
    # benches prefer fail-fast; production serving turns it on.
    auto_restart: bool = False
    auto_restart_max: int = 3
    # step-introspection ring: per-dispatch summaries (kind, batch shape,
    # duration, tokens) kept for the diagnostics endpoint / admin UI
    step_log_size: int = 256
    # decode-step phase attribution: every Nth decode dispatch runs
    # serially with a timed block_until_ready window so its wall splits
    # into host-dispatch / table-sync / device-compute / read-back /
    # emission phases (step ring + mcpforge_llm_step_phase_seconds +
    # llm.decode span events). 0 disables — the default, so steady-state
    # traffic is unperturbed and token streams stay byte-identical.
    step_sample_every: int = 0
    # capture XLA cost_analysis() (FLOPs, bytes accessed) per compiled
    # executable at warmup into the engine's CostRegistry — what feeds
    # the live mcpforge_llm_mfu / mcpforge_llm_hbm_roofline_frac gauges.
    # Capture lowers each shape once more through the AOT path (a real
    # compile, amortized by the persistent cache); disable on cold TPUs
    # where warmup time is the binding constraint.
    cost_analysis: bool = True
    # per-chip roofline peaks the live gauges divide by (defaults: v5e)
    peak_tflops_per_chip: float = V5E_PEAK_BF16_TFLOPS
    hbm_gbps_per_chip: float = V5E_HBM_GBPS
    # extra superstep rungs warmed ALONGSIDE fused_steps so the serving
    # controller (tpu_local/controller.py) can retune K at drain
    # barriers onto pre-compiled executables — a knob move can never
    # trigger a mid-traffic XLA compile. () = no extra rungs: the
    # decode grid is exactly the static-K grid (controller-off builds
    # compile nothing new and behave bit-identically).
    k_ladder: tuple[int, ...] = ()

    @property
    def fused_steps(self) -> int:
        """Effective decode iterations fused per device dispatch: the
        superstep K when set, else the legacy decode_block alias."""
        return self.superstep if self.superstep > 1 else self.decode_block

    def k_rungs(self) -> tuple[int, ...]:
        """Superstep values the warmup decode grid compiles: the static
        fused_steps plus every configured ladder rung, deduped and
        ascending. Adaptive K only ever moves along this set."""
        rungs = {self.fused_steps}
        rungs.update(int(k) for k in self.k_ladder if int(k) >= 1)
        return tuple(sorted(rungs))

    @classmethod
    def from_settings(cls, settings) -> "EngineConfig":
        return cls(
            model=settings.tpu_local_model,
            checkpoint=settings.tpu_local_checkpoint,
            max_batch=settings.tpu_local_max_batch,
            max_seq_len=settings.tpu_local_max_seq_len,
            page_size=settings.tpu_local_page_size,
            num_pages=settings.tpu_local_num_pages,
            prefill_buckets=tuple(settings.tpu_local_prefill_buckets),
            prefill_max_batch=getattr(settings, "tpu_local_prefill_max_batch", 4),
            mesh_shape=settings.tpu_local_mesh_shape,
            dtype=settings.tpu_local_dtype,
            sp_impl=getattr(settings, "tpu_local_sp_impl", "none"),
            sp_threshold=getattr(settings, "tpu_local_sp_threshold", 1024),
            decode_block=getattr(settings, "tpu_local_decode_block", 1),
            superstep=getattr(settings, "tpu_local_superstep", 1),
            decode_overlap=getattr(settings, "tpu_local_decode_overlap", True),
            init_timeout_s=getattr(settings, "tpu_local_init_timeout_s", 120.0),
            warmup=getattr(settings, "tpu_local_warmup", False),
            warmup_mode=getattr(settings, "tpu_local_warmup_mode", "full"),
            compile_cache_dir=getattr(settings, "tpu_local_compile_cache_dir", ""),
            prefix_cache=getattr(settings, "tpu_local_prefix_cache", True),
            prefix_tiers=getattr(settings, "tpu_local_prefix_tiers", False),
            tier_host_bytes=getattr(
                settings, "tpu_local_tier_host_bytes", 256 * 1024 * 1024),
            tier_disk_bytes=getattr(
                settings, "tpu_local_tier_disk_bytes", 1024 * 1024 * 1024),
            tier_disk_dir=getattr(settings, "tpu_local_tier_disk_dir", ""),
            tier_spill_quant=getattr(
                settings, "tpu_local_tier_spill_quant", "int8"),
            tier_io_retry_max=getattr(settings, "tier_io_retry_max", 2),
            tier_io_retry_backoff_ms=getattr(
                settings, "tier_io_retry_backoff_ms", 10.0),
            tier_object_url=getattr(
                settings, "tpu_local_tier_object_url", ""),
            fabric_namespace=getattr(
                settings, "tpu_local_fabric_namespace", "shared"),
            spec_decode=getattr(settings, "tpu_local_spec_decode", False),
            spec_k=getattr(settings, "tpu_local_spec_k", 4),
            spec_ngram=getattr(settings, "tpu_local_spec_ngram", 2),
            quant=getattr(settings, "tpu_local_quant", ""),
            kv_quant=getattr(settings, "tpu_local_kv_quant", ""),
            moe_impl=getattr(settings, "tpu_local_moe_impl", ""),
            batch_buckets=getattr(settings, "tpu_local_batch_buckets", False),
            max_queue=getattr(settings, "tpu_local_max_queue", 1024),
            auto_restart=getattr(settings, "tpu_local_auto_restart", False),
            auto_restart_max=getattr(settings, "tpu_local_auto_restart_max", 3),
            step_log_size=getattr(settings, "tpu_local_step_log_size", 256),
            step_sample_every=getattr(
                settings, "tpu_local_step_sample_every", 0),
            cost_analysis=getattr(settings, "tpu_local_cost_analysis", True),
            peak_tflops_per_chip=getattr(
                settings, "tpu_local_peak_tflops_per_chip",
                V5E_PEAK_BF16_TFLOPS),
            hbm_gbps_per_chip=getattr(
                settings, "tpu_local_hbm_gbps_per_chip", V5E_HBM_GBPS),
            # extra K rungs only when the controller is on: off keeps the
            # warmup grid — and therefore compile count and serving
            # behavior — bit-identical to a pre-controller build
            k_ladder=(tuple(getattr(settings, "controller_k_ladder", ()))
                      if getattr(settings, "controller_enabled", False)
                      else ()),
        )


@dataclass
class GenRequest:
    request_id: str
    prompt_ids: list[int]
    max_tokens: int = 128
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop_ids: tuple[int, ...] = ()
    # admission class (SURVEY §7.2 #2 latency budget): 0 = interactive
    # (chat turns, agent hops), 1 = background (summaries, batch work).
    # Lower admits first when slots are contended; decode itself is shared
    # continuous batching, so a class never starves once admitted.
    priority: int = 0
    # unbounded: tokens are ints bounded by max_tokens, and a bounded queue
    # could drop the end-of-stream sentinel and hang the consumer
    stream: asyncio.Queue = field(default_factory=asyncio.Queue)
    created: float = field(default_factory=time.time)
    # filled by the engine
    slot: int = -1
    generated: list[int] = field(default_factory=list)
    finish_reason: str | None = None
    prefill_ms: float = 0.0
    queue_ms: float = 0.0
    # prefix-cache admission state: probed cached-history length and the
    # (suffix) bucket; bucket -1 means not yet probed. The probe takes no
    # page references — the real match happens at admission. ``chunked``
    # marks prompts whose (suffix) length exceeds every bucket: they
    # prefill in multiple bucket-sized chunks through the history path.
    hist: int = 0
    bucket: int = -1
    chunked: bool = False
    chunk_pos: int = 0   # tokens prefilled so far (chunk-round scheduler)
    # billing identity (observability/tenant.py resolution order:
    # team → API key → user; "" = unattributed internal work). Rides
    # into the engine so retire-time accounting lands in the tenant
    # ledger, survives pool failover (shadows copy it), and labels the
    # TTFT/TPOT/queue-wait histograms (clamped)
    tenant: str = ""
    # telemetry: (trace_id, span_id) of the submitter's llm.request span —
    # the dispatch thread parents llm.queue/prefill/decode spans to it
    trace_ctx: tuple[str, str] | None = None
    first_token_ts: float = 0.0
    # routing class for role-specialized pools (docs/disaggregation.md):
    # "" = classify by shape (prompt length) at the pool router; a
    # non-empty value pins the request to replicas holding that role
    # ("prefill"/"decode" for the phase split, or any fleet class such
    # as a tenant SLO tier / model size behind the same field)
    route_class: str = ""
    # once-only guard: crash-recovery requeues pass admission twice, and
    # the queue span/histogram must not double-observe the request
    queue_observed: bool = False
    # same pattern for the first-token surfaces: a pool-failover
    # continuation whose original attempt already emitted tokens must not
    # observe a second TTFT sample (it would span the failed attempt +
    # failover) or re-emit llm.prefill for the same logical request
    ttft_observed: bool = False


class EngineStats:
    def __init__(self) -> None:
        self.requests = 0
        self.prompt_tokens = 0
        self.completion_tokens = 0
        self.decode_steps = 0
        self.decode_dispatches = 0    # device dispatches (= host syncs);
        #                               decode_steps / decode_dispatches ≈ K
        self.prefill_batches = 0
        self.prefill_requests = 0
        self.queue_depth = 0
        self.spec_steps = 0      # speculative verify dispatches
        self.spec_tokens = 0     # extra tokens emitted beyond 1/step
        self.prefill_ms_total = 0.0   # device wall inside prefill dispatches
        self.decode_ms_total = 0.0    # device wall inside decode dispatches
        self.engine_restarts = 0      # crash-recovery restarts (auto_restart)
        self.chunking = 0             # long prompts mid-chunk-prefill
        self.overlap_steps = 0        # decode dispatches fed from device tokens
        self.pipeline_drains = 0      # overlap barriers that forced a drain
        self.dispatch_gap_ms_total = 0.0  # host-side stall between dispatches
        self.phase_samples = 0        # decode steps with phase attribution


class EngineInitTimeout(RuntimeError):
    """jax backend init exceeded the watchdog budget (dead TPU runtime)."""


_compile_cache_dir: str | None = None


def _host_fingerprint() -> str:
    """Hash of the host's CPU feature flags + arch.

    The persistent cache stores AOT executables specialized to the
    COMPILING host's CPU features; this container migrates between hosts
    with different feature sets (observed: +amx/+prefer-no-gather hosts
    vs hosts without), and XLA loading a mismatched AOT entry SIGSEGVs
    mid-request (cpu_aot_loader 'machine type ... doesn't match'
    warnings, then a crash in the decode path). Scoping the cache dir by
    fingerprint makes a migrated container start a fresh cache instead
    of loading poison. TPU executables don't depend on host CPU flags,
    but re-warming a per-host subdir is cheap relative to a SIGSEGV."""
    import hashlib
    import platform

    flags = ""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    flags = line
                    break
    except OSError:
        pass
    raw = f"{platform.machine()}:{flags}"
    return hashlib.sha256(raw.encode()).hexdigest()[:12]


def _apply_compile_cache(path: str) -> None:
    """Set the process-global persistent XLA cache exactly once.

    ``jax_compilation_cache_dir`` is process state, not engine state: a
    second engine (or a test constructing engines back to back) must not
    silently flip the cache out from under compiled-but-unwritten entries
    (round-2 ADVICE low). First caller wins; a conflicting later value is
    logged and ignored."""
    global _compile_cache_dir
    path = os.path.join(path, _host_fingerprint())
    if _compile_cache_dir is None:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _compile_cache_dir = path
    elif _compile_cache_dir != path:
        logger.warning(
            "compile cache already pinned to %s; ignoring %s "
            "(process-global setting)", _compile_cache_dir, path)


def probe_devices(timeout_s: float) -> list:
    """``jax.devices()`` under a watchdog.

    A wedged TPU runtime (e.g. a dead tunnel to the chip) blocks backend
    init indefinitely inside the PJRT client constructor; run it on a
    daemon thread so a hang becomes a diagnosable exception instead of a
    gateway that never binds its port. On success the backend is cached
    process-wide, so every later jax call returns instantly.
    """
    if timeout_s <= 0:
        return jax.devices()
    result: dict[str, Any] = {}

    def _probe() -> None:
        try:
            result["devices"] = jax.devices()
        except Exception as exc:  # surfaced on the caller thread
            result["error"] = exc

    t = threading.Thread(target=_probe, name="tpu-init-probe", daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise EngineInitTimeout(
            f"jax backend init did not complete within {timeout_s:.0f}s — "
            "TPU runtime unreachable (set MCPFORGE_TPU_LOCAL_ENABLED=false "
            "to serve without the engine, or raise "
            "MCPFORGE_TPU_LOCAL_INIT_TIMEOUT_S)")
    if "error" in result:
        raise result["error"]
    return result["devices"]


class TPUEngine:
    """Owns params + KV pool on the mesh; device syncs run on the dispatch
    thread, token emission hops back to the asyncio loop."""

    # static stop-id columns the super-step's on-device freeze checks:
    # column 0 is always EOS, the rest carry a request's first stop_ids.
    # STATIC so one compiled super-step serves every request mix; rows
    # with more stop ids stay host-detected (the device merely fails to
    # freeze early — streams are unaffected, see _decode_and_sample)
    _STOP_TBL_WIDTH = 4

    def __init__(self, config: EngineConfig, tracer=None, metrics=None,
                 devices: list | None = None, ledger=None,
                 tier_store=None, prefix_index=None, signals=None):
        # telemetry handles are optional: None means zero-cost no-ops, so
        # unit tests and benches constructing engines directly pay nothing
        self.tracer = tracer
        self.metrics = metrics
        # live signal bus (observability/signals.py): retire-site pushes
        # feed the serving controller; None = every publish site is a
        # single attribute check. Assignable post-construction too (the
        # gateway wires the bus after the pool builds its replicas).
        self.signals = signals
        # per-tenant usage ledger (observability/metering.py): fed at the
        # SAME sites as the untagged stats counters so per-tenant sums
        # conserve exactly against stats.prompt_tokens /
        # completion_tokens / allocator.prefix_hit_tokens
        self.ledger = ledger
        self.step_log: deque[dict[str, Any]] = deque(
            maxlen=max(1, config.step_log_size))
        self._step_seq = 0
        if config.decode_block < 1:
            raise ValueError(
                f"decode_block must be >= 1, got {config.decode_block}")
        if config.superstep < 1:
            raise ValueError(
                f"superstep must be >= 1, got {config.superstep}")
        if (config.superstep > 1 and config.decode_block > 1
                and config.superstep != config.decode_block):
            raise ValueError(
                f"superstep={config.superstep} and decode_block="
                f"{config.decode_block} disagree — set only one "
                "(decode_block is the legacy alias)")
        if config.spec_decode and config.fused_steps > 1:
            raise ValueError("spec_decode and superstep/decode_block>1 are "
                             "mutually exclusive (both widen the "
                             "per-dispatch step)")
        if config.spec_decode and any(int(k) > 1 for k in config.k_ladder):
            raise ValueError("k_ladder rungs > 1 are mutually exclusive "
                             "with spec_decode (same exclusivity as "
                             "superstep > 1)")
        if config.spec_decode and config.spec_k < 2:
            raise ValueError(f"spec_k must be >= 2, got {config.spec_k}")
        if config.spec_decode and config.spec_ngram < 1:
            raise ValueError(f"spec_ngram must be >= 1, got {config.spec_ngram}")
        if config.prefix_tiers and not config.prefix_cache:
            raise ValueError("prefix_tiers requires prefix_cache (the tiers "
                             "spill and restore prefix-cache pages)")
        if config.tier_spill_quant not in ("", "int8"):
            raise ValueError(f"unsupported tier_spill_quant mode "
                             f"{config.tier_spill_quant!r}")
        self.config = config
        # tiered prefix cache (kv/tiers.py): bind to the POOL-SHARED
        # store/index when an EnginePool passed them, else own a private
        # store (standalone engine). A client with only an index still
        # publishes HBM residency so the pool router can score affinity
        # across replicas even with the spill tiers off.
        self._owned_tier_store = None
        self._tier_client = None
        if config.prefix_cache and (config.prefix_tiers
                                    or prefix_index is not None
                                    or tier_store is not None):
            from .kv.tiers import TierClient, TieredPageStore
            store = tier_store
            if store is None and config.prefix_tiers:
                from .kv.fabric.object_store import object_store_or_none
                store = TieredPageStore(
                    host_bytes=config.tier_host_bytes,
                    disk_bytes=config.tier_disk_bytes,
                    disk_dir=config.tier_disk_dir,
                    index=prefix_index, metrics=metrics,
                    io_retry_max=config.tier_io_retry_max,
                    io_retry_backoff_ms=config.tier_io_retry_backoff_ms,
                    object_store=object_store_or_none(
                        config.tier_object_url),
                    object_namespace=config.fabric_namespace)
                self._owned_tier_store = store
            self._tier_client = TierClient(config.replica_id, store=store,
                                           index=prefix_index,
                                           metrics=metrics, tracer=tracer)
        # dispatch-side export snapshot for the per-tier hit counters
        self._tier_hits_exported: dict[str, int] = {}  # lint: thread[dispatch]
        # the fused super-step width every decode dispatch scans over
        # (1 = the classic one-token step); resolved once — the compiled
        # grid is keyed on it
        self._k = config.fused_steps
        if config.batch_buckets and not config.warmup:
            # unwarmed engines shrink only to widths already compiled
            # in-process (shrinking never compiles on the serving path);
            # warmup compiles the whole grid up front and starts at max
            logger.info(
                "batch_buckets=true without warmup: width starts small "
                "and shrink targets are limited to in-process-compiled "
                "widths — set MCPFORGE_TPU_LOCAL_WARMUP=true for "
                "production serving")
        if config.compile_cache_dir:
            _apply_compile_cache(config.compile_cache_dir)
        self.model_config: LlamaConfig = MODEL_CONFIGS[config.model]
        if config.moe_impl or config.moe_block:
            import dataclasses
            overrides: dict[str, Any] = {}
            if config.moe_impl:
                overrides["moe_impl"] = config.moe_impl
            if config.moe_block:
                overrides["moe_block"] = config.moe_block
            self.model_config = dataclasses.replace(self.model_config,
                                                    **overrides)
        self.tokenizer = load_tokenizer(config.checkpoint,
                                        vocab_size=self.model_config.vocab_size)
        self.stats = EngineStats()
        self._work: queue.Queue[GenRequest] = queue.Queue(maxsize=config.max_queue)
        self._pending: deque[GenRequest] = deque()   # lint: thread[dispatch]
        self._running: dict[int, GenRequest] = {}    # slot -> request  # lint: thread[dispatch]
        self._chunking: dict[int, GenRequest] = {}   # mid-chunk-prefill  # lint: thread[dispatch]
        self._thread: threading.Thread | None = None
        self._stop_event = threading.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = False
        self._killed = False
        # overlapped decode pipeline state (dispatch thread only): the
        # dispatched-but-not-yet-emitted decode step, if any
        self._inflight: dict[str, Any] | None = None  # lint: thread[dispatch]
        # submit-side wakeup: the dispatch thread blocks here when idle
        # instead of polling with time.sleep (satellite: idle wakeup
        # latency and idle CPU both drop)
        self._wake = threading.Event()
        # step emission buffer: tokens accumulate here during a step and
        # flush to the asyncio loop in ONE call_soon_threadsafe per step
        self._emit_buf: list[list[Any]] = []  # lint: thread[dispatch]
        # dispatch-gap telemetry: (gap_s, step_wall_s) per decode step
        self._gap_window: deque[tuple[float, float]] = deque(maxlen=256)  # lint: thread[dispatch]
        self._last_step_done_ts: float | None = None  # lint: thread[dispatch]
        # decode batch-width hysteresis state (see _decode_step_all).
        # UNWARMED engines start small (light load is free immediately; a
        # burst pays ONE grow re-home) and may shrink back to any width
        # compiled earlier in-process. warmup() flips the posture: width
        # starts at max (a warmed engine must never be slower than fixed
        # width — the round-5 config-4 A/B) and shrink targets are the
        # whole warmed grid. (_batch_width itself is set to the smallest
        # bucket just below, once _warmed_widths exists.)
        self._shrink_streak = 0  # lint: thread[dispatch]
        self._shrink_peak = 0  # lint: thread[dispatch]
        # widths whose full ctx-bucket decode grid warmup precompiled:
        # shrinking is an OPTIMIZATION, so the engine never eats a
        # mid-traffic compile (+ donated-pool re-home) to get smaller —
        # only warmed widths are shrink targets. Growth is correctness
        # (arrays must cover the ceiling) and may compile.
        self._warmed_widths: set[int] = set()  # lint: thread[dispatch]
        self._batch_width = self._batch_buckets()[0]  # smallest  # lint: thread[dispatch]
        # when the engine last had active work (idle-boundary reset guard);
        # starts "now" so the warmed start-at-max posture survives a
        # burst arriving right after startup
        self._last_active_ts = time.monotonic()  # lint: thread[dispatch]
        # liveness heartbeat: bumped once per dispatch-loop iteration (the
        # idle wait is bounded at 50 ms, so a healthy engine beats at
        # >=20 Hz even with no traffic). The pool's health monitor reads
        # its AGE to tell a wedged device call from an idle engine.
        self._heartbeat_ts = time.monotonic()  # lint: thread[dispatch]
        # cancellation handoff: request ids the loop side asked to
        # terminate; the dispatch thread consumes them at the top of each
        # iteration (request_cancel is the only other writer, lock-guarded)
        self._cancels: set[str] = set()  # lint: thread[dispatch]
        self._cancel_lock = threading.Lock()  # lint: lock[dispatch]
        # serving-knob handoff (tpu_local/controller.py): loop-side
        # callers stage validated knob moves under the lock; the dispatch
        # thread consumes them at the top of its iteration, DRAINING the
        # overlap pipeline first when K changes — knob moves only ever
        # land at drain barriers, so greedy token streams match a run
        # that used the new posture from that barrier on
        self._pending_knobs: dict[str, Any] = {}  # lint: thread[dispatch]
        self._knob_lock = threading.Lock()  # lint: lock[dispatch]
        # chain-export handoff (pool KV migration, docs/disaggregation.md):
        # the pool stages (prompt_ids, future) pairs; the dispatch thread
        # consumes them at its drain barrier — device page reads are
        # dispatch-thread-only, and exporting at the barrier guarantees
        # the prefill leg's pages are fully retired before they spill
        self._pending_exports: list[tuple[tuple[int, ...],
                                          "Future"]] = []  # lint: thread[dispatch]
        self._export_lock = threading.Lock()  # lint: lock[dispatch]
        # runtime spec-decode gate (the controller's on/off knob): plain
        # decode is always warmed as the fallback path, so flipping this
        # never compiles; engines built without spec_decode ignore it
        self._spec_enabled = True  # lint: thread[dispatch]
        # controller-requested decode width floor (0 = none): bounds the
        # batch-bucket shrink path from below when the live occupancy
        # histogram says the next burst will just re-grow anyway
        self._width_floor = 0  # lint: thread[dispatch]
        # superstep rungs the warmup grid compiled; adaptive K may only
        # select these (request_knobs rejects anything else)
        self._warmed_k: set[int] = set()  # lint: thread[dispatch]
        # EWMA twin of the tokens-per-dispatch gauge (the instantaneous
        # value whipsaws with batch occupancy; smoothed form is what the
        # signal bus and alerts act on)
        self._tpd_ewma: float | None = None  # lint: thread[dispatch]
        # last publish of O(window) signals (idle fraction): bounded tick
        self._signals_slow_ts = 0.0  # lint: thread[dispatch]
        # decode-step attribution + live roofline state: the dispatch
        # counter drives the sampling cadence, phase events feed llm.decode
        # span events, the roofline window backs roofline_snapshot(), and
        # the cost registry holds warmup-captured XLA cost_analysis()
        self._dispatch_count = 0  # lint: thread[dispatch]
        self._phase_events: deque[tuple[float, dict[str, float]]] = \
            deque(maxlen=64)  # lint: thread[dispatch]
        self._roofline_window: deque[tuple[float, float, float]] = \
            deque(maxlen=256)  # lint: thread[dispatch]
        self.cost_registry = CostRegistry()
        # XLA compile tracking: every backend compile on a registered
        # thread (dispatch = "serving", warmup callers = "warmup") counts
        # + times itself; a serving-stage compile on a warmed engine is
        # the PR-5 mid-traffic-compile catastrophe resurfacing
        self.compile_tracker = CompileTracker(self._on_xla_compile)
        install_listener()
        # the build window compiles for real (param init, KV-state
        # placement, config.warmup's grid): attribute it all to the
        # "warmup" stage so the every-engine-compile-is-attributed
        # contract holds from construction on
        ctor_token = track_thread(self.compile_tracker, "warmup")
        try:
            self._build_device_state(devices)
        finally:
            restore_thread(ctor_token)

    def _build_device_state(self, devices) -> None:
        """Mesh + params + KV pool + jitted-step tables (the compile-heavy
        tail of construction; runs under the constructor's warmup-stage
        compile attribution)."""
        config = self.config
        dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
        # an EnginePool passes each replica its device subset; a standalone
        # engine owns every device the (watchdogged) backend reports
        if devices is None:
            devices = probe_devices(config.init_timeout_s)
        self.mesh = make_mesh(config.mesh_shape, devices=devices)
        logger.info("tpu_local: mesh %s, model %s", self.mesh.shape, config.model)
        if config.sp_impl != "none":
            # SP shard_map requires the sequence (bucket) to divide the axis;
            # reject at construction instead of killing the dispatch thread
            # on the first long prefill
            axis = self.mesh.shape.get("model", 1)
            bad = [b for b in config.prefill_buckets
                   if b > config.sp_threshold and b % axis != 0]
            if bad:
                raise ValueError(
                    f"sp_impl={config.sp_impl!r}: prefill buckets {bad} not"
                    f" divisible by mesh model axis {axis}")

        if config.quant not in ("", "int8"):
            raise ValueError(f"unsupported quant mode {config.quant!r}")
        if config.kv_quant not in ("", "int8"):
            raise ValueError(
                f"unsupported kv_quant mode {config.kv_quant!r}")
        if config.moe_impl not in ("", "dense", "grouped", "grouped_pallas"):
            # a typo must not silently serve the dense path (and make a
            # hardware A/B compare dense against dense)
            raise ValueError(
                f"moe_impl must be dense|grouped|grouped_pallas, "
                f"got {config.moe_impl!r}")
        # params: load checkpoint or random-init, placed with TP shardings;
        # quant="int8" swaps in the {"q","s"} tree (quantize.py)
        with self.mesh:
            logical = params_logical(self.model_config)
            if config.quant == "int8":
                from .quantize import quantize_logical, quantize_tree
                shardings = param_specs(quantize_logical(logical), self.mesh)
            else:
                shardings = param_specs(logical, self.mesh)
            if config.checkpoint:
                from .checkpoint import load_params
                self.params = load_params(config.checkpoint, self.model_config,
                                          shardings, dtype, quant=config.quant)
            else:
                if config.quant == "int8":
                    def init_fn(key):
                        full = init_params(self.model_config, key, dtype=dtype)
                        return quantize_tree(full, logical, scale_dtype=dtype)
                    init = jax.jit(init_fn, out_shardings=shardings)
                else:
                    init = jax.jit(partial(init_params, self.model_config,
                                           dtype=dtype),
                                   out_shardings=shardings)
                self.params = init(jax.random.PRNGKey(0))

            self._kv_dtype = dtype
            self._init_kv()

        self._rng = jax.random.PRNGKey(int(time.time()) & 0x7FFFFFFF)

        # compiled steps
        self._prefill_sample = jax.jit(self._prefill_and_sample,
                                       donate_argnames=("kv",))
        self._prefill_sample_sp = (
            jax.jit(partial(self._prefill_and_sample, sp=True),
                    donate_argnames=("kv",))
            if config.sp_impl != "none" else None)
        # decode compiles per (batch-width, context-width) bucket pair:
        # attention reads only the table columns the longest active row
        # needs — the full-width gather wastes ~max_context/actual_context
        # x HBM bandwidth on short conversations, and decode is
        # bandwidth-bound
        self._decode_fns: dict[tuple[int, int], Any] = {}
        # device-token-feedback decode (overlapped pipeline steady state):
        # same grid as _decode_fns, but the input token comes from the
        # PREVIOUS dispatch's on-device sampled block instead of the host
        self._decode_fb_fns: dict[tuple[int, int], Any] = {}
        # the chunk/history prefill is a core primitive (prefix-cache hits
        # AND chunked prefill of prompts longer than the largest bucket);
        # compiled per context-width bucket like decode (a hit with 40
        # resident tokens must not pay full-table-width attention)
        self._prefill_hist_fns: dict[int, Any] = {}
        self._verify_fns: dict[int, Any] | None = (
            {} if config.spec_decode else None)
        # spill-tier device I/O (one compiled scatter/gather per direction;
        # the page index rides as a traced scalar so every page shares it)
        self._tier_read_fn = None
        self._tier_write_fn = None
        if self._tier_client is not None and self._tier_client.store is not None:
            self._build_tier_fns()
            self._tier_client.read_fn = self._read_page_payload
            self._tier_client.write_fn = self._upload_page
        if config.warmup:
            self.warmup()

    def _build_tier_fns(self) -> None:
        """Jitted device I/O for the spill tiers: a one-page device->host
        read (quantize-on-spill under a bf16/f32 pool — the same int8 +
        per-(layer, kv-head) running-max scheme the resident int8 mode
        uses; an int8 pool spills its resident bytes + scales verbatim,
        so its T1/T2 round trip is bit-exact) and the inverse host->device
        upload (dequantize-on-restore for full-precision pools). Warmup
        exercises both so a first spill/restore mid-traffic never
        compiles on the serving path."""
        from .quantize import kv_dequantize, kv_int8_scale, kv_quantize

        if self.config.kv_quant == "int8":
            def read(kv, idx):
                return (kv.k_pages[:, idx], kv.v_pages[:, idx],
                        kv.k_scales[:, idx].astype(jnp.float32),
                        kv.v_scales[:, idx].astype(jnp.float32))

            def write(kv, idx, k, v, ks, vs):
                return kv._replace(
                    k_pages=kv.k_pages.at[:, idx].set(k),
                    v_pages=kv.v_pages.at[:, idx].set(v),
                    k_scales=kv.k_scales.at[:, idx].set(
                        ks.astype(kv.k_scales.dtype)),
                    v_scales=kv.v_scales.at[:, idx].set(
                        vs.astype(kv.v_scales.dtype)))
        elif self.config.tier_spill_quant == "":
            # resident-precision spill (tier_spill_quant=""): payloads
            # carry the page values as float32 (a lossless container for
            # bf16/f32 residents), so the round trip is byte-identical
            # at 2-4x the tier footprint of int8
            def read(kv, idx):
                scales = jnp.ones(
                    (kv.k_pages.shape[0], kv.k_pages.shape[3]), jnp.float32)
                return (kv.k_pages[:, idx].astype(jnp.float32),
                        kv.v_pages[:, idx].astype(jnp.float32),
                        scales, scales)

            def write(kv, idx, k, v, ks, vs):
                dt = kv.k_pages.dtype
                return kv._replace(
                    k_pages=kv.k_pages.at[:, idx].set(k.astype(dt)),
                    v_pages=kv.v_pages.at[:, idx].set(v.astype(dt)))
        else:
            def _quant(page):  # [L, page, KV, hd] -> (int8, [L, KV] scales)
                amax = jnp.max(jnp.abs(page.astype(jnp.float32)),
                               axis=(1, 3))
                scales = kv_int8_scale(amax)
                return (kv_quantize(page, scales[:, None, :, None]),
                        scales.astype(jnp.float32))

            def read(kv, idx):
                kq, ks = _quant(kv.k_pages[:, idx])
                vq, vs = _quant(kv.v_pages[:, idx])
                return kq, vq, ks, vs

            def write(kv, idx, k, v, ks, vs):
                dt = kv.k_pages.dtype
                return kv._replace(
                    k_pages=kv.k_pages.at[:, idx].set(
                        kv_dequantize(k, ks[:, None, :, None], dt)),
                    v_pages=kv.v_pages.at[:, idx].set(
                        kv_dequantize(v, vs[:, None, :, None], dt)))

        self._tier_read_fn = jax.jit(read)
        self._tier_write_fn = jax.jit(write, donate_argnames=("kv",))

    def _read_page_payload(self, page: int):
        """Device->host read of one prefix page for spilling. Dispatch
        thread only; runs at eviction time (admission/grow under page
        pressure), and the payload must leave HBM before the page's new
        tenant overwrites it."""
        from .kv.tiers import SpilledPage
        out = self._tier_read_fn(self.kv, jnp.asarray(page, jnp.int32))
        k, v, ks, vs = jax.device_get(out)  # lint: allow[host-sync-in-hot-path] spill-on-evict: the evicted page's bytes must be read before its new tenant overwrites them
        return SpilledPage(chunk=(), parent=b"", k=np.asarray(k),
                           v=np.asarray(v), k_scales=np.asarray(ks),
                           v_scales=np.asarray(vs))

    def _upload_page(self, page: int, payload) -> None:
        """Host->device upload of a restored page into this replica's
        pool (fetch-on-miss inside the admission allocate path; dispatch
        thread, pipeline already drained by the admission barrier)."""
        # np.asarray normalizes pinned-host payloads too: every call sees
        # the same (shape, dtype, uncommitted-numpy) signature, so the
        # warmup-compiled executable serves all of them (zero mid-traffic
        # compiles — the pool wedge monitor depends on that invariant)
        self.kv = self._tier_write_fn(
            self.kv, jnp.asarray(page, jnp.int32),
            np.asarray(payload.k), np.asarray(payload.v),
            np.asarray(payload.k_scales), np.asarray(payload.v_scales))

    def _init_kv(self) -> None:
        """(Re)build the KV pool + allocator on the mesh — used at
        construction and by crash recovery (a fault inside a jitted call
        may have consumed the donated kv buffers).

        ``config.num_pages`` is a BYTE budget denominated in engine-dtype
        pages: under ``kv_quant="int8"`` the same bytes hold ~2x the
        pages (1 byte/element + a per-page scale sliver), so the pool and
        allocator are sized by the converted, dtype-aware page count."""
        config = self.config
        max_pages_per_slot = config.max_seq_len // config.page_size
        from .kv import kv_page_bytes, num_pages_for_budget
        from .parallel.sharding import (kv_pages_sharding, kv_scales_sharding,
                                        logical_to_sharding)
        # bytes one page costs under the ACTIVE storage mode (gauge unit)
        self._kv_page_bytes = kv_page_bytes(
            self.model_config, config.page_size, self._kv_dtype,
            config.kv_quant)
        if config.kv_quant:
            budget = config.num_pages * kv_page_bytes(
                self.model_config, config.page_size, self._kv_dtype)
            self.num_kv_pages = num_pages_for_budget(
                self.model_config, config.page_size, budget,
                self._kv_dtype, config.kv_quant)
        else:
            self.num_kv_pages = config.num_pages
        with self.mesh:
            # kv_logical is the single source of the state's structure;
            # the page/scale rules route through the divisibility-aware
            # helpers (kv heads that don't divide the TP degree replicate)
            n_kv = self.model_config.n_kv_heads

            def to_sharding(name: str):
                if name == "kv_pages":
                    return kv_pages_sharding(self.mesh, n_kv)
                if name == "kv_scales":
                    return kv_scales_sharding(self.mesh, n_kv)
                return logical_to_sharding(name, self.mesh)

            kv_shardings = jax.tree.map(to_sharding,
                                        kv_logical(config.kv_quant))
            kv_init = jax.jit(partial(
                init_kv_state, self.model_config, self.num_kv_pages,
                config.page_size, config.max_batch, max_pages_per_slot,
                dtype=self._kv_dtype, quant=config.kv_quant),
                out_shardings=kv_shardings)
            self.kv = kv_init()
        if self._tier_client is not None:
            # a rebuilt pool (crash restart, reload) invalidates every
            # resident page — stale HBM locations in the pool index would
            # mis-route until they aged out
            self._tier_client.drop_replica()
        # the fresh allocator's tier counters restart at zero: the delta
        # snapshot must too, or post-rebuild hits are swallowed until the
        # new totals pass the old ones (counters would silently flatline)
        self._tier_hits_exported.clear()
        self.allocator = PageAllocator(self.num_kv_pages, config.page_size,
                                       config.max_batch, max_pages_per_slot,
                                       tiers=self._tier_client)

    def _ctx_buckets(self) -> list[int]:
        """The page-width buckets decode compiles for: powers of two from
        4 pages up to (and always including) the full table width."""
        max_pages = self.config.max_seq_len // self.config.page_size
        buckets = []
        pages = 4
        while pages < max_pages:
            buckets.append(pages)
            pages *= 2
        buckets.append(max_pages)
        return buckets

    def _ctx_bucket_for(self, max_tokens_needed: int) -> int:
        pages_needed = (max_tokens_needed + self.config.page_size - 1) \
            // self.config.page_size
        for bucket in self._ctx_buckets():
            if bucket >= pages_needed:
                return bucket
        return self._ctx_buckets()[-1]

    def _batch_buckets(self) -> list[int]:
        """Decode batch-width buckets: powers of two from 8 (or max_batch
        if smaller) up to max_batch. Decode dispatches size their arrays
        by the ACTIVE slot ceiling, not configured capacity — with slot
        compaction (below) a half-idle engine stops paying attention and
        sampling FLOPs for empty slots."""
        buckets = []
        width = min(8, self.config.max_batch)
        while width < self.config.max_batch:
            buckets.append(width)
            width *= 2
        buckets.append(self.config.max_batch)
        return buckets

    def _batch_bucket_for(self, active_ceiling: int) -> int:
        for bucket in self._batch_buckets():
            if bucket >= active_ceiling:
                return bucket
        return self.config.max_batch

    def _decode_fn(self, ctx_pages: int, batch: int | None = None,
                   k: int | None = None):
        # K is part of the executable identity (the scan length is baked
        # into the trace), so the cache keys on it: adaptive K switches
        # between PRE-COMPILED entries and can never compile mid-traffic
        k = self._k if k is None else int(k)
        key = (k, batch or self.config.max_batch, ctx_pages)
        fn = self._decode_fns.get(key)
        if fn is None:
            fn = jax.jit(partial(self._decode_and_sample,
                                 ctx_pages=ctx_pages, k=k),
                         donate_argnames=("kv",))
            self._decode_fns[key] = fn
        return fn

    def _decode_fb_fn(self, ctx_pages: int, batch: int | None = None,
                      k: int | None = None):
        k = self._k if k is None else int(k)
        key = (k, batch or self.config.max_batch, ctx_pages)
        fn = self._decode_fb_fns.get(key)
        if fn is None:
            fn = jax.jit(partial(self._decode_and_sample_fb,
                                 ctx_pages=ctx_pages, k=k),
                         donate_argnames=("kv",))
            self._decode_fb_fns[key] = fn
        return fn

    def _compact_slots(self) -> None:
        """Move the highest-slot requests into the lowest free slots so the
        active ceiling equals the active COUNT. Only block-table rows move
        (pages are slot-agnostic); the device table refreshes on the next
        _sync_tables. Runs between dispatches on the dispatch thread."""
        if not self._running:
            return
        # dense prefix already (the steady state at ANY constant load):
        # skip the sort + first frees-scan the old loop paid per decode
        # step before breaking (constant-factor, not the O(B^2) sparse
        # path — a checkerboard of finishes still pays up to B/2 moves)
        occupied = len(self._running) + len(self._chunking)
        ceiling = max(max(self._running),
                      max(self._chunking, default=-1)) + 1
        if ceiling == occupied:
            return
        for slot in sorted(self._running, reverse=True):
            frees = [s for s in range(slot)
                     if s not in self._running and s not in self._chunking]
            if not frees:
                break  # nothing lower is free: already compact
            target = frees[0]
            request = self._running.pop(slot)
            self.allocator.move_slot(slot, target)
            request.slot = target
            self._running[target] = request

    def _hist_ctx_buckets(self) -> list[int]:
        """Context-width buckets for the history/chunk prefill: one per
        prefill bucket (covers hist≈0 hits) plus the full table width —
        a small set so warmup can precompile it."""
        page = self.config.page_size
        max_pages = self.config.max_seq_len // page

        def ceil_pow2(n: int) -> int:
            p = 1
            while p < n:
                p *= 2
            return p

        buckets = {min(max_pages, max(4, ceil_pow2(b // page)))
                   for b in self.config.prefill_buckets}
        buckets.add(max_pages)
        return sorted(buckets)

    def _hist_ctx_for(self, max_tokens_needed: int) -> int:
        pages_needed = (max_tokens_needed + self.config.page_size - 1) \
            // self.config.page_size
        for bucket in self._hist_ctx_buckets():
            if bucket >= pages_needed:
                return bucket
        return self._hist_ctx_buckets()[-1]

    def _hist_fn(self, ctx_pages: int):
        fn = self._prefill_hist_fns.get(ctx_pages)
        if fn is None:
            fn = jax.jit(partial(self._prefill_hist_and_sample,
                                 ctx_pages=ctx_pages),
                         donate_argnames=("kv",))
            self._prefill_hist_fns[ctx_pages] = fn
        return fn

    def warmup(self, mode: str | None = None) -> None:
        """Precompile the shape grid before traffic. Safe pre-traffic:
        warmup rows use positions=-1, so KV writes land on the reserved
        trash page (page 0) and the allocator is untouched. Also what
        benches call so their timed region measures steady state, not XLA
        compile latency. Compiles here (and the cost-registry AOT
        captures) attribute to the tracker's "warmup" stage — only
        compiles on the dispatch thread count as the mid-traffic kind.

        ``mode`` (default config.warmup_mode):
        - "full": every prefill bucket x power-of-2 admission batch x
          history context bucket + the decode grid — zero mid-traffic
          compiles, but on a cold TPU cache the grid is ~dozens of shapes
          at 20-40 s each;
        - "fast": per bucket only B=1 and the admission cap, history only
          at the smallest + largest context bucket — boots in minutes on
          a cold chip; a cache miss mid-traffic costs one compile (which
          the persistent cache then keeps).
        """
        token = track_thread(self.compile_tracker, "warmup")
        try:
            self._warmup_impl(mode)
        finally:
            restore_thread(token)

    def _warmup_impl(self, mode: str | None = None) -> None:
        mode = mode or self.config.warmup_mode
        if mode not in ("full", "fast"):
            raise ValueError(f"warmup mode must be full|fast, got {mode!r}")
        started = time.monotonic()
        shapes = 0
        # cost-registry capture (roofline.py): AOT-lower each executable
        # once and record XLA's FLOPs / bytes-accessed so live step timing
        # can feed the mcpforge_llm_mfu / hbm_roofline_frac gauges. Always
        # BEFORE the warming call at the same shape: the call donates
        # self.kv, and lower() must see live buffers
        capture = self.config.cost_analysis
        hist_ctx = self._hist_ctx_buckets()
        if mode == "fast" and len(hist_ctx) > 2:
            hist_ctx = [hist_ctx[0], hist_ctx[-1]]
        with self.mesh:
            # sharding-settle call: the first jitted call canonicalizes
            # the kv pytree's output shardings (P(...,'model',...) from
            # kv_init becomes the executables' inferred placement), and
            # the pjit cache keys on input shardings — compiling the grid
            # against the PRE-transition kv would bake the init placement
            # into the first shape and recompile it at first traffic hit
            b0 = min(self.config.prefill_buckets)
            settle = SamplingParams(jnp.zeros((1,), jnp.float32),
                                    jnp.zeros((1,), jnp.int32),
                                    jnp.ones((1,), jnp.float32))
            first, self.kv = self._prefill_sample(
                self.params, self.kv,
                jnp.full((1, b0), self.tokenizer.pad_id, jnp.int32),
                jnp.full((1, b0), -1, jnp.int32),
                jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32),
                settle, jax.random.PRNGKey(0))
            first.block_until_ready()
            # utility-kernel warmup: the dispatch thread's first
            # jax.random.split UNPACK (a slice program) and _sync_tables'
            # sharded block-table device_put would otherwise be tiny
            # serving-stage compiles, polluting the zero-mid-traffic-
            # compile invariant the compile tracker guards. After the
            # settle call so the table sharding is the canonical one.
            _k1, _k2 = jax.random.split(self._rng)
            del _k1, _k2
            jax.device_put(self.allocator.tables(),
                           self.kv.block_tables.sharding)
            if self._tier_read_fn is not None:
                # spill/restore executables: compile both directions now
                # (against the trash page — contents are zeros either
                # way) so eviction-under-pressure and fetch-on-miss never
                # compile on the serving path
                idx = jnp.asarray(0, jnp.int32)
                spilled = jax.device_get(self._tier_read_fn(self.kv, idx))
                self.kv = self._tier_write_fn(self.kv, idx, *spilled)
                shapes += 1
            for bucket in self.config.prefill_buckets:
                use_sp = (self._prefill_sample_sp is not None
                          and bucket > self.config.sp_threshold)
                # _admit_batch pads to the pow-2 CEILING of the group size,
                # so compile through ceil_pow2(prefill_max_batch), not just
                # the powers of two at or below it
                cap = 1
                while cap < max(1, self.config.prefill_max_batch):
                    cap *= 2
                B = 1
                while B <= cap:
                    if mode == "fast" and B not in (1, cap):
                        B *= 2
                        continue
                    # the history fn serves prefix-cache hits AND chunk
                    # rounds (both batch to any B now) — compile it for
                    # every B whenever either path is reachable; one
                    # compile per context-width bucket (see _hist_fn)
                    hist_reachable = (
                        self.config.prefix_cache
                        or self.config.max_seq_len
                        > max(self.config.prefill_buckets))
                    if use_sp:
                        fns = [self._prefill_sample_sp]
                    else:
                        fns = [self._prefill_sample]
                        if hist_reachable:
                            fns.extend(self._hist_fn(cp) for cp in hist_ctx)
                    samp = SamplingParams(jnp.zeros((B,), jnp.float32),
                                          jnp.zeros((B,), jnp.int32),
                                          jnp.ones((B,), jnp.float32))
                    for fn in fns:
                        args = (self.params, self.kv,
                                jnp.full((B, bucket), self.tokenizer.pad_id,
                                         jnp.int32),
                                jnp.full((B, bucket), -1, jnp.int32),
                                jnp.zeros((B,), jnp.int32),
                                jnp.zeros((B,), jnp.int32),
                                samp, jax.random.PRNGKey(0))
                        if capture and B == 1 and fn is self._prefill_sample:
                            self.cost_registry.capture("prefill", B, bucket,
                                                       fn, *args)
                        first, self.kv = fn(*args)
                        first.block_until_ready()
                        shapes += 1
                    B *= 2
            B = self.config.max_batch
            samp = SamplingParams(jnp.zeros((B,), jnp.float32),
                                  jnp.zeros((B,), jnp.int32),
                                  jnp.ones((B,), jnp.float32))
            if self._verify_fns is not None:
                for ctx_pages in self._ctx_buckets():
                    args = (self.params, self.kv,
                            jnp.zeros((B, self.config.spec_k), jnp.int32),
                            jnp.full((B, self.config.spec_k), -1, jnp.int32),
                            jnp.arange(B, dtype=jnp.int32), samp,
                            jax.random.PRNGKey(0))
                    if capture:
                        self.cost_registry.capture(
                            "spec_verify", B, ctx_pages,
                            self._verify_fn(ctx_pages), *args)
                    block, self.kv = self._verify_fn(ctx_pages)(*args)
                    block.block_until_ready()
                    shapes += 1
            # plain decode is always live: spec engines fall back to it on
            # steps where no greedy row would draft (width-K verify would be
            # pure compute waste — round-2 ADVICE low). One compile per
            # (batch-width, context-width) bucket pair.
            # seq_lens=0: every slot is "inactive", writes masked to trash
            widths = (self._batch_buckets() if self.config.batch_buckets
                      else [self.config.max_batch])
            # the K ladder multiplies the grid: every (width, ctx, K rung)
            # triple compiles here so the controller's adaptive K only
            # ever lands on pre-warmed executables. With no ladder
            # configured this is exactly the static-K grid (one rung).
            k_rungs = self.config.k_rungs()
            for batch in widths:
                bsamp = SamplingParams(jnp.zeros((batch,), jnp.float32),
                                       jnp.zeros((batch,), jnp.int32),
                                       jnp.ones((batch,), jnp.float32))
                # super-step freeze inputs (values are irrelevant to the
                # compile — jit keys on shape/dtype): zero budgets, empty
                # stop table
                wbudget = jnp.zeros((batch,), jnp.int32)
                wstops = jnp.full((batch, self._STOP_TBL_WIDTH), -1,
                                  jnp.int32)
                for ctx_pages in self._ctx_buckets():
                    for k_rung in k_rungs:
                        # cost entries for non-default rungs carry the
                        # rung in the kind (FLOPs/bytes scale with K, so
                        # MFU after a K switch must divide by the right
                        # cost); the static rung keeps the bare kind the
                        # existing roofline consumers look up
                        suffix = "" if k_rung == self._k else f"@k{k_rung}"
                        args = (self.params, self.kv,
                                jnp.zeros((batch,), jnp.int32),
                                jnp.zeros((batch,), jnp.int32),
                                jnp.arange(batch, dtype=jnp.int32),
                                jnp.zeros((batch,), jnp.int32), wbudget,
                                wstops, bsamp, jax.random.PRNGKey(0))
                        if capture:
                            self.cost_registry.capture(
                                "decode" + suffix, batch, ctx_pages,
                                self._decode_fn(ctx_pages, batch, k_rung),
                                *args)
                        (block, _, _), self.kv = \
                            self._decode_fn(ctx_pages, batch, k_rung)(*args)
                        block.block_until_ready()
                        shapes += 1
                        if (self.config.decode_overlap
                                and self._verify_fns is None):
                            # the pipelined steady state runs the feedback
                            # variant; warm it alongside so overlap never
                            # compiles mid-traffic. Feed it the plain
                            # decode's OUTPUT block — at runtime the feed
                            # is always the previous step's on-device jit
                            # output, and the pjit cache keys on that
                            # committed sharding (a fresh jnp.zeros here
                            # would warm a cache entry traffic never hits)
                            fb_args = (self.params, self.kv, block,
                                       jnp.zeros((batch,), jnp.int32),
                                       jnp.arange(batch, dtype=jnp.int32),
                                       jnp.zeros((batch,), jnp.int32),
                                       wbudget, wstops, bsamp,
                                       jax.random.PRNGKey(0))
                            if capture:
                                self.cost_registry.capture(
                                    "decode_fb" + suffix, batch, ctx_pages,
                                    self._decode_fb_fn(ctx_pages, batch,
                                                       k_rung),
                                    *fb_args)
                            (block, _, _), self.kv = self._decode_fb_fn(
                                ctx_pages, batch, k_rung)(*fb_args)
                            block.block_until_ready()
                            shapes += 1
                self._warmed_widths.add(batch)
            self._warmed_k.update(k_rungs)
            if self.config.batch_buckets:
                # warmed posture: start at max (never slower than fixed
                # width; the first burst costs zero transitions) — the
                # warmed grid makes every later shrink compile-free. Any
                # pre-warmup shrink evidence is stale at the new width.
                self._batch_width = self.config.max_batch
                self._shrink_streak = 0
                self._shrink_peak = 0
        logger.info("tpu_local warmup: %d shapes compiled in %.1fs",
                    shapes, time.monotonic() - started)

    # ------------------------------------------------------------- device fns

    def _prefill_and_sample(self, params, kv, tokens, positions, slot_ids,
                            last_idx, sampling: SamplingParams, key,
                            sp: bool = False):
        """Batched prefill + on-device first-token sampling (same sampler and
        PRNG stream as decode — round-1 VERDICT weak #5). ``sp=True`` runs
        the sequence-parallel attention path for long prompts."""
        impl = self.config.sp_impl if sp else self.config.attn_impl
        # last_idx inside the forward: only those rows go through the lm
        # head — [B,S,V] f32 logits would be gigabytes at real vocab sizes
        logits, kv = prefill(params, self.model_config, tokens, positions, kv,
                             slot_ids, attn_impl=impl,
                             mesh=self.mesh if sp else None,
                             last_idx=last_idx)
        first = sample_tokens(logits, sampling, key)
        return first, kv

    def _prefill_hist_and_sample(self, params, kv, tokens, positions, slot_ids,
                                 last_idx, sampling: SamplingParams, key,
                                 ctx_pages: int | None = None):
        """Suffix prefill over cached prefix pages (prefix-cache hit path):
        same surface as _prefill_and_sample, but attention spans the slot's
        paged context up to the static ``ctx_pages`` bucket, so rows start
        at their history offset."""
        logits, kv = prefill_with_history(params, self.model_config, tokens,
                                          positions, kv, slot_ids,
                                          ctx_pages=ctx_pages,
                                          last_idx=last_idx)
        first = sample_tokens(logits, sampling, key)
        return first, kv

    def _verify_fn(self, ctx_pages: int):
        fn = self._verify_fns.get(ctx_pages)
        if fn is None:
            fn = jax.jit(partial(self._verify_and_sample,
                                 ctx_pages=ctx_pages),
                         donate_argnames=("kv",))
            self._verify_fns[ctx_pages] = fn
        return fn

    def _verify_and_sample(self, params, kv, tokens, positions, slot_ids,
                           sampling: SamplingParams, key,
                           ctx_pages: int | None = None):
        """Speculative verify: a [B, K] chunk (1 real token + K-1 drafts per
        row) through the gathered-history path, sampling at EVERY position.
        Position j's sample is the model's true next token given the chunk
        prefix up to j — the host accepts drafts while they agree. Returns
        ([B, K] sampled tokens, kv)."""
        logits, kv = prefill_with_history(params, self.model_config, tokens,
                                          positions, kv, slot_ids,
                                          ctx_pages=ctx_pages)
        B, K, V = logits.shape
        flat = logits.reshape(B * K, V)
        samp = SamplingParams(jnp.repeat(sampling.temperature, K),
                              jnp.repeat(sampling.top_k, K),
                              jnp.repeat(sampling.top_p, K))
        out = sample_tokens(flat, samp, key)
        return out.reshape(B, K), kv

    def _decode_and_sample(self, params, kv, tokens, positions, slot_ids,
                           seq_lens, budgets, stop_tbl,
                           sampling: SamplingParams, key,
                           ctx_pages: int | None = None,
                           k: int | None = None):
        """One decode SUPER-STEP: k = config.fused_steps decode iterations
        as a single jitted lax.scan — fused sampling, in-loop paged-KV
        append over pre-granted pages, and per-slot budget/EOS/stop
        masking so finished rows FREEZE on device instead of burning a
        host round-trip per token (the SnapStream-style token-loop
        fusion of ROADMAP item 1).

        ``budgets`` [B] int32 caps how many of the k sampled tokens are
        real per row (max_tokens remainder ∧ granted page capacity);
        ``stop_tbl`` [B, _STOP_TBL_WIDTH] int32 carries each row's EOS +
        stop ids (-1 padding, never a real token). A frozen row (EOS/stop
        sampled, or budget exhausted) stops writing KV and stops
        advancing positions/lens — so int8 page scales never creep on
        post-EOS garbage — while the fixed-shape compute rides along
        masked. The host stays authoritative at retire (_emit re-checks
        every finish condition), so a stop id beyond the static table
        width costs only wasted lookahead compute, never a wrong stream.

        Returns ((tokens [k, B], valid [k, B] bool, done [B] bool), kv):
        valid[j, b] marks a token the host should emit; done[b] is the
        device's end-of-stream verdict, retired in ONE readback."""
        # k is partial-bound by _decode_fn so the scan length is part of
        # the executable identity (adaptive K); the self._k fallback
        # serves direct (unjitted) callers in tests
        k = self._k if k is None else k
        # rows with work this dispatch (inactive slots — empty or
        # mid-chunk-prefill — never write; the mask below derives from
        # the INITIAL lens, not the in-scan incremented ones)
        active = seq_lens > 0

        def step(carry, xs):
            (step_tokens, step_positions, step_lens, done, prev_valid,
             step_kv) = carry
            j, step_key = xs
            # sub-step j writes the KV of its INPUT token — sampled at
            # j-1, or host/feedback-fed at j=0, always a real emitted
            # token — so the write mask trails validity by one sub-step,
            # and a done row never writes its terminal token's KV
            # (exactly the serial engine, which never re-dispatches a
            # finished request)
            logits, step_kv = decode_step(params, self.model_config,
                                          step_tokens, step_positions,
                                          step_kv, slot_ids, step_lens,
                                          ctx_pages=ctx_pages,
                                          write_mask=(active & prev_valid
                                                      & ~done))
            sampled = sample_tokens(logits, sampling, step_key)
            valid = active & ~done & (j < budgets)
            hit_stop = jnp.any(sampled[:, None] == stop_tbl, axis=1)
            done = done | (valid & hit_stop)
            next_positions = jnp.where(valid, step_positions + 1,
                                       step_positions)
            next_lens = jnp.where(valid, step_lens + 1, step_lens)
            return ((sampled, next_positions, next_lens, done, valid,
                     step_kv), (sampled, valid))

        B = tokens.shape[0]
        keys = jax.random.split(key, k)
        carry0 = (tokens, positions, seq_lens,
                  jnp.zeros((B,), dtype=bool), active, kv)
        (_, _, _, done, _, kv), (all_tokens, all_valid) = jax.lax.scan(
            step, carry0, (jnp.arange(k), keys))
        return (all_tokens, all_valid, done), kv

    def _decode_and_sample_fb(self, params, kv, prev_block, positions,
                              slot_ids, seq_lens, budgets, stop_tbl,
                              sampling: SamplingParams, key,
                              ctx_pages: int | None = None,
                              k: int | None = None):
        """Device-token-feedback decode (overlapped pipeline steady state):
        the input token is the PREVIOUS dispatch's last sampled token —
        row k-1 of its [k, B] block — which never left the device, so the
        host feeds no tokens at all between barriers. prev_block is NOT
        donated: the retire path still reads it back for emission while
        this step executes."""
        return self._decode_and_sample(params, kv, prev_block[-1], positions,
                                       slot_ids, seq_lens, budgets, stop_tbl,
                                       sampling, key, ctx_pages=ctx_pages,
                                       k=k)

    # --------------------------------------------------------------- lifecycle

    async def start(self) -> None:
        if self._started:
            return
        if self._thread is not None and self._thread.is_alive():
            # a wedged thread from a failed stop() still owns kv/_running;
            # a second dispatch thread would corrupt both
            raise RuntimeError("previous dispatch thread still running")
        self._started = True
        self._killed = False
        self._loop = asyncio.get_running_loop()
        # fresh events per thread: a wedged old thread keeps seeing its own
        # (set) events and can never be revived by a later start()
        self._stop_event = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._device_loop,
                                        name="tpu-engine-dispatch", daemon=True)
        self._thread.start()

    async def stop(self) -> None:
        if not self._started:
            self._close_owned_tiers()
            return
        self._started = False
        self._stop_event.set()
        self._wake.set()  # unblock an idle dispatch thread immediately
        thread = self._thread
        if thread is not None:
            await asyncio.to_thread(thread.join, 30.0)
            if thread.is_alive():
                logger.error("dispatch thread failed to stop within 30s; "
                             "engine restart refused until it exits")
                return  # keep self._thread so start() refuses a double-start
        self._thread = None
        self._close_owned_tiers()

    def spill_prefix_pages(self) -> int:
        """Spill-on-drain: copy every ref==0 resident prefix page into
        the (pool-shared) spill store so a rebuilt engine fetches the
        corpus on miss instead of losing it with the HBM pool
        (docs/resilience.md; ROADMAP item 3's remaining half). Caller
        contract: the dispatch thread must be QUIESCED (stop() joined —
        the pool's reload path) — this reads device pages from the
        calling thread, which is only legal with no concurrent device
        mutation. Runs under the engine mesh so the warmup-compiled
        tier-read executable serves every page (no fresh compiles)."""
        client = self._tier_client
        if client is None or not client.active:
            return 0
        with self.mesh:
            spilled = self.allocator.spill_resident_prefix()
        if spilled:
            logger.info("tpu_local: spilled %d resident prefix page(s) "
                        "to the tier store before teardown", spilled)
        return spilled

    def _close_owned_tiers(self) -> None:
        """Shut down a standalone engine's private spill store (its
        write-behind worker + tempdir). Pool-shared stores are closed by
        the pool, which outlives every replica engine."""
        if self._owned_tier_store is not None:
            self._owned_tier_store.close()
            self._owned_tier_store = None
            if self._tier_client is not None:
                self._tier_client.store = None

    def kill(self) -> None:
        """Signal the dispatch thread to stop WITHOUT joining it.

        Pool failover path: a wedged device call can hold the thread for
        minutes, and the pool must not wait on it before requeueing the
        replica's in-flight requests onto healthy replicas. After kill()
        the engine refuses new submissions (_check_alive) and a revived
        zombie thread exits at its next loop check; any tokens it emits
        land in streams the pool has already abandoned."""
        self._killed = True
        self._started = False
        self._stop_event.set()
        self._wake.set()

    def heartbeat_age(self) -> float:
        """Seconds since the dispatch loop last started an iteration —
        the pool health monitor's wedge signal (a healthy loop beats at
        >=20 Hz; a thread stuck inside a device call stops beating)."""
        return max(0.0, time.monotonic() - self._heartbeat_ts)

    def last_step_age(self) -> float | None:
        """Seconds since the last device dispatch retired (step-ring
        staleness); None before the first step."""
        if self._last_step_done_ts is None:
            return None
        return max(0.0, time.monotonic() - self._last_step_done_ts)

    def dispatch_alive(self) -> bool:
        """True while the dispatch thread is running (started and the
        thread object is live) — the crash half of the health check."""
        return bool(self._started and self._thread is not None
                    and self._thread.is_alive())

    @property
    def warmed(self) -> bool:
        """True once warmup compiled at least one decode width. A warmed
        engine has no first-dispatch compile left, so the pool health
        monitor may read a stale heartbeat as a wedge even before the
        first traffic step retires."""
        return bool(self._warmed_widths)

    def request_cancel(self, request_id: str) -> bool:
        """Thread-safe: ask the dispatch thread to terminate a generation.

        Returns True when the id matches a request the engine currently
        holds (pending, chunk-prefilling, or decoding); the stream then
        receives its terminal like any other finish, with
        ``finish_reason="cancelled"``. A request still in the submit
        handoff queue is not yet visible here (the window is one
        dispatch-loop iteration) — callers get False and may retry.
        The id set is consumed by ``_apply_cancels`` on the dispatch
        thread; this side only reads the request tables (snapshots under
        the GIL) and mutates under the cancel lock."""
        for _ in range(8):
            try:
                known = any(
                    r.request_id == request_id
                    for bucket in (list(self._pending),
                                   list(self._chunking.values()),
                                   list(self._running.values()))
                    for r in bucket)
                break
            except RuntimeError:
                # the dispatch thread mutated a table mid-snapshot; the
                # tables are small and mutate once per step — retry
                continue
        else:
            known = True  # can't prove absence: mark anyway (an unmatched
            #               id is dropped at the next _apply_cancels sweep)
        if not known:
            return False
        with self._cancel_lock:
            self._cancels.add(request_id)
        self._wake.set()
        return True

    # ------------------------------------------------------------- submission

    async def submit(self, request: GenRequest) -> GenRequest:
        self._check_alive()
        self.stats.requests += 1
        self.stats.prompt_tokens += len(request.prompt_ids)
        if self.ledger is not None:
            # same site as stats.prompt_tokens — the per-tenant slices
            # must sum to the untagged total (conservation gate)
            self.ledger.add(request.tenant, requests=1,
                            prompt_tokens=len(request.prompt_ids))
        while True:
            try:
                self._work.put_nowait(request)
                self._wake.set()  # wake an idle dispatch thread
                break
            except queue.Full:  # backpressure without blocking the loop
                await asyncio.sleep(0.005)
                # recheck AFTER the await, with no further await before
                # the retry put: the pool's health sweep can kill this
                # engine during the sleep (kill + _fail_outstanding drain
                # the queue), and a put that then succeeds would register
                # work on a dead replica no sweep will ever requeue
                self._check_alive()
        self.stats.queue_depth = self._work.qsize() + len(self._pending)
        if self.metrics is not None:
            self.metrics.llm_queue_depth.labels(
                replica=self.config.replica_id).set(self.stats.queue_depth)
        return request

    def _check_alive(self) -> None:
        """Fail fast instead of queueing work no consumer will ever drain
        (a crashed dispatch thread must not hang every later request).
        A kill()ed engine refuses outright: kill clears _started without
        joining, so the liveness clause alone would wave submissions into
        a queue nothing drains — exactly the pool race where a submit
        awaiting backpressure resumes after the health sweep killed the
        replica."""
        if self._killed:
            raise RuntimeError("tpu_local engine was killed (failover)")
        if self._started and (self._thread is None
                              or not self._thread.is_alive()):
            raise RuntimeError("tpu_local engine dispatch thread is not running")

    async def generate(self, prompt_ids: list[int], **kwargs) -> AsyncIterator[int]:
        """Submit and yield token ids as they decode."""
        from ..utils.ids import new_id
        request = GenRequest(request_id=new_id(), prompt_ids=prompt_ids, **kwargs)
        await self.submit(request)
        while True:
            token = await request.stream.get()
            if token is None:
                break
            yield token

    # --------------------------------------------------------- dispatch thread

    def _device_loop(self) -> None:  # lint: runs-on[dispatch]  # lint: hot-path
        """Owns every jax call + device sync. Never touched by the asyncio
        loop; results hop back via loop.call_soon_threadsafe (one flush
        per step, not one wakeup per token).

        With ``decode_overlap`` the decode phase runs a depth-2 pipeline:
        one decode step is always in flight, fed by the previous step's
        on-device tokens, and results emit one step behind. Everything
        that re-homes slots or pages (admission, chunk completion, width
        changes, stop/crash) first drains the pipeline so token streams
        stay byte-identical to the serial path."""
        crashed = False
        overlap = self.config.decode_overlap and self._verify_fns is None
        # every XLA compile on this thread is a mid-traffic ("serving")
        # compile — the thing warmup exists to prevent; count + time it
        compile_token = track_thread(self.compile_tracker, "serving")
        try:
            # the pjit dispatch cache keys on the AMBIENT mesh context, not
            # just input shardings: warmup() compiles under ``with
            # self.mesh`` so dispatch must run under it too, or every
            # warmed shape recompiles on its first traffic hit (observed:
            # seconds-long "mid-traffic" compiles on shapes warmup had
            # already built, which reads as a wedge to the pool's
            # heartbeat monitor)
            with self.mesh:
                while not self._stop_event.is_set():
                    self._heartbeat_ts = time.monotonic()
                    # fault point engine.dispatch (docs/resilience.md),
                    # scope = replica id: latency = a slow replica (the
                    # chaos matrix's slow-replica arm — heartbeat still
                    # beats, work just drags), error = a dispatch-thread
                    # crash through the REAL crash/failover path below.
                    # Unarmed (the default): one dict miss per iteration.
                    fault = fault_point("engine.dispatch",
                                        scope=self.config.replica_id)
                    if fault is not None:
                        fault.apply()
                    did_work = False
                    # drain the bounded handoff queue EVERY iteration (as the
                    # old unconditional _admit_batch did): the backlog lives
                    # in the unbounded _pending, where the priority sort and
                    # within-class FIFO apply — even while all slots are busy
                    self._drain_work()
                    if self._cancels:
                        self._apply_cancels()
                        did_work = True
                    if self._pending_knobs:
                        # controller knob moves land HERE — before
                        # admission/decode, draining the overlap pipeline
                        # when K changes, so every move is a clean drain
                        # barrier (greedy parity holds)
                        self._apply_knobs()
                        did_work = True
                    if self._pending_exports:
                        # pool KV-migration exports land at the same
                        # barrier: the pipeline drains first so every
                        # exported page holds fully retired prefill state
                        self._apply_exports()
                        did_work = True
                    incoming = bool(self._pending)
                    occupied = len(self._running) + len(self._chunking)
                    can_admit = incoming and occupied < self.config.max_batch
                    if self._inflight is not None and (
                            can_admit or self._chunking or not self._running):
                        # drain barriers: admission and chunk completion move
                        # requests into slots/pages the in-flight lookahead
                        # indexes; an empty running set means the lookahead
                        # holds only rows that already finished
                        self._drain_pipeline()
                        did_work = True
                    if can_admit:
                        did_work = self._admit_batch() or did_work
                    if self._chunking:
                        self._chunk_round()
                        did_work = True
                    if self._running:
                        if (self._verify_fns is not None
                                and self._spec_enabled
                                and self._any_would_draft()):
                            self._spec_step_all()
                        elif overlap:
                            self._decode_step_overlapped()
                        else:
                            self._decode_step_all()
                        did_work = True
                    self.stats.queue_depth = self._work.qsize() + len(self._pending)
                    self.stats.chunking = len(self._chunking)
                    self._flush_emits()
                    if not did_work:
                        self._wait_for_work()
                # clean stop: already-sampled in-flight tokens reach their
                # streams before the cancel sweep below
                self._drain_pipeline()
        except Exception:
            crashed = True
            # device state (and the in-flight block) is suspect after a
            # fault inside a jitted call; never try to read it back
            self._inflight = None
            logger.exception("tpu_local dispatch thread crashed")
        finally:
            self._flush_emits()
            try:
                if (crashed and self.config.auto_restart
                        and not self._stop_event.is_set()
                        and self.stats.engine_restarts
                        < self.config.auto_restart_max):
                    # still registered: crash-recovery compiles (fresh
                    # _init_kv jit wrappers) are mid-traffic "serving"
                    # compiles and must not escape attribution
                    self._restart_after_crash()
                else:
                    # a dead thread must not strand consumers on
                    # stream.get()
                    self._fail_outstanding(
                        "cancelled" if self._stop_event.is_set()
                        else "error")
            finally:
                restore_thread(compile_token)

    def _restart_after_crash(self) -> None:
        """Device-fault recovery (SURVEY §5.3: "TPU driver errors → engine
        restart + request re-queue"). Runs on the DYING dispatch thread:

        - mid-stream requests fail (tokens already emitted; a silent retry
          would duplicate output) — the gateway's retry layer owns those;
        - PENDING requests (no tokens yet) re-queue and survive;
        - the KV pool + allocator are REBUILT: a crash inside a jitted call
          may have consumed the donated kv buffers, so resident state is
          untrustworthy (params are never donated and stay);
        - a fresh dispatch thread takes over. Bounded by auto_restart_max.
        """
        self.stats.engine_restarts += 1
        logger.warning("tpu_local: restarting engine after crash (%d/%d)",
                       self.stats.engine_restarts, self.config.auto_restart_max)
        self._inflight = None  # sampled-but-unfetched tokens die with the kv
        self._drain_work()
        requeue = list(self._pending)
        self._pending.clear()
        # mid-chunk requests have emitted NOTHING — they re-queue safely
        # (their pages die with the KV rebuild below)
        requeue.extend(self._chunking.values())
        self._chunking.clear()
        for request in list(self._running.values()):
            if request.finish_reason is None:
                request.finish_reason = "error"
            # crash-killed requests are the ones an operator hunts for in
            # traces — emit their ERROR llm.decode span like every other
            # termination path does
            self._observe_finish(request)
            self._running.pop(request.slot, None)
            self._post_tokens(request, [], done=True)
        # flush BEFORE the replacement thread can exist: two dispatch
        # threads must never race on the unlocked emit buffer
        self._flush_emits()
        try:
            self._init_kv()
            for request in requeue:  # fresh admission state
                request.slot = -1
                request.bucket = -1
                request.hist = 0
                request.chunked = False
                request.chunk_pos = 0
                self._pending.append(request)
            requeue = []
            replacement = threading.Thread(target=self._device_loop,
                                           name="tpu-engine-dispatch",
                                           daemon=True)
            # start BEFORE publishing: a concurrent stop() must never join
            # a not-yet-started thread (the dying thread keeps
            # _check_alive() true until this method returns)
            replacement.start()
            self._thread = replacement
        except Exception:
            logger.exception("tpu_local: crash recovery failed; engine down")
            # fail EVERYTHING reachable — the requeue list, _pending, and
            # anything submitted into _work while the rebuild ran — so no
            # consumer is stranded on stream.get()
            self._pending.extendleft(reversed(requeue))
            self._fail_outstanding("error")

    def _fail_outstanding(self, reason: str) -> None:
        self._inflight = None
        self._drain_work()
        with self._export_lock:
            exports, self._pending_exports = self._pending_exports, []
        for _ids, fut in exports:
            # a migration awaiting this export degrades to decode-in-
            # place (or a plain requeue) instead of hanging forever
            if not fut.done():
                fut.set_exception(RuntimeError(
                    f"engine dispatch thread died ({reason}) before the "
                    f"chain export ran"))
        for request in list(self._running.values()):
            if request.finish_reason is None:
                request.finish_reason = reason
            # trace correlation (observability/logging.py): the incident
            # line for a generation killed mid-decode joins to the OTel
            # trace of the request it truncated
            logger.warning(
                "tpu_local: failing in-flight request %s (%s) after %d "
                "generated token(s)", request.request_id,
                request.finish_reason, len(request.generated),
                extra=trace_extra(request.trace_ctx))
            self._finish(request)
        for request in list(self._chunking.values()):
            self._chunking.pop(request.slot, None)
            self.allocator.free_slot(request.slot)
            if request.finish_reason is None:
                request.finish_reason = reason
            self._post_tokens(request, [], done=True)
        while self._pending:
            request = self._pending.popleft()
            if request.finish_reason is None:
                request.finish_reason = reason
            self._post_tokens(request, [], done=True)
        self._flush_emits()

    def _apply_cancels(self) -> None:  # lint: runs-on[dispatch]
        """Terminate the generations request_cancel() marked. Runs at the
        top of the dispatch iteration; a cancelled RUNNING slot re-homes
        pages, so the overlap pipeline drains first (same barrier as
        admission/stop). Ids that matched nothing (the request finished
        between the mark and this sweep) are dropped — cancelling a
        completed request is a no-op by contract."""
        with self._cancel_lock:
            ids, self._cancels = self._cancels, set()
        if not ids:
            return
        if self._inflight is not None and any(
                r.request_id in ids for r in self._running.values()):
            self._drain_pipeline()
        for request in list(self._running.values()):
            if request.request_id in ids and request.finish_reason is None:
                request.finish_reason = "cancelled"
                self._finish(request)
        for request in list(self._chunking.values()):
            if request.request_id in ids and request.finish_reason is None:
                self._chunking.pop(request.slot, None)
                self.allocator.free_slot(request.slot)
                request.finish_reason = "cancelled"
                self._post_tokens(request, [], done=True)
        kept: deque[GenRequest] = deque()
        for request in self._pending:
            if request.request_id in ids and request.finish_reason is None:
                request.finish_reason = "cancelled"
                self._post_tokens(request, [], done=True)
            else:
                kept.append(request)
        self._pending = kept

    def request_knobs(self, *, superstep: int | None = None,
                      spec_enabled: bool | None = None,
                      width_floor: int | None = None) -> dict[str, bool]:
        """Stage serving-knob changes for the dispatch thread to land at
        its next drain barrier (the controller's actuation surface —
        same handoff pattern as request_cancel). Validation happens HERE,
        against the warmed grid, so a rejected value never reaches the
        loop: adaptive K may only select warmed ladder rungs (zero
        mid-traffic XLA compiles by construction), toggling spec needs a
        spec-built engine, and a width floor must be a warmed bucket
        width. Returns {knob: accepted} so the caller can audit refusals.
        Thread-safe; callable from any thread."""
        accepted: dict[str, bool] = {}
        staged: dict[str, Any] = {}
        if superstep is not None:
            k = int(superstep)
            ok = k >= 1 and (k in self._warmed_k or any(
                key[0] == k for key in self._decode_fns))
            if self._verify_fns is not None and k > 1:
                ok = False  # spec engines can't take K>1 (ctor exclusivity)
            accepted["superstep"] = ok
            if ok:
                staged["superstep"] = k
        if spec_enabled is not None:
            ok = self._verify_fns is not None
            accepted["spec_enabled"] = ok
            if ok:
                staged["spec_enabled"] = bool(spec_enabled)
        if width_floor is not None:
            w = int(width_floor)
            ok = w == 0 or (self.config.batch_buckets
                            and w in self._warmed_widths)
            accepted["width_floor"] = ok
            if ok:
                staged["width_floor"] = min(w, self.config.max_batch)
        if staged:
            with self._knob_lock:
                self._pending_knobs.update(staged)
            self._wake.set()
        return accepted

    def _apply_knobs(self) -> None:  # lint: runs-on[dispatch]
        """Land staged knob moves on the dispatch thread. A superstep
        change drains the overlap pipeline first: the in-flight lookahead
        was dispatched at the OLD K and its retire accounting carries its
        own ``k``; after the drain the switch is a clean barrier and the
        next dispatch picks the pre-warmed executable for the new K.
        Spec/width-floor moves are pure host-side posture flips."""
        with self._knob_lock:
            knobs, self._pending_knobs = self._pending_knobs, {}
        if not knobs:
            return
        new_k = knobs.get("superstep")
        if new_k is not None and new_k != self._k:
            if self._inflight is not None:
                self._drain_pipeline()
            self._k = int(new_k)
        if "spec_enabled" in knobs:
            self._spec_enabled = bool(knobs["spec_enabled"])
        if "width_floor" in knobs:
            self._width_floor = int(knobs["width_floor"])

    def request_chain_export(self, prompt_ids: list[int]) -> "Future[int]":
        """Stage a KV chain export for the dispatch thread (the pool's
        prefill->decode migration seam, docs/disaggregation.md): the
        prompt's registered full-page chain spills — as a COPY — into
        the pool-shared tier store at the next drain barrier. Same
        handoff pattern as request_knobs: stage under the lock, wake the
        loop, let the only thread allowed to touch device state do the
        reads. Returns a future resolving to the number of pages now
        present in the store; it fails if the engine dies first.
        Thread-safe; callable from any thread."""
        self._check_alive()
        fut: "Future[int]" = Future()
        with self._export_lock:
            self._pending_exports.append((tuple(prompt_ids), fut))
        self._wake.set()
        return fut

    def _apply_exports(self) -> None:  # lint: runs-on[dispatch]
        """Land staged chain exports on the dispatch thread, draining the
        overlap pipeline first — the prefill leg's retire must be fully
        applied to the pages before their bytes are read off the device."""
        with self._export_lock:
            exports, self._pending_exports = self._pending_exports, []
        if not exports:
            return
        if self._inflight is not None:
            self._drain_pipeline()
        for prompt_ids, fut in exports:
            if fut.cancelled():
                continue
            try:
                fut.set_result(self.allocator.spill_chain(list(prompt_ids)))
            except Exception as exc:  # device read failed: the POOL
                fut.set_exception(exc)  # degrades; the engine lives on

    def knob_state(self) -> dict[str, Any]:
        """Live serving-knob posture (the /admin/controller "now" row and
        the bench harness's zero-compile assertion read this)."""
        return {
            "superstep": self._k,
            "spec_built": self._verify_fns is not None,
            "spec_enabled": bool(self._verify_fns is not None
                                 and self._spec_enabled),
            "width_floor": self._width_floor,
            "batch_width": self._batch_width,
            "warmed_k": sorted(self._warmed_k),
            "warmed_widths": sorted(self._warmed_widths),
        }

    def _wait_for_work(self) -> None:
        """Idle path: block on the submit-side wake event instead of a
        1 ms sleep poll — submit latency drops to the event signal and
        idle CPU to ~zero. clear-then-check closes the race where a
        request lands between the caller's emptiness check and the wait;
        the timeout is a safety net for states the event cannot signal
        (e.g. page-bound pending work that must periodically re-probe)."""
        self._wake.clear()
        if self._work.qsize() or self._stop_event.is_set():
            return
        self._wake.wait(0.05)

    def _drain_work(self) -> None:
        while True:
            try:
                self._pending.append(self._work.get_nowait())
            except queue.Empty:
                return

    def _bucket_for(self, length: int) -> int | None:
        for bucket in sorted(self.config.prefill_buckets):
            if length <= bucket:
                return bucket
        return None

    def _assign_bucket(self, request: GenRequest) -> int:
        """Request's prefill bucket (0 = fits no bucket). A prefix-cache
        hit buckets by SUFFIX length, so a 2048-token prompt with a cached
        1920-token template prefix prefills in the smallest bucket. The
        probe is READ-ONLY — no page references are taken here, so pending
        requests never pin cache pages (a pinned-pages cycle between two
        queued requests would deadlock admission); the real match happens
        at admission and is re-verified against this probe. SP buckets
        never run the history path (the shard_map prefill has no
        paged-history support) — those fall back to a dense full prefill."""
        if request.bucket != -1:
            return request.bucket
        request.chunked = False  # recomputed below on every (re-)probe
        ids = request.prompt_ids
        if len(ids) + 1 > self.config.max_seq_len:
            # the prompt plus >=1 generated token must fit the block table;
            # past it, page indices clamp and silently overwrite (and, with
            # the prefix cache, publish) the slot's last page
            request.bucket = 0
            return 0
        if self.config.prefix_cache:
            hist = self.allocator.probe_prefix(ids)
            # a hit only pays when the suffix lands a STRICTLY smaller
            # bucket than dense prefill of the whole prompt: the history
            # path costs more per padded token (gathered context
            # attention), so "saving" 16 cached tokens of a 90-token
            # prompt while still padding to the same bucket is a net loss
            # on every backend
            if hist:
                dense_bucket = self._bucket_for(len(ids))
                bucket = self._bucket_for(len(ids) - hist)
                if (dense_bucket is not None and bucket is not None
                        and bucket >= dense_bucket):
                    hist = 0
            if hist:
                bucket = self._bucket_for(len(ids) - hist)
                sp_bucket = (self._prefill_sample_sp is not None
                             and bucket is not None
                             and bucket > self.config.sp_threshold)
                if bucket is not None and not sp_bucket:
                    request.hist = hist
                    request.bucket = bucket
                    return bucket
                if bucket is None:
                    # the suffix alone exceeds every bucket: chunk it, but
                    # FROM the cached prefix — the chunk loop starts at hist
                    request.hist = hist
                    request.chunked = True
                    request.bucket = max(self.config.prefill_buckets)
                    return request.bucket
        request.hist = 0
        bucket = self._bucket_for(len(ids))
        if bucket is None:
            # longer than every bucket but fits the block table: chunked
            # prefill — bucket-sized chunks through the history path, each
            # attending to the previous chunks' KV. (Also the safety net
            # for a prefix-cache hit whose pages were evicted between
            # probe and admission: the request stays servable.)
            request.chunked = True
            request.bucket = max(self.config.prefill_buckets)
            return request.bucket
        request.bucket = bucket
        return request.bucket

    def _admit_batch(self) -> bool:
        """Admit up to prefill_max_batch same-bucket requests in ONE prefill
        call (round-1 VERDICT weak #4: serial batch=1 admission serialized
        bursts behind each other and behind decode)."""
        config = self.config
        self._drain_work()
        if not self._pending:
            return False
        was_idle = (not self._running and not self._chunking
                    and (time.monotonic() - self._last_active_ts
                         >= config.batch_idle_reset_s))
        # priority classes: interactive requests admit before queued
        # background work (summaries must not make a chat turn wait for a
        # free slot — and the sort is stable, so FIFO holds within each
        # class and no class reorders internally)
        if len({r.priority for r in self._pending}) > 1:
            self._pending = deque(sorted(self._pending,
                                         key=lambda r: r.priority))

        # (oversized prompts reject inside the head-selection scan below)
        free_slots = [s for s in range(config.max_batch)
                      if s not in self._running and s not in self._chunking]
        if not self._pending or not free_slots:
            return False

        # chunk rounds advance at most prefill_max_batch rows: admitting
        # more chunkers would pin full-prompt page allocations that sit
        # idle for rounds — but a chunked HEAD at capacity must not block
        # the short requests behind it either, so capacity-blocked
        # chunkers step aside (keeping FIFO among themselves) and the
        # next admissible request leads the group
        deferred: list[GenRequest] = []
        head: GenRequest | None = None
        while self._pending:
            candidate = self._pending[0]
            if self._assign_bucket(candidate) == 0:
                # oversized requests behind deferred chunkers reject here —
                # promoting one to head would admit it with bucket 0
                self._pending.popleft()
                candidate.finish_reason = "length"
                self._post_tokens(candidate, [], done=True)
                continue
            if (candidate.chunked
                    and len(self._chunking) >= config.prefill_max_batch):
                deferred.append(self._pending.popleft())
                continue
            head = candidate
            break
        if head is None:
            for request in reversed(deferred):
                self._pending.appendleft(request)
            return False
        bucket = self._assign_bucket(head)
        # history rows run the gathered-context attention path, which costs
        # O(S * max_context) regardless of hist — don't drag dense rows of
        # the same bucket through it (they'd pay for a hit they didn't get)
        with_hist = head.hist > 0
        group: list[GenRequest] = []
        skipped: list[GenRequest] = []
        limit = min(len(free_slots), config.prefill_max_batch)
        if head.chunked:
            limit = min(limit,
                        config.prefill_max_batch - len(self._chunking))
        while self._pending and len(group) < limit:
            request = self._pending.popleft()
            if head.chunked:
                # chunked requests group with each other regardless of hist
                # — chunk ROUNDS batch them (per-row absolute positions)
                ok = (self._assign_bucket(request) != 0 and request.chunked)
            else:
                ok = (self._assign_bucket(request) == bucket
                      and (request.hist > 0) == with_hist
                      and not request.chunked)
            if ok:
                group.append(request)
            else:
                skipped.append(request)
        for request in reversed(skipped):  # preserve FIFO for other buckets
            self._pending.appendleft(request)
        for request in reversed(deferred):  # capacity-blocked chunkers first
            self._pending.appendleft(request)
        if not group:
            return False

        admitted: list[GenRequest] = []
        for request in group:
            total = min(len(request.prompt_ids) + request.max_tokens,
                        config.max_seq_len)
            slot = free_slots[len(admitted)]
            shared: list[int] = []
            # trace attribution for tier IO: spills/restores the match +
            # allocate below trigger emit tier.spill/tier.restore spans
            # into THIS request's trace (cleared after — spills forced by
            # later decode-time page growth stay unattributed)
            if self._tier_client is not None:
                self._tier_client.trace_ctx = request.trace_ctx
            try:
                if request.hist:
                    hist, shared = self.allocator.match_prefix(
                        request.prompt_ids)
                    if hist != request.hist:
                        # the cache moved between probe and admission
                        # (eviction or a longer registration): re-probe
                        # for a new bucket
                        self.allocator.release_prefix(shared)
                        request.bucket = -1
                        self._pending.appendleft(request)
                        continue
                if not self.allocator.allocate_slot(slot, total,
                                                    prefix_pages=shared):
                    # page pressure: release the match (references held
                    # past this point would pin pages and could deadlock
                    # admission) and retry later with a fresh probe
                    if self.metrics is not None:
                        self.metrics.llm_kv_alloc_failures.inc()
                    self.allocator.release_prefix(shared)
                    request.bucket = -1
                    self._pending.appendleft(request)
                    continue
            finally:
                if self._tier_client is not None:
                    self._tier_client.trace_ctx = None
            if shared and self.ledger is not None:
                # discounted prefill: these tokens were served from shared
                # prefix-cache pages. Same site semantics as the
                # allocator's prefix_hit_tokens (counted when the match is
                # CONSUMED by a successful allocate), so the per-tenant
                # slices conserve against it exactly
                self.ledger.add(request.tenant, cache_hit_tokens=(
                    len(shared) * self.allocator.page_size))
            request.slot = slot
            request.queue_ms = (time.time() - request.created) * 1000
            self._observe_admitted(request)
            if request.chunked:
                # chunk-round scheduler owns it until the prompt is fully
                # prefilled; slots/pages are held, decode ignores it
                request.chunk_pos = request.hist
                self._chunking[slot] = request
            else:
                self._running[slot] = request
            admitted.append(request)
        if not admitted:
            return False
        self._sync_tables()
        self._last_active_ts = time.monotonic()
        if was_idle and config.batch_buckets:
            # idle-boundary width reset: a width inherited from a drained
            # burst must not tax the next arrival for batch_shrink_steps
            # decode steps (the config-3 post-burst bad mode: summaries
            # decoding at width 64 with 8 active). Guards: the engine was
            # idle past batch_idle_reset_s (millisecond inter-wave dips
            # keep the warmed start-at-max posture), the ceiling counts
            # ADMISSIBLE load only (a page-bound backlog must not hold a
            # too-wide bucket over a handful of decodable slots — same
            # clamp the decode-path sizing uses), slots were assigned
            # from index 0 up so the bucket covers every admitted slot,
            # and the reset never compiles (warmed widths only).
            active = len(self._running) + len(self._chunking)
            admissible = max(0, min(
                len(self._pending),
                config.max_batch - active,
                self.allocator.free_pages
                // self.allocator.avg_slot_pages()))
            ceiling = min(active + admissible, config.max_batch)
            desired = self._batch_bucket_for(max(ceiling, 1))
            if desired < self._batch_width and desired in self._warmed_widths:
                self._batch_width = desired
                self._shrink_streak = 0
                self._shrink_peak = 0

        if admitted[0].chunked:
            return True  # device work happens in _chunk_round

        started = time.monotonic()
        tokens, positions, last_idx, slot_ids, sampling = self._pack_rows(
            [(r, r.hist, len(r.prompt_ids)) for r in admitted], bucket)
        self._rng, key = jax.random.split(self._rng)
        # long buckets route through the sequence-parallel attention path
        # (shape-deterministic: SP-ness is a property of the bucket; SP
        # groups never carry history — _assign_bucket guarantees it)
        use_sp = (self._prefill_sample_sp is not None
                  and bucket > self.config.sp_threshold)
        any_hist = any(r.hist > 0 for r in admitted)
        if use_sp:
            prefill_fn = self._prefill_sample_sp
        elif any_hist:
            # context-width bucket: history attention only needs to span
            # the longest admitted prompt (hist + suffix)
            prefill_fn = self._hist_fn(self._hist_ctx_for(
                max(len(r.prompt_ids) for r in admitted)))
        else:
            prefill_fn = self._prefill_sample
        first, self.kv = prefill_fn(
            self.params, self.kv, tokens, positions,
            slot_ids, last_idx, sampling, key)
        if self.config.prefix_cache:
            # prompt pages are on the device write path now; register the
            # full ones so later prompts sharing the prefix skip their KV
            for request in admitted:
                self.allocator.register_prefix(request.slot,
                                               request.prompt_ids)
        first_host = jax.device_get(first)  # lint: allow[host-sync-in-hot-path] first-token fetch: prefill result feeds host-side admission
        self._last_step_done_ts = time.monotonic()
        elapsed_ms = (time.monotonic() - started) * 1000
        self.stats.prefill_ms_total += elapsed_ms
        self.stats.prefill_batches += 1
        self.stats.prefill_requests += len(admitted)
        self._record_step("prefill", batch=len(admitted),
                          width=int(tokens.shape[0]),  # the dispatched pad
                          dur_ms=elapsed_ms, tokens=len(admitted),
                          bucket=bucket)
        for i, request in enumerate(admitted):
            request.prefill_ms = elapsed_ms
            self._emit(request, int(first_host[i]))
        return True

    def _pack_rows(self, rows: list[tuple[GenRequest, int, int]], S: int):
        """Pack [(request, start, end)] prompt spans into padded [B, S]
        device arrays + per-row sampling params. B pads to the next power
        of two so XLA compiles at most log2(prefill_max_batch)+1 shapes
        per width; padding rows have positions -1 (no KV write — the same
        masking decode uses for inactive slots) and their samples are
        discarded. Shared by dense/suffix prefill and chunk rounds."""
        B = 1
        while B < len(rows):
            B *= 2
        tokens = np.full((B, S), self.tokenizer.pad_id, dtype=np.int32)
        positions = np.full((B, S), -1, dtype=np.int32)
        last_idx = np.zeros((B,), dtype=np.int32)
        slot_ids = np.zeros((B,), dtype=np.int32)
        temperature = np.zeros((B,), dtype=np.float32)
        top_k = np.zeros((B,), dtype=np.int32)
        top_p = np.ones((B,), dtype=np.float32)
        for i, (request, start, end) in enumerate(rows):
            n = end - start
            tokens[i, :n] = request.prompt_ids[start:end]
            positions[i, :n] = np.arange(start, end)
            last_idx[i] = n - 1
            slot_ids[i] = request.slot
            temperature[i] = request.temperature
            top_k[i] = request.top_k
            top_p[i] = request.top_p
        sampling = SamplingParams(jnp.asarray(temperature), jnp.asarray(top_k),
                                  jnp.asarray(top_p))
        return (jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(last_idx), jnp.asarray(slot_ids), sampling)

    def _chunk_round(self) -> None:
        """Advance every mid-prefill long prompt by ONE chunk, batched.

        Prompts longer than every bucket prefill in bucket-sized chunks
        through the history path — chunk i attends to chunks 0..i-1
        already in the slot's pages (plus any cached prefix). Rows carry
        ABSOLUTE positions, so requests at different chunk offsets batch
        into one dispatch (previously each long prompt chunked alone at
        B=1, serializing summarizer-style concurrent traffic). Mid-chunk
        samples predict known prompt tokens and are discarded; a row
        whose prompt completes this round emits its sampled token and
        moves to decode."""
        config = self.config
        batch = list(self._chunking.values())[:config.prefill_max_batch]
        # the smallest bucket covering the WIDEST remaining span this
        # round — rows all on short final chunks must not pay a
        # max-bucket-wide dispatch (every (B, bucket) pair is warmed)
        max_remaining = max(len(r.prompt_ids) - r.chunk_pos for r in batch)
        S = next((b for b in sorted(config.prefill_buckets)
                  if max_remaining <= b), max(config.prefill_buckets))
        started = time.monotonic()
        rows: list[tuple[GenRequest, int, int]] = []
        max_end = 1
        for request in batch:
            start = request.chunk_pos
            end = min(start + S, len(request.prompt_ids))
            rows.append((request, start, end))
            request.chunk_pos = end
            max_end = max(max_end, end)
        tokens, positions, last_idx, slot_ids, sampling = \
            self._pack_rows(rows, S)
        self._rng, key = jax.random.split(self._rng)
        first, self.kv = self._hist_fn(self._hist_ctx_for(max_end))(
            self.params, self.kv, tokens, positions,
            slot_ids, last_idx, sampling, key)
        first_host = jax.device_get(first)  # lint: allow[host-sync-in-hot-path] chunk-round boundary: host decides next chunk from these tokens
        self._last_step_done_ts = time.monotonic()
        elapsed_ms = (time.monotonic() - started) * 1000
        self.stats.prefill_batches += 1
        self.stats.prefill_ms_total += elapsed_ms
        self._record_step(
            "chunk_prefill", batch=len(batch), width=int(tokens.shape[0]),
            dur_ms=elapsed_ms,
            tokens=sum(1 for r in batch
                       if r.chunk_pos >= len(r.prompt_ids)),
            bucket=S)
        for i, request in enumerate(batch):
            request.prefill_ms += elapsed_ms
            if request.chunk_pos < len(request.prompt_ids):
                continue  # more chunks to go; sample discarded
            del self._chunking[request.slot]
            # register BEFORE emitting: a first token that finishes the
            # request (EOS / max_tokens=1) frees the slot's pages, and a
            # post-emit registration would cache nothing
            if config.prefix_cache:
                self.allocator.register_prefix(request.slot,
                                               request.prompt_ids)
            self.stats.prefill_requests += 1
            self._running[request.slot] = request
            self._emit(request, int(first_host[i]))

    # ------------------------------------------------------- speculative step

    def _draft_tokens(self, request: GenRequest, k: int) -> list[int]:
        """Prompt-lookup drafting: the most recent earlier occurrence of the
        trailing spec_ngram in (prompt + generated), returning up to k
        tokens that followed it. No draft model — the context itself is the
        proposer (works because summaries/tool outputs echo their inputs,
        and greedy decoding revisits its own phrases)."""
        n = self.config.spec_ngram
        ctx = request.prompt_ids + request.generated
        if len(ctx) <= n:
            return []
        tail = ctx[-n:]
        lo = max(0, len(ctx) - n - 512)  # bounded scan window
        for start in range(len(ctx) - n - 1, lo - 1, -1):
            if ctx[start:start + n] == tail:
                return ctx[start + n:start + n + k]
        return []

    def _any_would_draft(self) -> bool:
        """True iff some active row can take speculative drafts this step.
        Purely-sampled (or one-token-remaining) traffic pays ~spec_k x the
        attention/MLP compute through the [B,K] verify for zero extra
        emitted tokens — those steps run the plain width-1 decode instead
        (round-2 ADVICE low)."""
        for request in self._running.values():
            if (request.temperature == 0.0
                    and request.max_tokens - len(request.generated) > 1):
                return True
        return False

    def _spec_step_all(self) -> None:
        """One [B, K] verify step over every active slot: row = last token
        + up to K-1 drafted continuations. Drafts are accepted while the
        model's own (sampled) next token agrees, so each dispatch emits
        1..K tokens per slot for a single param read. Greedy rows only get
        drafts; sampled rows ride along at width 1 (their one token is
        drawn from the true distribution). Rejected-draft KV is dead by
        masking: attention reads at position p only after some later chunk
        rewrites p."""
        config = self.config
        B, K = config.max_batch, config.spec_k
        tokens = np.zeros((B, K), dtype=np.int32)
        positions = np.full((B, K), -1, dtype=np.int32)
        temperature = np.zeros((B,), dtype=np.float32)
        top_k = np.zeros((B,), dtype=np.int32)
        top_p = np.ones((B,), dtype=np.float32)
        active = list(self._running.items())
        widths: dict[int, int] = {}
        chunks: dict[int, list[int]] = {}
        for slot, request in active:
            n_ctx = len(request.prompt_ids) + len(request.generated)
            p0 = n_ctx - 1
            remaining = max(0, request.max_tokens - len(request.generated))
            chunk = [request.generated[-1]]
            if request.temperature == 0.0 and remaining > 1:
                chunk += self._draft_tokens(request, K - 1)
            chunk = chunk[:min(K, remaining)]  # active => remaining >= 1
            # one allocator call per slot (not one per drafted token): the
            # usable width falls out of the granted token capacity
            # (n_ctx = p0 + 1: the verify chunk's first token sits at p0)
            usable = self.allocator.pregrant_block(slot, p0 + 1, len(chunk))
            widths[slot] = usable
            if usable == 0:
                # page pool exhausted mid-stream: the request truncates
                request.finish_reason = "length"
                if self.metrics is not None:
                    self.metrics.llm_kv_alloc_failures.inc()
                continue
            chunk = chunk[:usable]
            chunks[slot] = chunk
            tokens[slot, :usable] = chunk
            positions[slot, :usable] = np.arange(p0, p0 + usable)
            temperature[slot] = request.temperature
            top_k[slot] = request.top_k
            top_p[slot] = request.top_p
        self._sync_tables()
        sampling = SamplingParams(jnp.asarray(temperature), jnp.asarray(top_k),
                                  jnp.asarray(top_p))
        self._rng, key = jax.random.split(self._rng)
        started = time.monotonic()
        max_pos = int(positions.max()) + 1 if active else K
        spec_ctx_pages = self._ctx_bucket_for(max_pos)
        block, self.kv = self._verify_fn(spec_ctx_pages)(
            self.params, self.kv, jnp.asarray(tokens), jnp.asarray(positions),
            jnp.arange(B, dtype=jnp.int32), sampling, key)
        self.stats.decode_steps += 1
        self.stats.decode_dispatches += 1
        self.stats.spec_steps += 1
        block_host = jax.device_get(block)  # [B, K]  # lint: allow[host-sync-in-hot-path] spec verify: host must compare drafts to accept
        self._last_step_done_ts = time.monotonic()
        spec_elapsed_ms = (time.monotonic() - started) * 1000
        spec_emitted = 0
        for slot, request in active:
            if request.finish_reason == "length" and request.slot in self._running:
                self._finish(request)
                continue
            chunk = chunks.get(slot, [])
            sampled = block_host[slot]
            emitted = 0
            for j in range(widths[slot]):
                # chunk[j] (j>0) is a draft: valid iff it matched the
                # model's sample at the previous position
                if j > 0 and chunk[j] != sampled[j - 1]:
                    break
                self._emit(request, int(sampled[j]))
                emitted += 1
                if request.slot not in self._running:
                    break  # EOS/stop/max hit inside the chunk
            self.stats.spec_tokens += max(0, emitted - 1)
            spec_emitted += emitted
        mfu, hbm_frac = self._observe_roofline(
            "spec_verify", B, spec_ctx_pages, spec_elapsed_ms)
        if self.signals is not None and active:
            # acceptance = EXTRA tokens per row this dispatch (0..K-1);
            # the controller's spec on/off knob acts on its EWMA
            self.signals.publish(
                "llm.spec_accept",
                max(0.0, spec_emitted / len(active) - 1.0),
                self.config.replica_id)
        self._record_step("spec_decode", batch=len(active), width=B,
                          dur_ms=spec_elapsed_ms, tokens=spec_emitted,
                          ctx_pages=spec_ctx_pages, mfu=mfu,
                          hbm_frac=hbm_frac)

    # ------------------------------------------------------------ decode step

    def _decode_step_all(self) -> None:
        """Serial decode: one fixed-shape step over every active slot,
        dispatched and retired back-to-back (the pre-overlap behavior;
        also the first step after any pipeline drain)."""
        inflight = self._decode_dispatch(self._decode_width(), None)
        self._decode_retire(inflight)

    def _decode_step_overlapped(self) -> None:
        """Depth-2 pipelined decode: dispatch step N+1 fed by step N's
        device-resident sampled tokens, THEN retire step N while the
        device executes N+1. The host's per-step work — device_get,
        emission, EOS checks, page extension — overlaps device compute
        instead of sitting between dispatches. Rows that finish inside
        step N still ride dispatch N+1 (their KV writes land in pages no
        one can reuse before the next drain barrier) and their lookahead
        tokens are discarded at retire, exactly like tokens past EOS
        inside a decode_block."""
        config = self.config
        k = self._k
        if self._phase_sample_due():
            # sampled steps run SERIALLY so the timed block_until_ready
            # window attributes this one step alone (a device-fed step's
            # wall overlaps its neighbor and cannot be split into
            # phases). Drains are the same barrier admission uses, so
            # token streams stay byte-identical to the unsampled run.
            self._drain_pipeline()
            if self._running:
                self._decode_step_all()
            return
        feed = self._inflight
        self._inflight = None
        if feed is not None:
            # barriers that invalidate the lookahead's slot->column map or
            # its token-feedback row:
            # - a row the in-flight dispatch doesn't cover (defensive —
            #   admission/chunk completion drain upstream);
            # - a PARTIAL budget on a row that will survive its retire
            #   (per-slot page cap granted 0 < b < k): the feedback fn
            #   feeds block row k-1, but the row's true last token is at
            #   b-1 — only a host-fed dispatch can resume it correctly;
            # - a batch_buckets compaction/width decision that would move
            #   slots under it
            stale = any(
                feed["reqs"].get(slot) is not request
                or (0 < feed["budgets"].get(slot, 0) < k
                    and len(request.generated) + feed["budgets"][slot]
                    < request.max_tokens)
                for slot, request in self._running.items())
            holes = False
            if config.batch_buckets:
                ceiling = max(self._running) + 1
                holes = (ceiling != len(self._running) + len(self._chunking)
                         or self._batch_bucket_for(ceiling)
                         != self._batch_width)
            if stale or holes:
                if not self._drain_feed(feed):
                    return
                feed = None
        if feed is not None and all(
                request.max_tokens - len(request.generated)
                - feed["budgets"].get(slot, 0) <= 0
                for slot, request in self._running.items()):
            # every surviving row's budget is already exhausted by the
            # in-flight tokens (max_tokens tail): a lookahead would sample
            # only discards — retire instead, keeping decode_steps and RNG
            # consumption identical to the serial path on these tails
            self._decode_retire(feed)
            return
        if feed is not None:
            # page-pressure pre-flight: the lookahead's grow_slot calls run
            # BEFORE retire N frees any EOS'd rows' pages, so dispatching
            # into a too-dry pool would truncate rows the serial order
            # (retire, then grow from the freed pages) would have served.
            # If the pool can't cover every surviving row's full want,
            # drain first — the retire may free pages, and the follow-up
            # host-fed dispatch then truncates exactly where serial would.
            deficit = 0
            for slot, request in self._running.items():
                pending = feed["budgets"].get(slot, 0)
                n_ctx = (len(request.prompt_ids) + len(request.generated)
                         + pending)
                want = min(k, max(0, request.max_tokens
                                  - len(request.generated) - pending))
                if want > 0:
                    deficit += max(
                        0, self.allocator.pages_needed(n_ctx + want - 1)
                        - self.allocator.slot_pages(slot))
            if deficit > self.allocator.free_pages:
                if not self._drain_feed(feed):
                    return
                feed = None
        B = self._decode_width(allow_compact=feed is None)
        if feed is not None and feed["B"] != B:
            # width changed (batch_buckets growth): the [k, B] feedback
            # shape no longer matches — drain and restart host-fed
            if not self._drain_feed(feed):
                return
            feed = None
            B = self._decode_width()
        nxt = self._decode_dispatch(B, feed)
        self._inflight = nxt
        if feed is not None:
            self._decode_retire(feed)

    def _drain_pipeline(self) -> None:
        """Retire the in-flight decode step, if any (pipeline barrier)."""
        inflight = self._inflight
        if inflight is None:
            return
        self._inflight = None
        self.stats.pipeline_drains += 1
        self._decode_retire(inflight)

    def _drain_feed(self, feed: dict[str, Any]) -> bool:
        """Barrier inside the overlap step: retire the fed step now and
        report whether any rows survive to dispatch."""
        self.stats.pipeline_drains += 1
        self._decode_retire(feed)
        return bool(self._running)

    def _decode_width(self, allow_compact: bool = True) -> int:
        """The decode dispatch width: the power-of-two bucket covering the
        ACTIVE slot ceiling (slots compacted first) under batch_buckets,
        else the configured max. ``allow_compact=False`` skips slot
        compaction — moving rows under an in-flight lookahead would break
        its slot->column mapping."""
        config = self.config
        if self._running or self._chunking:
            self._last_active_ts = time.monotonic()
        if config.batch_buckets:
            # Hysteresis on the width: switching executables makes XLA
            # re-home the donated KV pool (~a full pool copy), so width
            # changes must be RARE. Grow immediately (correctness: arrays
            # must cover the active ceiling); shrink only after the smaller
            # width has sufficed for a sustained streak (load genuinely
            # dropped, not an inter-wave dip).
            # the width target is the ACTIVE ceiling plus the queued load
            # that could actually admit (anticipatory growth, round-4):
            # one transiently queued request at 8-active/64-slot light
            # load targets 16, not 64 — jumping to max on any queued item
            # cost config-3 a 4.5x regression in the round-5 gateway
            # bench. At genuine full load the target IS max_batch, so
            # this matches the fixed-width engine there.
            incoming = self._work.qsize() + len(self._pending)
            free_slots = (config.max_batch - len(self._running)
                          - len(self._chunking))
            page_capacity = (self.allocator.free_pages
                             // self.allocator.avg_slot_pages())
            admissible = max(0, min(incoming, free_slots, page_capacity))
            if admissible == 0 and allow_compact:
                # compaction pays exactly when holes will NOT refill at
                # the next admission: an empty queue, OR a page-bound
                # backlog (queued work that cannot admit) — without it a
                # lone high-index slot would hold the ceiling at max for
                # the backlog's whole duration
                self._compact_slots()
            ceiling = min(max(max(self._running) + 1,
                              len(self._running) + len(self._chunking)
                              + admissible),
                          config.max_batch)
            desired = self._batch_bucket_for(ceiling)
            if self._width_floor:
                # controller floor: live occupancy says the next burst
                # would just re-grow — don't shrink below it (each width
                # change re-homes the donated KV pool)
                desired = max(desired, self._batch_bucket_for(
                    min(self._width_floor, config.max_batch)))
            if desired >= self._batch_width:
                # grow immediately (arrays must cover the ceiling)
                self._batch_width = desired
                self._shrink_streak = 0
                self._shrink_peak = 0
            else:
                self._shrink_streak += 1
                # shrink to the PEAK desired width seen over the streak,
                # not the instantaneous one — a momentary dip must not
                # trigger an over-shrink followed by an immediate re-grow
                # (each width change re-homes the donated KV pool)
                self._shrink_peak = max(self._shrink_peak, desired)
                if self._shrink_streak >= config.batch_shrink_steps:
                    # never EAT a compile to get smaller (round-4
                    # config-4 tail: the drain-phase shrink compiled a
                    # fresh executable inside the serving path) — shrink
                    # only to warmup-compiled widths or widths this
                    # process already compiled (an unwarmed engine that
                    # grew for a burst may return to its earlier width:
                    # the executables exist)
                    target = self._shrink_peak
                    # "already compiled" means the (width, ctx) PAIR the
                    # next dispatch would use — a width whose executables
                    # exist only for shorter contexts would still compile
                    # mid-traffic
                    ctx_now = self._ctx_bucket_for(max(
                        (len(r.prompt_ids) + len(r.generated)
                         for r in self._running.values()), default=1)
                        + self._k)
                    if (target in self._warmed_widths
                            or (self._k, target, ctx_now)
                            in self._decode_fns):
                        self._batch_width = target
                    self._shrink_streak = 0
                    self._shrink_peak = 0
            return self._batch_width
        return config.max_batch

    def _decode_dispatch(self, B: int, feed: dict[str, Any] | None
                         ) -> dict[str, Any]:  # lint: hot-path
        """Build and submit one decode SUPER-STEP dispatch of width ``B``;
        returns the in-flight record the matching _decode_retire consumes.

        ``feed`` is the previous, still-in-flight step: its [k, B] sampled
        block (device-resident) supplies this step's input token, and host
        state advances OPTIMISTICALLY by the fed step's per-slot budgets.
        The optimism is sound: a row that survives its step always used
        its FULL budget (a short budget means max_tokens or the page pool
        ended it, i.e. the row dies at that step's retire), so surviving
        rows advance by exactly ``budget`` tokens and dead rows' lookahead
        output is discarded wholesale."""
        config = self.config
        k = self._k
        # phase attribution (opt-in sampling): this dispatch runs serial
        # (the overlapped caller drained first) and times each phase
        build_ts = time.monotonic()
        sampled = self._phase_sample_due()
        self._dispatch_count += 1
        tokens = np.zeros((B,), dtype=np.int32)
        positions = np.zeros((B,), dtype=np.int32)
        seq_lens = np.zeros((B,), dtype=np.int32)
        temperature = np.zeros((B,), dtype=np.float32)
        top_k = np.zeros((B,), dtype=np.int32)
        top_p = np.ones((B,), dtype=np.float32)
        # device-side freeze inputs: per-slot token budgets (max_tokens
        # remainder ∧ granted pages) and the EOS/stop-id table — what
        # lets a finished row freeze INSIDE the super-step without a
        # host round-trip
        budget_arr = np.zeros((B,), dtype=np.int32)
        stop_tbl = np.full((B, self._STOP_TBL_WIDTH), -1, dtype=np.int32)
        # per-slot budget within this block: page capacity and max_tokens cap
        # how many of the k decoded tokens are usable
        budgets: dict[int, int] = {}
        truncated: set[int] = set()
        reqs = dict(self._running)
        for slot, request in reqs.items():
            pending = feed["budgets"].get(slot, 0) if feed is not None else 0
            # n_ctx counts every token that exists (prompt + generated +
            # the fed step's budgeted-but-unseen tokens); the input token
            # sits at 0-based position n_ctx-1 and is written to the cache
            # this step, after which the slot's context length is n_ctx.
            n_ctx = len(request.prompt_ids) + len(request.generated) + pending
            if feed is None:
                tokens[slot] = request.generated[-1]
            positions[slot] = n_ctx - 1
            seq_lens[slot] = n_ctx
            temperature[slot] = request.temperature
            top_k[slot] = request.top_k
            top_p[slot] = request.top_p
            # pre-grant pages for the whole super-step in ONE allocator
            # call; writes beyond the granted range land on the reserved
            # trash page and their tokens are discarded via the budget
            remaining = max(0, request.max_tokens - len(request.generated)
                            - pending)
            want = min(k, remaining)
            usable = 0
            if want > 0:
                usable = self.allocator.pregrant_block(slot, n_ctx, want)
                if usable == 0:
                    # page pool exhausted mid-stream: the request truncates
                    # (finish happens at retire so the PREVIOUS step's
                    # tokens still emit first)
                    truncated.add(slot)
                    if self.metrics is not None:
                        self.metrics.llm_kv_alloc_failures.inc()
            budgets[slot] = usable
            budget_arr[slot] = usable
            stops = (self.tokenizer.eos_id,) + tuple(
                request.stop_ids)[:self._STOP_TBL_WIDTH - 1]
            stop_tbl[slot, :len(stops)] = stops
        sync_start = time.monotonic()
        self._sync_tables()
        sync_s = time.monotonic() - sync_start
        sampling = SamplingParams(jnp.asarray(temperature), jnp.asarray(top_k),
                                  jnp.asarray(top_p))
        self._rng, key = jax.random.split(self._rng)
        # context-width bucket: the longest row this block can reach
        # (seq_lens counts the incoming token; k-1 more may be written)
        started = time.monotonic()
        ctx_pages = self._ctx_bucket_for(int(seq_lens.max()) + k)
        # dispatch-gap telemetry: host time the device sat idle between
        # steps. A device-fed dispatch by construction overlaps the still-
        # running previous step, so its gap is zero.
        gap_s = 0.0
        if feed is None and self._last_step_done_ts is not None:
            gap_s = max(0.0, started - self._last_step_done_ts)
        else:
            self.stats.overlap_steps += int(feed is not None)
        self.stats.dispatch_gap_ms_total += gap_s * 1000
        if self.metrics is not None:
            self.metrics.llm_dispatch_gap.labels(
                replica=self.config.replica_id).observe(gap_s)
        if feed is None:
            (block_tokens, block_valid, block_done), self.kv = \
                self._decode_fn(ctx_pages, B)(
                    self.params, self.kv, jnp.asarray(tokens),
                    jnp.asarray(positions), jnp.arange(B, dtype=jnp.int32),
                    jnp.asarray(seq_lens), jnp.asarray(budget_arr),
                    jnp.asarray(stop_tbl), sampling, key)
        else:
            (block_tokens, block_valid, block_done), self.kv = \
                self._decode_fb_fn(ctx_pages, B)(
                    self.params, self.kv, feed["block"],
                    jnp.asarray(positions), jnp.arange(B, dtype=jnp.int32),
                    jnp.asarray(seq_lens), jnp.asarray(budget_arr),
                    jnp.asarray(stop_tbl), sampling, key)
        dispatched_ts = time.monotonic()
        phases: dict[str, float] | None = None
        if sampled:
            # the one intentional sync sampling buys: bounds this step's
            # device-compute phase exactly, every Nth step only
            block_tokens.block_until_ready()  # lint: allow[host-sync-in-hot-path] opt-in phase-attribution window (config.step_sample_every): every Nth step pays one timed sync; steady-state steps stay overlapped
            ready_ts = time.monotonic()
            phases = {
                "host_dispatch_ms": max(
                    0.0, (dispatched_ts - build_ts - sync_s) * 1000),
                "table_sync_ms": sync_s * 1000,
                "device_compute_ms": (ready_ts - dispatched_ts) * 1000,
            }
        try:
            # D2H overlaps device compute (tokens + the super-step's
            # valid/done masks all retire in one readback)
            block_tokens.copy_to_host_async()
            block_valid.copy_to_host_async()
            block_done.copy_to_host_async()
        except AttributeError:
            pass
        self.stats.decode_steps += k
        self.stats.decode_dispatches += 1
        return {"block": block_tokens, "valid": block_valid,
                "done": block_done, "budgets": budgets, "reqs": reqs,
                "truncated": truncated, "B": B, "k": k,
                "ctx_pages": ctx_pages,
                "batch": len(reqs), "dispatch_ts": started, "gap_s": gap_s,
                "fed": feed is not None, "build_ts": build_ts,
                "phases": phases}

    def _decode_retire(self, inflight: dict[str, Any]) -> None:  # lint: hot-path
        """Fetch and emit one dispatched decode SUPER-STEP: the [k, B]
        token block plus the device's valid/done masks come back in ONE
        readback, and up to k tokens per slot emit per sync. Under
        overlap this runs while the NEXT step executes on device, so
        every line here is off the device's critical path."""
        fetch_ts = time.monotonic()
        block_host, valid_host, done_host = jax.device_get(  # lint: allow[host-sync-in-hot-path] retire-side read-back — the ONE host sync per K-token super-step, overlapped by the in-flight dispatch
            (inflight["block"], inflight["valid"], inflight["done"]))
        done_ts = time.monotonic()
        prev_done_ts = self._last_step_done_ts
        self._last_step_done_ts = done_ts
        decode_elapsed_ms = (done_ts - inflight["dispatch_ts"]) * 1000
        # roofline denominator: under the depth-2 pipeline this step was
        # dispatched while its PREDECESSOR still executed, so dispatch->
        # done spans ~2 device steps at steady state — the per-step wall
        # is retire-to-retire there, and dispatch->done only when the
        # device was idle at dispatch (serial path / first after drain)
        step_wall_ms = (done_ts - max(inflight["dispatch_ts"],
                                      prev_done_ts or 0.0)) * 1000
        self.stats.decode_ms_total += decode_elapsed_ms
        decode_emitted = 0
        for slot, request in inflight["reqs"].items():
            if self._running.get(slot) is not request:
                continue  # finished at an earlier retire: lookahead discards
            if slot in inflight["truncated"]:
                if request.finish_reason is None:
                    request.finish_reason = "length"
                self._finish(request)
                continue
            for step_i in range(inflight["budgets"][slot]):
                if not valid_host[step_i][slot]:
                    # the device froze this row mid-super-step (EOS/stop
                    # sampled earlier in the block): nothing real follows
                    break
                self._emit(request, int(block_host[step_i][slot]))
                decode_emitted += 1
                if self._running.get(slot) is not request:
                    break  # finished (EOS/stop/max): rest of block discarded
        emit_done_ts = time.monotonic()
        phases = inflight.get("phases")
        if phases is not None:
            # a phase row exists only when the SAMPLED dispatch reached
            # retire intact (crash/drop paths discard the inflight record,
            # so partial rows never surface)
            phases["readback_ms"] = (done_ts - fetch_ts) * 1000
            phases["emit_ms"] = (emit_done_ts - done_ts) * 1000
            phases["total_ms"] = (emit_done_ts - inflight["build_ts"]) * 1000
            self._observe_phases(phases)
        mfu, hbm_frac = self._observe_roofline(
            "decode_fb" if inflight.get("fed") else "decode",
            inflight["B"], inflight["ctx_pages"], step_wall_ms,
            k=inflight["k"])
        self._gap_window.append((inflight["gap_s"],
                                 decode_elapsed_ms / 1000))
        self._record_step("decode", batch=inflight["batch"],
                          width=inflight["B"], dur_ms=decode_elapsed_ms,
                          tokens=decode_emitted,
                          ctx_pages=inflight["ctx_pages"],
                          gap_ms=inflight["gap_s"] * 1000,
                          phases=phases, mfu=mfu, hbm_frac=hbm_frac,
                          superstep=inflight["k"],
                          frozen=int(done_host.sum()),
                          wall_ms=step_wall_ms)
        if self.metrics is not None:
            self.metrics.llm_device_idle_frac.labels(
                replica=self.config.replica_id).set(
                self.device_idle_fraction())

    def device_idle_fraction(self) -> float:
        """Fraction of recent decode wall time the device spent waiting on
        host bookkeeping (dispatch gaps / (gaps + in-step wall)); the
        number the overlapped pipeline exists to drive to ~0."""
        gaps = walls = 0.0
        # snapshot first: callers include the asyncio thread (diagnostics,
        # bench) while the dispatch thread appends
        for gap_s, wall_s in list(self._gap_window):
            gaps += gap_s
            walls += wall_s
        total = gaps + walls
        return gaps / total if total > 0 else 0.0

    # --------------------------------------------------------------- telemetry

    def _phase_sample_due(self) -> bool:
        """True when the NEXT decode dispatch should take the timed
        phase-attribution window (every Nth; 0 disables). Pure predicate
        on the dispatch counter so the overlapped wrapper and the
        dispatch itself agree within one step."""
        n = self.config.step_sample_every
        return n > 0 and self._dispatch_count % n == 0

    def _observe_phases(self, phases: dict[str, float]) -> None:
        """Publish one completed sampled-step phase row: stats counter,
        the per-phase histograms, and the event buffer llm.decode spans
        attach from. Runs at retire on the dispatch thread."""
        self.stats.phase_samples += 1
        self._phase_events.append((time.time(), dict(phases)))
        if self.metrics is not None:
            rid = self.config.replica_id
            for key, dur_ms in phases.items():
                if key == "total_ms":
                    continue
                self.metrics.llm_step_phase.labels(
                    replica=rid, phase=key[:-3]).observe(
                    max(0.0, dur_ms / 1e3))

    def _observe_roofline(self, kind: str, width: int, ctx_pages: int,
                          dur_ms: float, k: int | None = None
                          ) -> tuple[float | None, float | None]:
        """Live roofline: the dispatched executable's warmup-captured XLA
        cost over this step's measured wall. Feeds the mcpforge_llm_mfu /
        hbm_roofline_frac gauges and the snapshot window; (None, None)
        when the registry has no entry (unwarmed engine or cost capture
        off). ``k`` selects the rung-suffixed cost entry when adaptive K
        moved off the static rung (FLOPs/bytes scale with K)."""
        entry = None
        if k is not None and k != self.config.fused_steps:
            entry = self.cost_registry.lookup(f"{kind}@k{k}", width,
                                              ctx_pages)
            if entry is None and kind == "decode_fb":
                entry = self.cost_registry.lookup(f"decode@k{k}", width,
                                                  ctx_pages)
        if entry is None:
            entry = self.cost_registry.lookup(kind, width, ctx_pages)
        if entry is None and kind == "decode_fb":
            entry = self.cost_registry.lookup("decode", width, ctx_pages)
        if entry is None or dur_ms <= 0:
            return None, None
        dur_s = dur_ms / 1e3
        mfu, frac = roofline_fractions(
            entry.flops, entry.bytes_accessed, dur_s, self.mesh.size,
            self.config.peak_tflops_per_chip, self.config.hbm_gbps_per_chip)
        self._roofline_window.append((entry.flops, entry.bytes_accessed,
                                      dur_s))
        if self.metrics is not None:
            rid = self.config.replica_id
            self.metrics.llm_mfu.labels(replica=rid).set(mfu)
            self.metrics.llm_hbm_roofline.labels(replica=rid).set(frac)
        return mfu, frac

    def roofline_snapshot(self) -> dict[str, Any]:
        """Aggregate cost-model roofline over the recent decode window
        (the live twin of bench_engine's post-hoc mfu/hbm numbers)."""
        flops = byts = dur = 0.0
        window = list(self._roofline_window)
        for f, b, d in window:
            flops += f
            byts += b
            dur += d
        out: dict[str, Any] = {
            "window_steps": len(window),
            "cost_entries": self.cost_registry.counts(),
        }
        if dur > 0:
            mfu, frac = roofline_fractions(
                flops, byts, dur, self.mesh.size,
                self.config.peak_tflops_per_chip,
                self.config.hbm_gbps_per_chip)
            # 12 digits: a CPU-test replica's MFU sits at ~1e-7 — and a
            # load-stalled host can stretch one step's wall enough to
            # push it below 1e-9 — it must never round to a dead 0.0
            out["mfu"] = round(mfu, 12)
            out["hbm_roofline_frac"] = round(frac, 12)
        return out

    def _on_xla_compile(self, stage: str, duration_s: float) -> None:
        """CompileTracker callback — runs on whichever thread compiled.
        Counts every attributed compile; serving-stage compiles (the
        mid-traffic kind PR 5 proved catastrophic) also emit a span so
        they are findable next to the request traces they stalled."""
        rid = self.config.replica_id
        if self.metrics is not None:
            try:
                self.metrics.llm_xla_compiles.labels(
                    replica=rid, stage=stage).inc()
                self.metrics.llm_xla_compile_time.labels(
                    replica=rid).observe(duration_s)
            except Exception:
                pass
        if stage == "serving" and self.tracer is not None:
            try:
                now = time.time()
                self.tracer.emit_span(
                    "llm.xla_compile", now - duration_s, now,
                    attributes={"gen_ai.request.model": self.config.model,
                                "llm.replica_id": rid,
                                "llm.compile_stage": stage})
            except Exception:
                pass  # telemetry must never break the compiling thread

    def compile_stats(self) -> dict[str, Any]:
        """Warmup/serving XLA compile counts + timings (admin surfaces,
        pool status, support bundle)."""
        return self.compile_tracker.snapshot()

    def _record_step(self, kind: str, *, batch: int, width: int,
                     dur_ms: float, tokens: int, bucket: int | None = None,
                     ctx_pages: int | None = None,
                     gap_ms: float | None = None,
                     phases: dict[str, float] | None = None,
                     mfu: float | None = None,
                     hbm_frac: float | None = None,
                     superstep: int | None = None,
                     frozen: int | None = None,
                     wall_ms: float | None = None) -> None:
        """One ring-buffer entry + gauge refresh per device dispatch.
        Runs on the dispatch thread; deque.append and prometheus_client
        ops are both thread-safe, and the asyncio side only ever copies
        the deque (recent_steps), never mutates it."""
        self._step_seq += 1
        depth = self._work.qsize() + len(self._pending)
        pages_in_use = self.allocator.pages_in_use
        self.step_log.append({
            "seq": self._step_seq,
            "ts": time.time(),
            "kind": kind,                       # prefill|chunk_prefill|decode|spec_decode
            "batch": batch,                     # rows carrying real work
            "width": width,                     # padded dispatch width
            "bucket": bucket,                   # prefill token bucket (S)
            "ctx_pages": ctx_pages,             # decode context-width bucket
            "duration_ms": round(dur_ms, 3),
            "tokens": tokens,                   # tokens emitted by this step
            # decode iterations fused into this dispatch (None for
            # prefill rows) and rows the device froze mid-super-step —
            # K>1 accounting: tokens ≈ batch × superstep at steady state,
            # and ONE host sync retired them all
            "superstep": superstep,
            "frozen": frozen,
            "queue_depth": depth,
            "kv_pages_in_use": pages_in_use,
            # host-side stall before this dispatch (decode only; 0 when the
            # overlapped pipeline kept the device fed)
            "gap_ms": round(gap_ms, 3) if gap_ms is not None else None,
            # sampled phase attribution (None unless this step took the
            # step_sample_every window) and live cost-model roofline
            "phases": ({k: round(v, 3) for k, v in phases.items()}
                       if phases is not None else None),
            "mfu": round(mfu, 12) if mfu is not None else None,
            "hbm_frac": round(hbm_frac, 12) if hbm_frac is not None else None,
        })
        if (kind in ("decode", "spec_decode") and superstep is not None
                and tokens):
            # smoothed tokens-per-dispatch (satellite): updated before
            # the gauge refresh below so the exported EWMA includes this
            # very step
            self._tpd_ewma = (
                float(tokens) if self._tpd_ewma is None
                else _TPD_EWMA_ALPHA * tokens
                + (1.0 - _TPD_EWMA_ALPHA) * self._tpd_ewma)
        m = self.metrics
        if m is not None:
            rid = self.config.replica_id
            m.llm_batch_occupancy.labels(replica=rid).set(
                len(self._running) + len(self._chunking))
            m.llm_kv_pages_in_use.labels(replica=rid).set(pages_in_use)
            m.llm_kv_page_utilization.labels(replica=rid).set(
                pages_in_use / max(1, self.num_kv_pages - 1))
            # dtype-aware byte view: pages x page bytes under the ACTIVE
            # KV dtype, so int8 and bf16 engines are comparable on one
            # dashboard even though their page counts differ 2x
            m.llm_kv_bytes_in_use.labels(
                replica=self.config.replica_id).set(self.kv_bytes_in_use())
            m.llm_queue_depth.labels(replica=rid).set(depth)
            # tokens/s over the TRUE per-step wall (retire-to-retire under
            # the depth-2 overlap — dur_ms there spans ~2 device steps and
            # would halve the gauge); tokens counts every token this
            # dispatch emitted, so the gauge stays truthful at superstep>1
            rate_ms = wall_ms if wall_ms is not None else dur_ms
            if rate_ms > 0 and tokens:
                m.llm_step_tokens_per_sec.labels(replica=rid).set(
                    tokens / (rate_ms / 1e3))
            if superstep is not None and tokens:
                m.llm_tokens_per_dispatch.labels(replica=rid).set(tokens)
                if self._tpd_ewma is not None:
                    # smoothed twin (satellite): the instantaneous gauge
                    # whipsaws with batch occupancy — alerts and the
                    # controller act on this one
                    m.llm_tokens_per_dispatch_ewma.labels(
                        replica=rid).set(self._tpd_ewma)
            if self._tier_client is not None:
                self._export_tier_metrics(m, rid)
        if kind in ("decode", "spec_decode"):
            self._publish_signals(tokens=tokens, depth=depth, mfu=mfu,
                                  hbm_frac=hbm_frac, gap_ms=gap_ms,
                                  wall_ms=wall_ms if wall_ms is not None
                                  else dur_ms)

    def _publish_signals(self, *, tokens: int, depth: int,
                         mfu: float | None, hbm_frac: float | None,
                         gap_ms: float | None,
                         wall_ms: float | None) -> None:
        """Push this decode dispatch's signals onto the live bus (the
        controller's inputs — docs/controller.md signal catalog). Every
        publish is O(1); the O(window) idle fraction goes out on a
        bounded tick, not per retire. No bus = one attribute check."""
        bus = self.signals
        if bus is None:
            return
        rid = self.config.replica_id
        if tokens:
            bus.publish("llm.tokens_per_dispatch", tokens, rid)
        if mfu is not None:
            bus.publish("llm.mfu", mfu, rid)  # lint: allow[signal-name-conformance] dashboard-only export via the /signals snapshot
        if hbm_frac is not None:
            bus.publish("llm.hbm_roofline_frac", hbm_frac, rid)  # lint: allow[signal-name-conformance] dashboard-only export via the /signals snapshot
        if gap_ms is not None:
            bus.publish("llm.dispatch_gap_ms", gap_ms, rid)  # lint: allow[signal-name-conformance] dashboard-only export via the /signals snapshot
        if wall_ms is not None and wall_ms > 0 and tokens:
            bus.publish("llm.step_tokens_per_sec",
                        tokens / (wall_ms / 1e3), rid)
        bus.publish("llm.saturation",  # lint: allow[signal-name-conformance] dashboard-only export via the /signals snapshot
                    depth / max(1, self.config.max_queue), rid)
        bus.publish("llm.occupancy",
                    (len(self._running) + len(self._chunking))
                    / max(1, self.config.max_batch), rid)
        now = time.monotonic()
        if now - self._signals_slow_ts >= 0.25:
            self._signals_slow_ts = now
            bus.publish("llm.idle_frac", self.device_idle_fraction(), rid)

    def _export_tier_metrics(self, m, rid: str) -> None:
        """Per-tier prefix counters/gauges (dispatch thread, piggybacked
        on the per-step gauge refresh): hit counters export as deltas
        from the allocator's consume-site totals; byte gauges report HBM
        residency per replica and the shared store's host/disk footprint
        (pool-shared, so every replica's child reports the same store
        number — read one, don't sum)."""
        alloc = self.allocator
        for tier, count in alloc.tier_hits.items():
            prev = self._tier_hits_exported.get(tier, 0)
            if count > prev:
                m.llm_prefix_tier_hits.labels(replica=rid, tier=tier).inc(
                    count - prev)
                self._tier_hits_exported[tier] = count
        m.llm_prefix_tier_bytes.labels(replica=rid, tier="hbm").set(
            alloc.cached_pages * self._kv_page_bytes)
        store = self._tier_client.store
        if store is not None:
            s = store.stats()
            m.llm_prefix_tier_bytes.labels(replica=rid, tier="host").set(
                s["host_bytes"])
            m.llm_prefix_tier_bytes.labels(replica=rid, tier="disk").set(
                s["disk_bytes"])
            if "object_bytes" in s:
                m.llm_prefix_tier_bytes.labels(
                    replica=rid, tier="object").set(s["object_bytes"])

    def recent_steps(self, limit: int | None = None) -> list[dict[str, Any]]:
        """Last N step summaries, oldest first (diagnostics surface)."""
        steps = list(self.step_log)
        if limit is not None and limit > 0:
            steps = steps[-limit:]
        return steps

    def _span(self, name: str, request: GenRequest, start_ts: float,
              end_ts: float, status: str = "OK",
              events: list[tuple[float, str, dict[str, Any]]] | None = None,
              **attrs: Any) -> None:
        """Emit one per-request engine span parented to the submitter's
        llm.request span (no contextvars on the dispatch thread)."""
        if self.tracer is None or request.trace_ctx is None:
            return
        attributes: dict[str, Any] = {
            "gen_ai.system": "tpu_local",
            "gen_ai.request.model": self.config.model,
            "llm.replica_id": self.config.replica_id,
            "llm.slot": request.slot,
        }
        if request.tenant:
            attributes["llm.tenant"] = request.tenant
        attributes.update(attrs)
        try:
            self.tracer.emit_span(name, start_ts, end_ts,
                                  trace_ctx=request.trace_ctx,
                                  attributes=attributes, status=status,
                                  events=events)
        except Exception:
            pass  # telemetry must never kill the dispatch thread

    def _tenant_label(self, request: GenRequest) -> str:
        """Clamped Prometheus tenant label for a request (the registry's
        shared TenantClamp bounds the exported child set)."""
        return self.metrics.tenant_clamp.label(request.tenant)

    def _exemplar(self, metric: str, value: float, request: GenRequest,
                  labels: tuple = ()) -> dict[str, str] | None:
        """Trace-id exemplar for a latency observe (None when the
        request is unattributed or exemplars are off) — the forensics
        click-through from a histogram bucket to the retained trace.
        ``labels`` must match the ``.labels(...)`` child the observe
        targets (prometheus keeps exemplars per labeled child)."""
        if self.metrics is None or request.trace_ctx is None:
            return None
        return self.metrics.exemplar(metric, value, request.trace_ctx[0],
                                     labels)

    def _observe_admitted(self, request: GenRequest) -> None:
        """Queue-phase telemetry at the moment a request wins a slot."""
        if request.queue_observed:
            return  # re-admission after crash recovery
        request.queue_observed = True
        if self.metrics is not None:
            wait_s = max(0.0, request.queue_ms / 1e3)
            tenant = self._tenant_label(request)
            self.metrics.llm_queue_wait.labels(tenant=tenant).observe(
                wait_s, exemplar=self._exemplar("llm_queue_wait", wait_s,
                                                request, (tenant,)))
        if self.signals is not None:
            self.signals.publish("llm.queue_wait_ms",
                                 max(0.0, request.queue_ms),
                                 self.config.replica_id)
        self._span("llm.queue", request, request.created, time.time(),
                   **{"llm.queue_ms": round(request.queue_ms, 2),
                      "llm.priority": request.priority})

    def _observe_finish(self, request: GenRequest) -> None:
        """Decode-phase telemetry when a request leaves the engine: TPOT
        over the inter-token phase + the llm.decode span."""
        now = time.time()
        n = len(request.generated)
        decode_start = request.first_token_ts or now
        if self.metrics is not None and n > 1:
            tpot_s = max(0.0, (now - decode_start) / (n - 1))
            tenant = self._tenant_label(request)
            self.metrics.llm_tpot.labels(
                model=self.config.model,
                replica=self.config.replica_id,
                tenant=tenant).observe(
                tpot_s, exemplar=self._exemplar(
                    "llm_tpot", tpot_s, request,
                    (self.config.model, self.config.replica_id, tenant)))
        if self.signals is not None and n > 1:
            self.signals.publish(  # lint: allow[signal-name-conformance] dashboard-only export via the /signals snapshot
                "llm.tpot_ms", max(0.0, (now - decode_start) / (n - 1)) * 1e3,
                self.config.replica_id)
        if self.ledger is not None and request.slot >= 0:
            # HBM residency: pages this request held x its resident wall
            # (admission -> retire; pages are still held here — the
            # callers free the slot AFTER _observe_finish)
            admitted_ts = request.created + request.queue_ms / 1e3
            self.ledger.add(request.tenant, kv_page_seconds=(
                self.allocator.slot_pages(request.slot)
                * max(0.0, now - admitted_ts)))
        reason = request.finish_reason or "stop"
        # sampled phase rows that landed during this request's decode
        # phase ride along as span events — the trace-side view of the
        # step-attribution ring (batch-wide, so shared across the
        # requests decoding concurrently)
        phase_events = [(ts, "decode.step.phases", attrs)
                        for ts, attrs in list(self._phase_events)
                        if ts >= decode_start][-8:]
        self._span("llm.decode", request, decode_start, now,
                   status="OK" if reason in ("stop", "length") else "ERROR",
                   events=phase_events or None,
                   **{"gen_ai.usage.completion_tokens": n,
                      "llm.finish_reason": reason,
                      "llm.kv_pages": self.allocator.slot_pages(request.slot)})

    # ---------------------------------------------------------------- plumbing

    def _sync_tables(self) -> None:
        """Refresh the device block table — but only when the allocator
        marked rows dirty since the last sync. Steady-state decode (no
        page growth, no finishes) uploads NOTHING: the previous table
        rides through the donated kv pytree unchanged."""
        if self.allocator.dirty:
            # upload under the table's existing (replicated NamedSharding)
            # placement: the pjit cache keys on input shardings, so a bare
            # jnp.array here — single-device, uncommitted — would recompile
            # every warmup-built executable at its first traffic hit
            self.kv = self.kv._replace(block_tables=jax.device_put(
                self.allocator.tables(), self.kv.block_tables.sharding))

    def _emit(self, request: GenRequest, token: int) -> None:
        request.generated.append(token)
        self.stats.completion_tokens += 1
        if self.ledger is not None:
            # same site as stats.completion_tokens (conservation gate);
            # counting at retire rather than finish means a failover
            # never loses a killed replica's already-emitted tokens
            self.ledger.add(request.tenant, generated_tokens=1)
        if request.first_token_ts == 0.0:
            request.first_token_ts = time.time()
            if not request.ttft_observed:
                request.ttft_observed = True
                if self.signals is not None:
                    self.signals.publish(
                        "llm.ttft_ms",
                        max(0.0, request.first_token_ts - request.created)
                        * 1e3,
                        self.config.replica_id)
                if self.metrics is not None:
                    ttft_s = max(0.0,
                                 request.first_token_ts - request.created)
                    tenant = self._tenant_label(request)
                    self.metrics.llm_ttft.labels(
                        model=self.config.model,
                        replica=self.config.replica_id,
                        tenant=tenant).observe(
                        ttft_s, exemplar=self._exemplar(
                            "llm_ttft", ttft_s, request,
                            (self.config.model, self.config.replica_id,
                             tenant)))
                self._span("llm.prefill", request, request.created
                           + request.queue_ms / 1e3, request.first_token_ts,
                           **{"gen_ai.usage.prompt_tokens":
                                  len(request.prompt_ids),
                              "llm.prefill_ms": round(request.prefill_ms, 2),
                              "llm.bucket": request.bucket,
                              "llm.cached_prefix_tokens": request.hist,
                              "llm.chunked": request.chunked,
                              "llm.kv_pages": self.allocator.slot_pages(
                                  request.slot)})
        done = (token == self.tokenizer.eos_id or token in request.stop_ids
                or len(request.generated) >= request.max_tokens)
        if done and request.finish_reason is None:
            request.finish_reason = ("stop" if (token == self.tokenizer.eos_id
                                                or token in request.stop_ids)
                                     else "length")
        if done:
            self._observe_finish(request)  # before free_slot: pages still held
            self._running.pop(request.slot, None)
            self.allocator.free_slot(request.slot)
            # no table sync here: free_slot marked the row dirty, and every
            # device dispatch path syncs before submitting
        self._post_tokens(request, [token], done=done)

    def _finish(self, request: GenRequest) -> None:
        self._observe_finish(request)
        self._running.pop(request.slot, None)
        self.allocator.free_slot(request.slot)
        self._post_tokens(request, [], done=True)

    def _post_tokens(self, request: GenRequest, tokens: list[int],
                     done: bool) -> None:
        """Queue tokens for the consumer. Posts accumulate in a step-local
        buffer (merged per request) and hop to the asyncio loop in ONE
        call_soon_threadsafe per flush — one loop wakeup per engine step,
        not one per token (the old per-token wakeups were measurable
        scheduler pressure at decode_block/spec widths > 1)."""
        buf = self._emit_buf
        if buf and buf[-1][0] is request and not buf[-1][2]:
            buf[-1][1].extend(tokens)
            buf[-1][2] = done
        else:
            buf.append([request, list(tokens), done])

    def _flush_emits(self) -> None:
        """Deliver everything buffered by _post_tokens in one loop hop.
        Called once per dispatch-loop iteration and at the end of every
        termination path (fail/crash/stop), so no consumer can strand on
        an unflushed buffer."""
        if not self._emit_buf:
            return
        batch, self._emit_buf = self._emit_buf, []
        loop = self._loop

        def _put() -> None:
            for request, tokens, done in batch:
                for token in tokens:
                    request.stream.put_nowait(token)
                if done:
                    request.stream.put_nowait(None)

        if loop is not None and not loop.is_closed():
            try:
                loop.call_soon_threadsafe(_put)
                return
            except RuntimeError:
                pass  # loop shut down mid-flight; fall through
        _put()  # no loop (tests driving the thread directly)

    # ------------------------------------------------------------ embeddings

    def kv_pages_in_use(self) -> int:
        return self.allocator.pages_in_use

    def kv_bytes_in_use(self) -> int:
        """HBM bytes the in-use KV pages occupy under the active storage
        dtype (int8 pages cost half their bf16 twin plus a scale sliver)."""
        return self.allocator.pages_in_use * self._kv_page_bytes

    def tier_stats(self) -> dict[str, Any] | None:
        """Tiered-prefix-cache snapshot for the stats/pool/admin
        surfaces: per-tier hit split (consume-site, conserves against
        prefix_hit_tokens), spill/restore counts + restore p95, and the
        shared store's per-tier footprint. None when no tier client is
        wired (prefix_tiers off AND no pool index)."""
        if self._tier_client is None:
            return None
        out = self._tier_client.stats()
        out["enabled"] = self._tier_client.store is not None
        out["hits"] = dict(self.allocator.tier_hits)
        out["hit_tokens"] = dict(self.allocator.tier_hit_tokens)
        return out

    def kv_bytes_capacity(self) -> int:
        """HBM bytes the whole KV pool occupies (fixed at construction)."""
        return self.num_kv_pages * self._kv_page_bytes
