"""Continuous-batching inference engine.

The crux component (SURVEY.md §7.2 #1): an asyncio front (request queue,
tokenizer, per-request token streams) bridged to a device loop that
interleaves bucketed prefill with fixed-capacity decode steps over the paged
KV cache. XLA's static-shape discipline is respected everywhere:

- prefill compiles once per (bucket, batch=1) shape from
  ``tpu_local_prefill_buckets``;
- decode compiles once for the full [max_batch] slot array — inactive slots
  ride along masked (position 0 into the trash page);
- sampling params are per-slot device arrays, so mixed greedy/temperature
  requests share one compiled step.

The engine is a single-owner of its mesh/slice: gateway workers reach it
in-process (single worker) or over the /v1 HTTP surface (multi-worker),
mirroring the reference's session-affinity routing (SURVEY.md §7.1 phase 4).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator

import jax
import jax.numpy as jnp
import numpy as np

from .kv import PageAllocator, init_kv_state, kv_logical
from .models import MODEL_CONFIGS, LlamaConfig
from .models.llama import decode_step, init_params, params_logical, prefill
from .parallel import make_mesh, param_specs
from .sampling import SamplingParams, sample_tokens
from .tokenizer import load_tokenizer

logger = logging.getLogger(__name__)


@dataclass
class EngineConfig:
    model: str = "llama3-tiny"
    checkpoint: str = ""
    max_batch: int = 8              # decode slots
    max_seq_len: int = 2048
    page_size: int = 128
    num_pages: int = 512
    prefill_buckets: tuple[int, ...] = (128, 512, 2048)
    mesh_shape: str = ""
    dtype: str = "bfloat16"
    max_queue: int = 1024
    attn_impl: str = "auto"

    @classmethod
    def from_settings(cls, settings) -> "EngineConfig":
        return cls(
            model=settings.tpu_local_model,
            checkpoint=settings.tpu_local_checkpoint,
            max_batch=settings.tpu_local_max_batch,
            max_seq_len=settings.tpu_local_max_seq_len,
            page_size=settings.tpu_local_page_size,
            num_pages=settings.tpu_local_num_pages,
            prefill_buckets=tuple(settings.tpu_local_prefill_buckets),
            mesh_shape=settings.tpu_local_mesh_shape,
            dtype=settings.tpu_local_dtype,
        )


@dataclass
class GenRequest:
    request_id: str
    prompt_ids: list[int]
    max_tokens: int = 128
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop_ids: tuple[int, ...] = ()
    # unbounded: tokens are ints bounded by max_tokens, and a bounded queue
    # could drop the end-of-stream sentinel and hang the consumer
    stream: asyncio.Queue = field(default_factory=asyncio.Queue)
    created: float = field(default_factory=time.time)
    # filled by the engine
    slot: int = -1
    generated: list[int] = field(default_factory=list)
    finish_reason: str | None = None
    prefill_ms: float = 0.0
    queue_ms: float = 0.0


class EngineStats:
    def __init__(self) -> None:
        self.requests = 0
        self.prompt_tokens = 0
        self.completion_tokens = 0
        self.decode_steps = 0
        self.queue_depth = 0


class TPUEngine:
    """Owns params + KV pool on the mesh; runs the scheduler loop."""

    def __init__(self, config: EngineConfig):
        self.config = config
        self.model_config: LlamaConfig = MODEL_CONFIGS[config.model]
        self.tokenizer = load_tokenizer(config.checkpoint,
                                        vocab_size=self.model_config.vocab_size)
        self.stats = EngineStats()
        self._queue: asyncio.Queue[GenRequest] = asyncio.Queue(maxsize=config.max_queue)
        self._running: dict[int, GenRequest] = {}  # slot -> request
        self._loop_task: asyncio.Task | None = None
        self._started = False
        self._dirty_tables = True

        dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
        self.mesh = make_mesh(config.mesh_shape)
        logger.info("tpu_local: mesh %s, model %s", self.mesh.shape, config.model)

        # params: load checkpoint or random-init, placed with TP shardings
        with self.mesh:
            shardings = param_specs(params_logical(self.model_config), self.mesh)
            if config.checkpoint:
                from .checkpoint import load_params
                self.params = load_params(config.checkpoint, self.model_config,
                                          shardings, dtype)
            else:
                init = jax.jit(partial(init_params, self.model_config, dtype=dtype),
                               out_shardings=shardings)
                self.params = init(jax.random.PRNGKey(0))

            max_pages_per_slot = config.max_seq_len // config.page_size
            from .kv import PagedKVState
            from .parallel.sharding import kv_pages_sharding, logical_to_sharding
            pages = kv_pages_sharding(self.mesh, self.model_config.n_kv_heads)
            kv_shardings = PagedKVState(
                k_pages=pages, v_pages=pages,
                block_tables=logical_to_sharding("replicated", self.mesh))
            kv_init = jax.jit(partial(
                init_kv_state, self.model_config, config.num_pages, config.page_size,
                config.max_batch, max_pages_per_slot, dtype=dtype),
                out_shardings=kv_shardings)
            self.kv = kv_init()

        self.allocator = PageAllocator(config.num_pages, config.page_size,
                                       config.max_batch, max_pages_per_slot)
        self._rng = jax.random.PRNGKey(int(time.time()) & 0x7FFFFFFF)

        # compiled steps
        self._prefill = jax.jit(partial(prefill, config=self.model_config,
                                        attn_impl=config.attn_impl),
                                donate_argnames=("kv",))
        self._decode = jax.jit(self._decode_and_sample, donate_argnames=("kv",))

    # ------------------------------------------------------------- device fns

    def _decode_and_sample(self, params, kv, tokens, positions, slot_ids,
                           seq_lens, sampling: SamplingParams, key):
        logits, kv = decode_step(params, self.model_config, tokens, positions,
                                 kv, slot_ids, seq_lens)
        next_tokens = sample_tokens(logits, sampling, key)
        return next_tokens, kv

    # --------------------------------------------------------------- lifecycle

    async def start(self) -> None:
        if not self._started:
            self._started = True
            self._loop_task = asyncio.create_task(self._scheduler_loop())

    async def stop(self) -> None:
        self._started = False
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass
            self._loop_task = None

    # ------------------------------------------------------------- submission

    async def submit(self, request: GenRequest) -> GenRequest:
        self.stats.requests += 1
        self.stats.prompt_tokens += len(request.prompt_ids)
        await self._queue.put(request)
        self.stats.queue_depth = self._queue.qsize()
        return request

    async def generate(self, prompt_ids: list[int], **kwargs) -> AsyncIterator[int]:
        """Submit and yield token ids as they decode."""
        from ..utils.ids import new_id
        request = GenRequest(request_id=new_id(), prompt_ids=prompt_ids, **kwargs)
        await self.submit(request)
        while True:
            token = await request.stream.get()
            if token is None:
                break
            yield token

    # ---------------------------------------------------------------- schedule

    def _bucket_for(self, length: int) -> int | None:
        for bucket in sorted(self.config.prefill_buckets):
            if length <= bucket:
                return bucket
        return None

    async def _scheduler_loop(self) -> None:
        config = self.config
        decode_interval = 0.0
        while True:
            did_work = False
            # 1) admit waiting requests while slots + pages are free
            while (len(self._running) < config.max_batch and not self._queue.empty()):
                request = self._queue.get_nowait()
                admitted = await self._admit(request)
                did_work = did_work or admitted
                if not admitted:
                    break
            # 2) one decode step over the running batch
            if self._running:
                await self._decode_step_all()
                did_work = True
            self.stats.queue_depth = self._queue.qsize()
            if not did_work:
                await asyncio.sleep(0.002)
            else:
                await asyncio.sleep(decode_interval)  # yield to the event loop

    async def _admit(self, request: GenRequest) -> bool:
        """Allocate a slot + pages, run prefill, enqueue first token."""
        config = self.config
        n_prompt = len(request.prompt_ids)
        bucket = self._bucket_for(n_prompt)
        if bucket is None:
            request.finish_reason = "length"
            await request.stream.put(None)
            return True  # consumed (rejected)
        free_slots = [s for s in range(config.max_batch) if s not in self._running]
        if not free_slots:
            await self._requeue(request)
            return False
        total = min(n_prompt + request.max_tokens, config.max_seq_len)
        slot = free_slots[0]
        if not self.allocator.allocate_slot(slot, total):
            await self._requeue(request)
            return False

        request.slot = slot
        request.queue_ms = (time.time() - request.created) * 1000
        self._running[slot] = request
        self._sync_tables()

        started = time.monotonic()
        tokens = np.full((1, bucket), self.tokenizer.pad_id, dtype=np.int32)
        positions = np.full((1, bucket), -1, dtype=np.int32)
        tokens[0, :n_prompt] = request.prompt_ids
        positions[0, :n_prompt] = np.arange(n_prompt)
        logits, self.kv = self._prefill(
            self.params, tokens=jnp.asarray(tokens), positions=jnp.asarray(positions),
            kv=self.kv, slot_ids=jnp.array([slot]))
        # sample the first generated token from the last prompt position
        last = jax.device_get(logits[0, n_prompt - 1])
        first_token = self._sample_host(last, request)
        request.prefill_ms = (time.monotonic() - started) * 1000
        await self._emit(request, first_token)
        return True

    async def _requeue(self, request: GenRequest) -> None:
        # put back at the front is not supported by asyncio.Queue; re-put and
        # let FIFO order approximate fairness
        await self._queue.put(request)

    def _sample_host(self, logits: np.ndarray, request: GenRequest) -> int:
        if request.temperature <= 0:
            return int(np.argmax(logits))
        scaled = logits / max(request.temperature, 1e-6)
        if request.top_k > 0:
            kth = np.partition(scaled, -request.top_k)[-request.top_k]
            scaled = np.where(scaled >= kth, scaled, -np.inf)
        probs = np.exp(scaled - scaled.max())
        if request.top_p < 1.0:
            order = np.argsort(probs)[::-1]
            cum = np.cumsum(probs[order]) / probs.sum()
            cutoff = np.searchsorted(cum, request.top_p) + 1
            mask = np.zeros_like(probs, dtype=bool)
            mask[order[:cutoff]] = True
            probs = np.where(mask, probs, 0.0)
        probs = probs / probs.sum()
        return int(np.random.choice(len(probs), p=probs))

    def _sync_tables(self) -> None:
        self.kv = self.kv._replace(block_tables=self.allocator.tables())

    async def _emit(self, request: GenRequest, token: int) -> None:
        request.generated.append(token)
        self.stats.completion_tokens += 1
        done = (token == self.tokenizer.eos_id or token in request.stop_ids
                or len(request.generated) >= request.max_tokens)
        request.stream.put_nowait(token)
        if done:
            if request.finish_reason is None:
                request.finish_reason = ("stop" if (token == self.tokenizer.eos_id
                                                    or token in request.stop_ids)
                                         else "length")
            await self._finish(request)

    async def _finish(self, request: GenRequest) -> None:
        self._running.pop(request.slot, None)
        self.allocator.free_slot(request.slot)
        self._sync_tables()
        request.stream.put_nowait(None)

    async def _decode_step_all(self) -> None:
        """One fixed-shape decode step over every active slot."""
        config = self.config
        B = config.max_batch
        tokens = np.zeros((B,), dtype=np.int32)
        positions = np.zeros((B,), dtype=np.int32)
        seq_lens = np.zeros((B,), dtype=np.int32)
        temperature = np.zeros((B,), dtype=np.float32)
        top_k = np.zeros((B,), dtype=np.int32)
        top_p = np.ones((B,), dtype=np.float32)
        active = list(self._running.items())
        for slot, request in active:
            # n_ctx counts every token that exists (prompt + generated); the
            # last generated token is the incoming input: it sits at 0-based
            # position n_ctx-1 and is written to the cache this step, after
            # which the slot's context length is n_ctx.
            n_ctx = len(request.prompt_ids) + len(request.generated)
            tokens[slot] = request.generated[-1]
            positions[slot] = n_ctx - 1
            seq_lens[slot] = n_ctx
            temperature[slot] = request.temperature
            top_k[slot] = request.top_k
            top_p[slot] = request.top_p
            if not self.allocator.extend_slot(slot, n_ctx):
                request.finish_reason = "length"
        self._sync_tables()
        sampling = SamplingParams(jnp.asarray(temperature), jnp.asarray(top_k),
                                  jnp.asarray(top_p))
        self._rng, key = jax.random.split(self._rng)
        next_tokens, self.kv = self._decode(
            self.params, self.kv, jnp.asarray(tokens), jnp.asarray(positions),
            jnp.arange(B, dtype=jnp.int32), jnp.asarray(seq_lens), sampling, key)
        self.stats.decode_steps += 1
        next_host = jax.device_get(next_tokens)
        for slot, request in active:
            if request.finish_reason == "length" and request.slot in self._running:
                await self._finish(request)
                continue
            await self._emit(request, int(next_host[slot]))

    # ------------------------------------------------------------ embeddings

    def kv_pages_in_use(self) -> int:
        return self.allocator.pages_in_use
