"""Training step (fine-tuning path + the driver's multi-chip dry-run).

The gateway itself serves inference, but the engine's model stack is fully
differentiable: this module provides next-token cross-entropy loss and an
optax AdamW step, pjit-sharded DP×TP over the same mesh/sharding rules as
serving (batch over ``data``, params over ``model``), so checkpoints can be
fine-tuned in place on the slice that serves them.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .models.configs import LlamaConfig
from .models.llama import _attention_block, _ffn_block, lm_logits, rms_norm
from .ops.attention import causal_attention
from .parallel.sharding import param_specs
from .models.llama import params_logical


def forward_logits(params: dict[str, Any], config: LlamaConfig,
                   tokens: jax.Array, attn_impl: str = "reference",
                   return_aux: bool = False):
    """Plain forward (no KV cache) for training: tokens [B,S] -> logits
    fp32. ``return_aux=True`` also returns the Switch-style router
    load-balancing loss (E * sum_e f_e * P_e, averaged over MoE layers)
    computed inside the SAME forward — without it, MoE fine-tuning can
    collapse routing onto a few experts (nothing else pushes back; the
    drop-free serving formulation happily computes a collapsed
    router)."""
    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
    x = params["embed"][tokens]
    if config.embed_multiplier != 1.0:  # Gemma sqrt(dim) scaling
        x = x * jnp.asarray(config.embed_multiplier, dtype=x.dtype)
    aux = jnp.zeros((), jnp.float32)
    n_moe = 0
    for layer in params["layers"]:
        h = rms_norm(x, layer["attn_norm"], config.norm_eps, config.norm_plus_one)
        q, k, v = _attention_block(layer, config, h, positions)
        attn = causal_attention(q, k, v, impl=attn_impl)
        x = x + attn.reshape(B, S, -1) @ layer["wo"]
        h = rms_norm(x, layer["ffn_norm"], config.norm_eps, config.norm_plus_one)
        if return_aux and "router" in layer:
            from .parallel.moe import router_probs

            probs = router_probs(layer["router"],
                                 h.reshape(-1, config.dim))
            top1 = jnp.argmax(probs, axis=-1)
            frac = jnp.mean(
                jax.nn.one_hot(top1, config.n_experts, dtype=jnp.float32),
                axis=0)
            aux = aux + config.n_experts * jnp.sum(frac
                                                   * jnp.mean(probs, axis=0))
            n_moe += 1
        x = x + _ffn_block(layer, config, h)
    x = rms_norm(x, params["final_norm"], config.norm_eps, config.norm_plus_one)
    logits = lm_logits(params, x)
    if return_aux:
        return logits, aux / jnp.maximum(n_moe, 1)
    return logits


def loss_fn(params: dict[str, Any], config: LlamaConfig, tokens: jax.Array,
            targets: jax.Array, mask: jax.Array,
            attn_impl: str = "reference",
            moe_aux_weight: float = 0.01) -> jax.Array:
    if config.n_experts:
        logits, aux = forward_logits(params, config, tokens, attn_impl,
                                     return_aux=True)
    else:
        logits, aux = forward_logits(params, config, tokens, attn_impl), 0.0
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    ce = -(picked * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return ce + moe_aux_weight * aux


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def make_optimizer(lr: float = 1e-4, weight_decay: float = 0.01):
    return optax.adamw(lr, weight_decay=weight_decay)


def train_step(state: TrainState, config: LlamaConfig, optimizer,
               tokens: jax.Array, targets: jax.Array, mask: jax.Array,
               attn_impl: str = "reference") -> tuple[TrainState, jax.Array]:
    loss, grads = jax.value_and_grad(loss_fn)(state.params, config, tokens,
                                              targets, mask, attn_impl)
    updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss


def build_sharded_train_step(mesh: Mesh, config: LlamaConfig, lr: float = 1e-4):
    """pjit the full train step over the mesh: DP on batch, TP on params.

    Returns (jitted_step, init_state_fn)."""
    optimizer = make_optimizer(lr)
    p_shardings = param_specs(params_logical(config), mesh)
    data_sharding = NamedSharding(mesh, P("data", None))
    replicated = NamedSharding(mesh, P())

    def init_state(key: jax.Array) -> TrainState:
        from .models.llama import init_params
        params = init_params(config, key, dtype=jnp.float32)
        return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))

    opt_shardings = None

    def _infer_state_shardings(state_shape) -> TrainState:
        # params get their TP shardings; optimizer state mirrors param tree
        # shapes — shard any leaf whose shape matches a param leaf, else
        # replicate (adamw mu/nu mirror params exactly).
        flat_params, _ = jax.tree.flatten(p_shardings)

        def match(leaf_shape, candidates):
            for sharding, pshape in candidates:
                if leaf_shape == pshape:
                    return sharding
            return replicated

        param_leaves = jax.tree.leaves(state_shape.params)
        candidates = list(zip(flat_params, [l.shape for l in param_leaves]))
        opt = jax.tree.map(lambda leaf: match(leaf.shape, candidates),
                           state_shape.opt_state)
        return TrainState(p_shardings, opt, replicated)

    init_shape = jax.eval_shape(init_state, jax.random.PRNGKey(0))
    state_shardings = _infer_state_shardings(init_shape)

    jit_init = jax.jit(init_state, out_shardings=state_shardings)

    step_fn = partial(train_step, config=config, optimizer=optimizer,
                      attn_impl="reference")
    jit_step = jax.jit(
        lambda state, tokens, targets, mask: step_fn(
            state, tokens=tokens, targets=targets, mask=mask),
        in_shardings=(state_shardings, data_sharding, data_sharding, data_sharding),
        out_shardings=(state_shardings, replicated),
        donate_argnums=(0,))
    return jit_step, jit_init
