"""Shared engine plane: one pool owner, N serving workers (scale-out).

The multi-worker gateway (docs/scaleout.md) forks N processes over one
listening socket — but the EnginePool owns HBM, and N pools would
duplicate weights and shred the KV budget. This module keeps ONE pool:

- every worker runs :class:`SharedEnginePlane`; they all contend for the
  ``engine-pool-owner`` lease through the coordination layer (the same
  leases the LeaderElector rides — gateway/app.py wires both);
- the winner builds the real pool/provider via ``provider_factory`` and
  serves the ``pool.*`` RPC methods over the bus RPC seam
  (coordination/rpc.py);
- the others register :class:`SharedPoolProvider` in their LLM registry:
  ``chat``/``chat_stream``/``embed``/``classify`` forward to the current
  owner, carrying the ORIGINATING tenant so the owner's ledger (and the
  distributed limiter reading it) bills the right principal;
- owner death: the lease expires, a survivor wins the next acquire and
  builds a fresh pool; requests that raced the failover surface
  :class:`~.provider.LLMUnavailable` (503 + Retry-After — the PR-14
  contract) and the client retries onto the re-elected owner. In-flight
  pool work on the dead owner follows the pool's OWN requeue path when
  only a replica died; a whole-process death is the 503-and-retry path.

Wire shapes (all JSON over the bus):
  pool.chat        {"body", "tenant"} -> {"ok", "result"} |
                   {"ok": false, "error_type", "message", "retry_after_s"}
  pool.chat_stream same params; chunks are chat.completion.chunk dicts;
                   refusals ride the stream-end error ("LLMUnavailable:…")
  pool.embed       {"texts", "model", "tenant"} -> {"ok", "result"}
  pool.classify    {"texts", "tenant"} -> {"ok", "result"}
  pool.status      {} -> owner stats (worker id, provider wired, models)
  pool.set_role    {"replica", "role"} -> {"ok", "result": replica status}
                   — the disaggregation lease plane: any worker can
                   retarget the owner pool's prefill/decode/any split
                   live (docs/disaggregation.md)
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, AsyncIterator, Awaitable, Callable

from ..observability import tenant as tenant_ctx
from .provider import LLMError, LLMProvider, LLMUnavailable

logger = logging.getLogger(__name__)

LEASE_NAME = "engine-pool-owner"


class SharedEnginePlane:
    """Leader-elected pool ownership + the RPC serving seam."""

    def __init__(self, rpc: Any, leases: Any, worker_id: str,
                 provider_factory: Callable[[], Awaitable[LLMProvider]],
                 lease_ttl: float = 15.0,
                 rpc_timeout_s: float = 120.0,
                 stream_idle_timeout_s: float = 15.0) -> None:
        self.rpc = rpc
        self.leases = leases
        self.worker_id = worker_id
        self.provider_factory = provider_factory
        self.lease_ttl = max(1.0, float(lease_ttl))
        self.rpc_timeout_s = rpc_timeout_s
        self.stream_idle_timeout_s = stream_idle_timeout_s
        self.local_provider: LLMProvider | None = None
        self.is_owner = False
        self.elections_won = 0
        self.build_failures = 0
        self._task: asyncio.Task | None = None
        self._building = False
        # non-owner backpressure: short-TTL cache of the OWNER's queue
        # state, refreshed over bus RPC (see queue_state_sync)
        self.queue_cache_ttl_s = 1.0
        self._queue_cache: dict[str, Any] | None = None
        self._queue_cache_at = 0.0
        self._queue_refresh: asyncio.Task | None = None

    # ------------------------------------------------------------- lifecycle

    async def start(self) -> None:
        self.rpc.register("pool.chat", self._serve_chat)
        self.rpc.register("pool.embed", self._serve_embed)
        self.rpc.register("pool.classify", self._serve_classify)
        self.rpc.register("pool.status", self._serve_status)  # lint: allow[bus-rpc-conformance] operator surface for non-owner workers; local callers use EnginePool.status() directly
        self.rpc.register("pool.set_role", self._serve_set_role)
        self.rpc.register("pool.queue_state", self._serve_queue_state)
        self.rpc.register_stream("pool.chat_stream", self._serve_chat_stream)
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._elector(), name="engine-pool-elector")

    async def stop(self) -> None:
        refresh, self._queue_refresh = self._queue_refresh, None
        if refresh is not None and not refresh.done():
            refresh.cancel()
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        if self.is_owner:
            try:
                await self.leases.release(LEASE_NAME, self.worker_id)
            except Exception:
                pass
        self.is_owner = False
        provider, self.local_provider = self.local_provider, None
        if provider is not None:
            try:
                await provider.shutdown()
            except Exception:
                logger.exception("shared pool provider shutdown failed")

    async def _elector(self) -> None:
        """Contend for pool ownership forever. Winning builds the pool
        (once); holding renews the lease at TTL/3 — the same cadence the
        worker heartbeat uses, so a dead owner's lease expires within
        one TTL and a survivor takes over."""
        while True:
            try:
                got = await self.leases.acquire(LEASE_NAME, self.worker_id,
                                                self.lease_ttl)
                if got:
                    if not self.is_owner:
                        logger.info("shared engine plane: worker %s won "
                                    "pool ownership", self.worker_id)
                        self.elections_won += 1
                    self.is_owner = True
                    if self.local_provider is None and not self._building:
                        await self._build()
                else:
                    self.is_owner = False
            except Exception:
                logger.exception("pool elector iteration failed")
            await asyncio.sleep(self.lease_ttl / 3)

    async def _build(self) -> None:
        self._building = True
        try:
            self.local_provider = await self.provider_factory()
            logger.info("shared engine plane: pool built on worker %s",
                        self.worker_id)
        except Exception:
            self.build_failures += 1
            logger.exception("shared engine plane: pool build FAILED; "
                             "releasing ownership")
            try:
                await self.leases.release(LEASE_NAME, self.worker_id)
            except Exception:
                pass
            self.is_owner = False
        finally:
            self._building = False

    @property
    def ready_local(self) -> bool:
        return self.is_owner and self.local_provider is not None

    async def owner(self) -> str | None:
        try:
            return await self.leases.holder(LEASE_NAME)
        except Exception:
            return None

    async def _remote_owner(self) -> str:
        """The serving owner, waiting one election interval for a
        failover to settle; no owner => LLMUnavailable (503 + retry)."""
        deadline = time.monotonic() + self.lease_ttl
        while time.monotonic() < deadline:
            owner = await self.owner()
            if owner is not None and owner != self.worker_id:
                return owner
            if owner == self.worker_id:
                # we hold the lease but the pool is still building
                if self.ready_local:
                    return self.worker_id
            await asyncio.sleep(min(0.25, self.lease_ttl / 10))
        raise LLMUnavailable(
            "no engine-pool owner elected (failover in progress)",
            retry_after_s=max(1, int(self.lease_ttl / 3)))

    # ----------------------------------------------------------- server side

    def _local(self) -> LLMProvider:
        if self.local_provider is None:
            raise LLMUnavailable("pool not built on this worker yet",
                                 retry_after_s=2)
        return self.local_provider

    @staticmethod
    def _fail(exc: Exception) -> dict[str, Any]:
        out = {"ok": False, "error_type": type(exc).__name__,
               "message": str(exc)}
        if isinstance(exc, LLMUnavailable):
            out["retry_after_s"] = exc.retry_after_s
        return out

    async def _serve_chat(self, params: dict[str, Any]) -> dict[str, Any]:
        token = tenant_ctx.set_current_tenant(params.get("tenant") or "")
        try:
            return {"ok": True,
                    "result": await self._local().chat(
                        params.get("body") or {})}
        except LLMError as exc:
            return self._fail(exc)
        finally:
            tenant_ctx.reset_current_tenant(token)

    async def _serve_chat_stream(self, params: dict[str, Any]
                                 ) -> AsyncIterator[dict[str, Any]]:
        token = tenant_ctx.set_current_tenant(params.get("tenant") or "")
        try:
            async for chunk in self._local().chat_stream(
                    params.get("body") or {}):
                yield chunk
        finally:
            tenant_ctx.reset_current_tenant(token)

    async def _serve_embed(self, params: dict[str, Any]) -> dict[str, Any]:
        token = tenant_ctx.set_current_tenant(params.get("tenant") or "")
        try:
            return {"ok": True,
                    "result": await self._local().embed(
                        list(params.get("texts") or []),
                        model=params.get("model"))}
        except LLMError as exc:
            return self._fail(exc)
        finally:
            tenant_ctx.reset_current_tenant(token)

    async def _serve_classify(self, params: dict[str, Any]) -> dict[str, Any]:
        token = tenant_ctx.set_current_tenant(params.get("tenant") or "")
        try:
            classify = getattr(self._local(), "classify", None)
            if classify is None:
                raise LLMError("owner provider has no classifier head")
            return {"ok": True,
                    "result": await classify(list(params.get("texts") or []))}
        except LLMError as exc:
            return self._fail(exc)
        finally:
            tenant_ctx.reset_current_tenant(token)

    async def _serve_set_role(self, params: dict[str, Any]
                              ) -> dict[str, Any]:
        """Live role reassignment on the owner pool (disaggregation's
        dynamic lease plane): routing-only state, no drain needed."""
        try:
            pool = getattr(self._local(), "engine", None)
            set_role = getattr(pool, "set_role", None)
            if set_role is None:
                raise LLMError("owner provider is not pool-backed "
                               "(roles need an EnginePool)")
            return {"ok": True,
                    "result": set_role(str(params.get("replica", "")),
                                       str(params.get("role", "")))}
        except (LLMError, KeyError, ValueError) as exc:
            return self._fail(exc)

    async def _serve_status(self, params: dict[str, Any]) -> dict[str, Any]:
        provider = self.local_provider
        return {"worker_id": self.worker_id, "is_owner": self.is_owner,
                "provider_ready": provider is not None,
                "models": (await provider.models()) if provider else []}

    def _local_queue_state(self) -> dict[str, Any] | None:
        """Admission state of the locally-built pool (owner only)."""
        from ..gateway.flight_recorder import compute_queue_state
        backend = getattr(self.local_provider, "engine", None)
        if backend is None:
            return None
        if hasattr(backend, "replicas"):
            return compute_queue_state(backend, None)
        return compute_queue_state(None, backend)

    async def _serve_queue_state(self, params: dict[str, Any]
                                 ) -> dict[str, Any]:
        """The owner's queue depth/capacity/saturation — the
        backpressure truth every non-owner worker's X-Queue-Depth /
        Retry-After / shed decision must reflect (a worker-local zero
        here is a lie: the worker has no engine, the owner does)."""
        return {"ok": True, "result": self._local_queue_state()}

    # ----------------------------------------------------------- client side

    def queue_state_sync(self) -> dict[str, Any] | None:
        """Backpressure state for THIS worker, synchronously: the local
        pool on the owner; elsewhere the owner's state via a short-TTL
        bus-RPC cache (refreshed in the background — the per-request
        path must not block on a hub round-trip). Returns None until the
        first refresh lands / when no owner is reachable: "no signal",
        which callers render as no backpressure headers — never a fake
        zero depth."""
        if self.ready_local:
            return self._local_queue_state()
        now = time.monotonic()
        if (self._queue_cache_at and
                now - self._queue_cache_at <= self.queue_cache_ttl_s):
            return self._queue_cache
        if self._queue_refresh is None or self._queue_refresh.done():
            try:
                self._queue_refresh = asyncio.get_running_loop(
                ).create_task(self._refresh_queue_cache())
            except RuntimeError:
                return self._queue_cache  # no loop (sync test context)
        return self._queue_cache

    async def _refresh_queue_cache(self) -> None:
        try:
            owner = await self.owner()
            if owner is None or owner == self.worker_id:
                # no elected owner (failover window) or we ARE the owner
                # but the pool is still building: no signal
                self._queue_cache = None
            else:
                resp = await self.rpc.call(
                    owner, "pool.queue_state", {},
                    timeout_s=min(5.0, self.rpc_timeout_s), batch=True)
                self._queue_cache = (resp.get("result")
                                     if resp.get("ok") else None)
        except Exception:
            self._queue_cache = None  # unreachable owner: no signal
        self._queue_cache_at = time.monotonic()

    @staticmethod
    def _raise_remote(resp: dict[str, Any]) -> Any:
        if resp.get("ok"):
            return resp.get("result")
        etype = resp.get("error_type", "LLMError")
        message = resp.get("message", "remote pool error")
        if etype == "LLMUnavailable":
            raise LLMUnavailable(message,
                                 retry_after_s=resp.get("retry_after_s", 1))
        raise LLMError(message)

    async def _call(self, method: str, params: dict[str, Any]) -> Any:
        from ..coordination.rpc import RpcError
        params["tenant"] = tenant_ctx.current_tenant()
        if self.ready_local:
            handler = {"pool.chat": self._serve_chat,
                       "pool.embed": self._serve_embed,
                       "pool.classify": self._serve_classify,
                       "pool.set_role": self._serve_set_role}[method]
            return self._raise_remote(await handler(params))
        owner = await self._remote_owner()
        try:
            return self._raise_remote(
                await self.rpc.call(owner, method, params,
                                    timeout_s=self.rpc_timeout_s))
        except RpcError as exc:
            # owner died mid-call / partition: 503 + Retry-After — the
            # next attempt lands on the re-elected owner
            raise LLMUnavailable(
                f"pool owner unreachable: {exc}",
                retry_after_s=max(1, int(self.lease_ttl / 3))) from exc

    async def chat(self, request: dict[str, Any]) -> dict[str, Any]:
        return await self._call("pool.chat", {"body": request})

    async def chat_stream(self, request: dict[str, Any]
                          ) -> AsyncIterator[dict[str, Any]]:
        from ..coordination.rpc import RpcAppError, RpcError
        tenant = tenant_ctx.current_tenant()
        if self.ready_local:
            async for chunk in self._serve_chat_stream(
                    {"body": request, "tenant": tenant}):
                yield chunk
            return
        owner = await self._remote_owner()
        try:
            async for chunk in self.rpc.call_stream(
                    owner, "pool.chat_stream",
                    {"body": request, "tenant": tenant},
                    idle_timeout_s=self.stream_idle_timeout_s):
                yield chunk
        except RpcAppError as exc:
            message = str(exc)
            if message.startswith("LLMUnavailable"):
                raise LLMUnavailable(message.split(":", 1)[-1].strip() or
                                     message) from exc
            raise LLMError(message) from exc
        except RpcError as exc:
            raise LLMUnavailable(
                f"pool owner lost mid-stream: {exc}",
                retry_after_s=max(1, int(self.lease_ttl / 3))) from exc

    async def embed(self, texts: list[str],
                    model: str | None = None) -> list[list[float]]:
        return await self._call("pool.embed", {"texts": texts,
                                               "model": model})

    async def classify(self, texts: list[str]) -> list[float]:
        return await self._call("pool.classify", {"texts": texts})

    async def set_role(self, replica: str, role: str) -> dict[str, Any]:
        """Retarget one owner-pool replica's role from ANY worker — the
        dynamic half of disaggregation's role assignment."""
        return await self._call("pool.set_role",
                                {"replica": replica, "role": role})

    def stats(self) -> dict[str, Any]:
        return {"worker_id": self.worker_id, "is_owner": self.is_owner,
                "provider_ready": self.local_provider is not None,
                "elections_won": self.elections_won,
                "build_failures": self.build_failures}


class SharedPoolProvider(LLMProvider):
    """LLM registry provider backed by the shared plane: local calls on
    the owning worker, RPC forwarding elsewhere — every worker serves
    LLM traffic, one copy of HBM state."""

    provider_type = "tpu_local_shared"

    def __init__(self, name: str, plane: SharedEnginePlane) -> None:
        self.name = name
        self.plane = plane

    async def chat(self, request: dict[str, Any]) -> dict[str, Any]:
        return await self.plane.chat(request)

    async def chat_stream(self, request: dict[str, Any]
                          ) -> AsyncIterator[dict[str, Any]]:
        async for chunk in self.plane.chat_stream(request):
            yield chunk

    async def embed(self, texts: list[str],
                    model: str | None = None) -> list[list[float]]:
        return await self.plane.embed(texts, model=model)

    async def classify(self, texts: list[str]) -> list[float]:
        return await self.plane.classify(texts)

    async def shutdown(self) -> None:
        await self.plane.stop()
