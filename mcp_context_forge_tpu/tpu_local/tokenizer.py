"""Tokenization.

Two backends behind one interface:
- ``HFTokenizer``: loads a ``tokenizer.json`` (HuggingFace ``tokenizers``)
  from the checkpoint dir — the real Llama-3 BPE when weights are provided.
- ``ByteTokenizer``: dependency-free byte-level fallback (256 bytes +
  specials) used by the tiny configs and in CI where no vocab can be
  downloaded (zero-egress environments).
"""

from __future__ import annotations

import os
from typing import Protocol


class Tokenizer(Protocol):
    vocab_size: int
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str, add_bos: bool = True) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """ids 0..255 = bytes; 256=bos, 257=eos, 258=pad."""

    def __init__(self, vocab_size: int = 512):
        self.vocab_size = vocab_size
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8", errors="replace"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    def __init__(self, path: str):
        from tokenizers import Tokenizer as _HF
        self._tok = _HF.from_file(path)
        self.vocab_size = self._tok.get_vocab_size()
        self.bos_id = self._special("<|begin_of_text|>", 128000)
        self.eos_id = self._special("<|eot_id|>", 128009)
        self.pad_id = self.eos_id

    def _special(self, token: str, default: int) -> int:
        tid = self._tok.token_to_id(token)
        return tid if tid is not None else default

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def load_tokenizer(checkpoint_dir: str = "", vocab_size: int = 512) -> Tokenizer:
    if checkpoint_dir:
        path = os.path.join(checkpoint_dir, "tokenizer.json")
        if os.path.exists(path):
            return HFTokenizer(path)
    return ByteTokenizer(vocab_size=vocab_size)


def render_chat(messages: list[dict], add_generation_prompt: bool = True) -> str:
    """Llama-3-style chat template (plain-text rendering)."""
    parts = []
    for msg in messages:
        role = msg.get("role", "user")
        content = msg.get("content", "")
        if isinstance(content, list):  # OpenAI content-part arrays
            content = "".join(p.get("text", "") for p in content
                              if isinstance(p, dict))
        parts.append(f"<|start_header_id|>{role}<|end_header_id|>\n{content}<|eot_id|>")
    if add_generation_prompt:
        parts.append("<|start_header_id|>assistant<|end_header_id|>\n")
    return "".join(parts)
