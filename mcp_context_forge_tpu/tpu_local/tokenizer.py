"""Tokenization.

Two backends behind one interface:
- ``HFTokenizer``: loads a ``tokenizer.json`` (HuggingFace ``tokenizers``)
  from the checkpoint dir — the real Llama-3 BPE when weights are provided.
- ``ByteTokenizer``: dependency-free byte-level fallback (256 bytes +
  specials) used by the tiny configs and in CI where no vocab can be
  downloaded (zero-egress environments).
"""

from __future__ import annotations

import os
from typing import Protocol


class Tokenizer(Protocol):
    vocab_size: int
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str, add_bos: bool = True) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """ids 0..255 = bytes; 256=bos, 257=eos, 258=pad; 259+ = chat-template
    markers. The markers encode as ONE token each — exactly how the real
    Llama-3 BPE treats its special tokens — otherwise every chat turn pays
    ~90 extra byte-tokens of template scaffolding, which on the tiny CPU
    proxies dominates prefill compute (3x the user content)."""

    SPECIALS = ("<|start_header_id|>", "<|end_header_id|>", "<|eot_id|>")

    def __init__(self, vocab_size: int = 512):
        self.vocab_size = vocab_size
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258
        self._special_ids = {tok: 259 + i
                             for i, tok in enumerate(self.SPECIALS)}
        self._id_specials = {i: tok for tok, i in self._special_ids.items()}

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids: list[int] = [self.bos_id] if add_bos else []
        i = 0
        while i < len(text):
            for tok, tid in self._special_ids.items():
                if text.startswith(tok, i):
                    ids.append(tid)
                    i += len(tok)
                    break
            else:
                # longest run of plain text until the next special
                nxt = min((text.find(t, i) for t in self.SPECIALS
                           if text.find(t, i) != -1), default=len(text))
                ids.extend(text[i:nxt].encode("utf-8", errors="replace"))
                i = nxt
        return ids

    def decode(self, ids: list[int]) -> str:
        out: list[str] = []
        run: list[int] = []
        for i in ids:
            if 0 <= i < 256:
                run.append(i)
            else:
                if run:
                    out.append(bytes(run).decode("utf-8", errors="replace"))
                    run = []
                # specials are dropped from decoded text (HF parity:
                # skip_special_tokens=True)
        if run:
            out.append(bytes(run).decode("utf-8", errors="replace"))
        return "".join(out)


class HFTokenizer:
    def __init__(self, path: str):
        from tokenizers import Tokenizer as _HF
        self._tok = _HF.from_file(path)
        self.vocab_size = self._tok.get_vocab_size()
        self.bos_id = self._special("<|begin_of_text|>", 128000)
        self.eos_id = self._special("<|eot_id|>", 128009)
        self.pad_id = self.eos_id

    def _special(self, token: str, default: int) -> int:
        tid = self._tok.token_to_id(token)
        return tid if tid is not None else default

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def load_tokenizer(checkpoint_dir: str = "", vocab_size: int = 512) -> Tokenizer:
    if checkpoint_dir:
        path = os.path.join(checkpoint_dir, "tokenizer.json")
        if os.path.exists(path):
            return HFTokenizer(path)
    return ByteTokenizer(vocab_size=vocab_size)


def render_chat(messages: list[dict], add_generation_prompt: bool = True,
                tools: list[dict] | None = None) -> str:
    """Llama-3-style chat template (plain-text rendering).

    Function-calling parity (OpenAI wire shapes -> prompt text):
    - ``tools`` renders as a system block of JSON function signatures
      (tool_calls.render_tools_block, Llama-3.1 convention);
    - assistant messages carrying ``tool_calls`` render the call JSON so
      the model sees its prior calls in-context;
    - ``tool`` role messages render under the ``ipython`` header —
      Llama 3's tool-response role."""
    parts = []
    if tools:
        from .tool_calls import render_tools_block

        parts.append(f"<|start_header_id|>system<|end_header_id|>\n"
                     f"{render_tools_block(tools)}<|eot_id|>")
    for msg in messages:
        role = msg.get("role", "user")
        content = msg.get("content", "")
        if isinstance(content, list):  # OpenAI content-part arrays
            content = "".join(p.get("text", "") for p in content
                              if isinstance(p, dict))
        if role == "assistant" and msg.get("tool_calls"):
            from .tool_calls import tool_call_message_text

            call_text = tool_call_message_text(msg["tool_calls"])
            content = f"{content}\n{call_text}" if content else call_text
        elif role == "tool":
            role = "ipython"
        parts.append(f"<|start_header_id|>{role}<|end_header_id|>\n{content}<|eot_id|>")
    if add_generation_prompt:
        parts.append("<|start_header_id|>assistant<|end_header_id|>\n")
    return "".join(parts)
