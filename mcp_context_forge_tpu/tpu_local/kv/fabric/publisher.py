"""Fabric gossip: advertise this host's object-resident chains.

One :class:`FabricIndexPublisher` runs per gateway host (asyncio task on
the gateway loop). Each tick it:

1. sweeps the local :class:`~.index.FabricIndex` (TTL expiry);
2. snapshots the chain hashes the local
   :class:`~..tiers.TieredPageStore` has durably persisted to the T3
   object store;
3. pushes them as one :class:`~.index.FabricAdvert` to every peer —
   in-fleet workers over the ``fabric.advert`` bus-RPC method (the hub
   relays frames between supervised worker processes), cross-supervisor
   hosts over ``POST /admin/fabric/adverts`` (the HTTP exchange returns
   the peer's own adverts, so a ONE-WAY peer list still converges both
   ways).

Receiving side: :meth:`handle_advert` is the bus-RPC handler AND the
HTTP endpoint's core — merge the batch, reply with the local view.

Delivery is best-effort and the protocol is idempotent (merge is
monotone, expiry is the only eviction): a dropped advert only delays
cross-host hits by one interval, never corrupts anything. Failures are
counted, logged once per peer transition, and never raised into the
gateway loop.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Callable, Iterable

from .index import FabricAdvert, FabricIndex, merge_wire_adverts

logger = logging.getLogger(__name__)

#: bus-RPC method name (registered in gateway/app.py; the bus-rpc
#: conformance lint tracks both sides)
ADVERT_METHOD = "fabric.advert"


class FabricIndexPublisher:
    """Advertise local T3 residency; merge what peers advertise back."""

    def __init__(self, store: Any, host_id: str,
                 rpc: Any = None,
                 bus_peers: Callable[[], Iterable[str]] | None = None,
                 http_peers: Iterable[str] = (),
                 post_json: Callable[[str, dict[str, Any]],
                                     Awaitable[dict[str, Any] | None]]
                 | None = None,
                 interval_s: float = 2.0, ttl_s: float = 300.0,
                 rpc_timeout_s: float = 5.0,
                 metrics: Any = None) -> None:
        # the store may materialize AFTER the publisher (leader-elected
        # shared pool builds lazily): accept a zero-arg resolver too
        self._store_src = store
        self.host_id = host_id
        self.rpc = rpc
        self.bus_peers = bus_peers
        self.http_peers = [u.rstrip("/") for u in http_peers if u]
        self.post_json = post_json
        self.interval_s = max(0.05, float(interval_s))
        self.ttl_s = max(1.0, float(ttl_s))
        self.rpc_timeout_s = max(0.1, float(rpc_timeout_s))
        self.metrics = metrics
        self._task: asyncio.Task | None = None
        self._peer_down: set[str] = set()  # log once per peer transition
        self.sent = 0          # adverts pushed to peers
        self.merged_in = 0     # hashes learned from peers
        self.send_failures = 0

    # -------------------------------------------------------------- lifecycle

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run(),
                                             name="fabric-advert")

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while True:
            try:
                await self.publish_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("fabric advert tick failed")
            await asyncio.sleep(self.interval_s)

    # ------------------------------------------------------------------ sends

    @property
    def store(self) -> Any:
        src = self._store_src
        return src() if callable(src) else src

    def _fabric(self) -> FabricIndex | None:
        return getattr(self.store, "fabric", None)

    def _local_advert(self) -> FabricAdvert | None:
        store = self.store
        if store is None or getattr(store, "object_store", None) is None:
            return None
        fabric: FabricIndex | None = getattr(store, "fabric", None)
        if fabric is not None:
            fabric.sweep()
        hashes = store.object_hashes()
        if not hashes:
            return None
        return FabricAdvert(tenant=store.object_namespace,
                            host=self.host_id, hashes=hashes,
                            ttl_s=self.ttl_s)

    async def publish_once(self) -> dict[str, Any]:
        """One gossip round; returns a small report (tests/bench)."""
        advert = self._local_advert()
        if advert is None:
            return {"sent": 0, "hashes": 0}
        frame = {"adverts": [advert.to_wire()]}
        pushed = 0
        if self.rpc is not None and self.bus_peers is not None:
            for worker in sorted(set(self.bus_peers())):
                if worker == self.host_id:
                    continue
                pushed += await self._push_bus(worker, frame)
        for url in self.http_peers:
            pushed += await self._push_http(url, frame)
        return {"sent": pushed, "hashes": len(advert.hashes)}

    async def _push_bus(self, worker: str, frame: dict[str, Any]) -> int:
        try:
            # literal method name: the bus-rpc-conformance lint matches
            # this call site against the gateway's register() side
            await self.rpc.call(worker, "fabric.advert", frame,
                                timeout_s=self.rpc_timeout_s)
        except Exception as exc:
            self._note_failure(f"bus:{worker}", exc)
            return 0
        self._note_success(f"bus:{worker}")
        return 1

    async def _push_http(self, url: str, frame: dict[str, Any]) -> int:
        if self.post_json is None:
            return 0
        try:
            reply = await self.post_json(url + "/admin/fabric/adverts",
                                         frame)
        except Exception as exc:
            self._note_failure(url, exc)
            return 0
        self._note_success(url)
        # the exchange reply carries the PEER's adverts: merge them so a
        # one-way peer configuration still converges in both directions
        if isinstance(reply, dict) and isinstance(reply.get("adverts"),
                                                  list):
            try:
                self._merge_in(reply["adverts"])
            except ValueError:
                logger.warning("fabric peer %s returned a malformed "
                               "advert reply", url)
        return 1

    def _note_failure(self, peer: str, exc: Exception) -> None:
        self.send_failures += 1
        if peer not in self._peer_down:
            self._peer_down.add(peer)
            logger.warning("fabric advert to %s failed: %s", peer, exc)

    def _note_success(self, peer: str) -> None:
        self.sent += 1
        self._peer_down.discard(peer)
        if self.metrics is not None:
            try:
                self.metrics.llm_fabric_adverts.labels(
                    direction="sent").inc()
            except Exception:
                pass

    # ---------------------------------------------------------------- receive

    def _merge_in(self, payloads: list[dict[str, Any]]) -> int:
        fabric = self._fabric()
        if fabric is None:
            return 0
        fresh = merge_wire_adverts(fabric, payloads)
        self.merged_in += fresh
        if fresh and self.metrics is not None:
            try:
                self.metrics.llm_fabric_adverts.labels(
                    direction="merged").inc(fresh)
            except Exception:
                pass
        return fresh

    async def handle_advert(self, params: dict[str, Any]) -> dict[str, Any]:
        """``fabric.advert`` bus-RPC handler / HTTP endpoint core: merge
        the sender's batch, answer with the local view (the gossip
        exchange). Malformed adverts raise ``ValueError`` — the bus
        layer maps it to an RPC error frame, the HTTP handler to 400."""
        payloads = params.get("adverts")
        if not isinstance(payloads, list):
            raise ValueError("fabric.advert params need an 'adverts' list")
        merged = self._merge_in(payloads)
        fabric = self._fabric()
        local: list[dict[str, Any]] = []
        if fabric is not None:
            local = [a.to_wire() for a in fabric.adverts(self.host_id)]
        advert = self._local_advert()
        if advert is not None:
            local.append(advert.to_wire())
        return {"merged": merged, "adverts": local}

    # ------------------------------------------------------------------ stats

    def stats(self) -> dict[str, Any]:
        return {"host": self.host_id, "interval_s": self.interval_s,
                "ttl_s": self.ttl_s, "sent": self.sent,
                "merged_in": self.merged_in,
                "send_failures": self.send_failures,
                "bus": self.rpc is not None,
                "http_peers": list(self.http_peers)}
