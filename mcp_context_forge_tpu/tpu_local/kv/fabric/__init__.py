"""Cross-host prefix-cache fabric (docs/cache_fabric.md).

The tiered prefix cache (``kv/tiers.py``) stops at local disk; this
package adds the cross-host hop:

- :mod:`object_store` — the T3 backend protocol plus the two in-tree
  implementations (``file://`` shared directory, ``gcs://`` optional);
- :mod:`index` — the replicated fabric index: tenant-namespaced, TTL'd
  chain-hash advertisements merged from remote hosts
  (first-registration-wins), consulted by the store's probe path;
- :mod:`publisher` — the gossip loop that advertises this host's
  object-resident chains over the ``fabric.advert`` bus-RPC method and
  the ``POST /admin/fabric/adverts`` HTTP peer endpoint.
"""

from .index import FabricAdvert, FabricIndex
from .object_store import (FileObjectStore, GcsObjectStore, ObjectStore,
                           build_object_store, object_store_or_none)
from .publisher import FabricIndexPublisher

__all__ = [
    "FabricAdvert",
    "FabricIndex",
    "FabricIndexPublisher",
    "FileObjectStore",
    "GcsObjectStore",
    "ObjectStore",
    "build_object_store",
    "object_store_or_none",
]
