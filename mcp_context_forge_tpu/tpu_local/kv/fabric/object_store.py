"""T3 object-store backends for the prefix-cache fabric.

The :class:`~..tiers.TieredPageStore` treats the object store as the
hop below disk: pages persist as content-addressed blobs keyed by the
chain hash under a tenant namespace (``<namespace>/<hash>.npz`` — see
docs/cache_fabric.md for the key scheme), so N hosts sharing one store
share one copy of every spilled prefix page.

Contract (deliberately tiny — the tier store owns retries, backoff,
breakers, and verification):

- ``get(key)`` returns the blob bytes or ``None`` when the key does not
  exist; any other failure raises ``OSError``;
- ``put(key, data)`` is ATOMIC per key (a reader never observes a
  half-written blob) and idempotent — last-writer-wins is safe because
  keys are content-addressed and every read is verified against the
  requester's expected payload identity before serving;
- ``delete(key)`` is best-effort (missing keys are not an error).

Two in-tree backends:

- ``file://<dir>`` — a shared directory (NFS/SSD/test tempdir); atomic
  via tmp-file + ``os.replace``. The bench fabric scenario and every
  test use this one.
- ``gcs://<bucket>[/<prefix>]`` — Google Cloud Storage behind the same
  interface. The dependency is OPTIONAL: :func:`build_object_store`
  refuses at build time with a clear error when the client library is
  not installed, instead of failing on first IO mid-serving.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any

logger = logging.getLogger(__name__)

_KEY_RE = re.compile(r"^[A-Za-z0-9._\-]+(/[A-Za-z0-9._\-]+)*$")


def _check_key(key: str) -> str:
    """Keys are namespace-qualified relative paths; reject anything that
    could escape the store root (``..``, absolute paths, empty
    segments) — the file backend joins them onto a shared directory."""
    if not _KEY_RE.match(key) or ".." in key.split("/"):
        raise ValueError(f"illegal object key {key!r}")
    return key


class ObjectStore:
    """Backend interface (docstring above). Subclasses implement the
    three IO methods; ``url`` echoes the configured location and
    ``stats()`` feeds the admin tier cards."""

    url: str = ""

    def get(self, key: str) -> bytes | None:  # pragma: no cover - interface
        raise NotImplementedError

    def put(self, key: str, data: bytes) -> None:  # pragma: no cover
        raise NotImplementedError

    def delete(self, key: str) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def stats(self) -> dict[str, Any]:
        return {"url": self.url}


class FileObjectStore(ObjectStore):
    """Shared-directory backend: one blob per key under ``root``."""

    def __init__(self, root: str) -> None:
        if not root:
            raise ValueError("file:// object store needs a directory path")
        self.root = os.path.abspath(root)
        self.url = f"file://{self.root}"
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *_check_key(key).split("/"))

    def get(self, key: str) -> bytes | None:
        try:
            with open(self._path(key), "rb") as fh:
                return fh.read()
        except FileNotFoundError:
            return None

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # atomic publish: a cross-host reader either sees the whole blob
        # or a miss, never a torn write (same discipline as the disk
        # tier's .npz writeback)
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "wb") as fh:
                fh.write(data)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def stats(self) -> dict[str, Any]:
        return {"url": self.url, "backend": "file"}


class GcsObjectStore(ObjectStore):
    """Google Cloud Storage backend. Construction requires the optional
    ``google-cloud-storage`` client — :func:`build_object_store` guards
    the import so a missing dependency refuses at BUILD time."""

    def __init__(self, bucket: str, prefix: str = "",
                 client: Any = None) -> None:
        if client is None:  # pragma: no cover - needs the optional dep
            from google.cloud import storage
            client = storage.Client()
        self._bucket = client.bucket(bucket)
        self._prefix = prefix.strip("/")
        self.url = f"gcs://{bucket}" + (f"/{self._prefix}"
                                        if self._prefix else "")

    def _blob(self, key: str):
        name = _check_key(key)
        if self._prefix:
            name = f"{self._prefix}/{name}"
        return self._bucket.blob(name)

    def get(self, key: str) -> bytes | None:
        try:
            return self._blob(key).download_as_bytes()
        except OSError:
            raise
        except Exception as exc:
            if type(exc).__name__ == "NotFound":
                return None
            raise OSError(f"gcs get failed: {exc}") from exc

    def put(self, key: str, data: bytes) -> None:
        try:
            # GCS object writes are atomic by contract; no tmp dance
            self._blob(key).upload_from_string(data)
        except OSError:
            raise
        except Exception as exc:
            raise OSError(f"gcs put failed: {exc}") from exc

    def delete(self, key: str) -> None:
        try:
            self._blob(key).delete()
        except Exception:
            pass

    def stats(self) -> dict[str, Any]:
        return {"url": self.url, "backend": "gcs"}


def gcs_available() -> bool:
    """True when the optional GCS client library is importable."""
    try:
        from google.cloud import storage  # noqa: F401
        return True
    except ImportError:
        return False


def build_object_store(url: str) -> ObjectStore:
    """Build a backend from its URL (``tpu_local_tier_object_url``).

    Raises ``ValueError`` for unknown schemes and for ``gcs://`` when
    the optional client library is missing — the refusal happens HERE,
    at build time, with an actionable message, never as a surprise
    OSError on the first spill mid-serving. Callers that prefer to
    serve degraded (T3 off) catch it and log.
    """
    if url.startswith("file://"):
        return FileObjectStore(url[len("file://"):])
    if url.startswith("gcs://"):
        if not gcs_available():
            raise ValueError(
                "tier_object_url is gcs:// but the google-cloud-storage "
                "package is not installed — install it or point the "
                "fabric at a file:// shared directory")
        rest = url[len("gcs://"):].strip("/")
        if not rest:
            raise ValueError("gcs:// object store needs a bucket name")
        bucket, _, prefix = rest.partition("/")
        return GcsObjectStore(bucket, prefix)
    raise ValueError(f"unsupported object store url {url!r} "
                     f"(expected file://<dir> or gcs://<bucket>[/prefix])")


def object_store_or_none(url: str) -> ObjectStore | None:
    """Graceful-degrade wrapper for the serving path: "" means "no
    fabric configured" (silent None); a configured-but-unbuildable URL
    (unknown scheme, missing GCS dep) logs ONE clear warning and serves
    without T3 — HBM/T1/T2 keep working, the fabric simply stays off."""
    if not url:
        return None
    try:
        return build_object_store(url)
    except ValueError as exc:
        logger.warning("prefix-cache fabric disabled: %s", exc)
        return None
