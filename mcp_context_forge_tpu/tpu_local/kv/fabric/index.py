"""Replicated fabric index: which prefix chains exist in the shared
object store, learned from cross-host advertisements.

Each host's :class:`~.publisher.FabricIndexPublisher` gossips a
:class:`FabricAdvert` — the tenant namespace, the advertising host, and
the chain hashes that host has persisted to T3 — over the
``fabric.advert`` bus-RPC method (in-fleet) and the
``POST /admin/fabric/adverts`` HTTP endpoint (cross-supervisor).
Receivers :meth:`merge <FabricIndex.merge>` them here; the local
:class:`~..tiers.TieredPageStore` consults :meth:`covers` on probe so a
chain prefilled on host A scores as restorable capacity on host B.

Semantics (pinned by the mutation oracle in ``testing/oracles.py``):

- **tenant-namespace isolation**: entries key on ``(tenant, hash)``;
  ``covers``/``lookup`` never cross namespaces — a tenant's cached
  pages are invisible (and, because the object KEY embeds the
  namespace, unreachable) from any other namespace;
- **TTL expiry**: every entry expires ``ttl_s`` after its last merge;
  an expired entry is exactly a miss. Staleness is therefore bounded —
  and harmless anyway: a stale ``covers`` only costs a failed object
  fetch, which invalidates the entry (verify-before-serve means a
  WRONG payload is impossible, see tiers.py);
- **first-registration-wins**: re-advertising a hash refreshes its
  expiry but never reassigns its origin host — the host attribution is
  stable for the life of the entry (mirrors the allocator's
  first-registration-wins page identity rule);
- **merge is monotone**: merging never removes entries; only expiry
  (``sweep`` or a lazy ``covers`` miss) does.

Thread model: merged from the gateway loop (bus-RPC handler, HTTP
endpoint) and read from engine dispatch threads (store probe) — every
access takes the internal lock; all operations are dict-sized.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

#: adverts larger than this are truncated at the wire boundary — one
#: advert carries at most this many chain hashes (a 32-page chain at
#: 16 tokens/page is a 512-token prefix; 4096 hashes ≈ 2 MB of prompt)
MAX_ADVERT_HASHES = 4096


@dataclass
class FabricAdvert:
    """One host's chain-head advertisement: "these hashes exist in the
    shared object store under this tenant namespace"."""

    tenant: str
    host: str
    hashes: list[bytes] = field(default_factory=list)
    ttl_s: float = 0.0          # 0 = receiver's default TTL

    def to_wire(self) -> dict[str, Any]:
        return {"tenant": self.tenant, "host": self.host,
                "ttl_s": self.ttl_s,
                "hashes": [h.hex() for h in self.hashes]}

    @classmethod
    def from_wire(cls, payload: dict[str, Any]) -> "FabricAdvert":
        """Parse one wire advert; raises ``ValueError`` on a frame that
        is not advert-shaped (the bus/HTTP handlers turn that into a
        clean protocol error, never a crash)."""
        if not isinstance(payload, dict):
            raise ValueError("advert must be an object")
        tenant = payload.get("tenant")
        host = payload.get("host")
        raw = payload.get("hashes", [])
        if not isinstance(tenant, str) or not isinstance(host, str) \
                or not host or not isinstance(raw, list):
            raise ValueError("advert needs tenant/host/hashes fields")
        hashes: list[bytes] = []
        for item in raw[:MAX_ADVERT_HASHES]:
            try:
                digest = bytes.fromhex(item)
            except (TypeError, ValueError) as exc:
                raise ValueError(f"bad advert hash {item!r}") from exc
            if len(digest) != 32:
                raise ValueError("advert hashes must be 32 bytes")
            hashes.append(digest)
        try:
            ttl_s = float(payload.get("ttl_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            ttl_s = 0.0
        return cls(tenant=tenant, host=host, hashes=hashes,
                   ttl_s=max(0.0, ttl_s))


class FabricIndex:
    """TTL'd (tenant, chain-hash) -> origin-host map (module doc)."""

    def __init__(self, default_ttl_s: float = 300.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.default_ttl_s = max(1.0, float(default_ttl_s))
        self._clock = clock
        self._lock = threading.Lock()
        # (tenant, hash) -> (origin host, expires_at)
        self._entries: dict[tuple[str, bytes], tuple[str, float]] = {}
        self.merged = 0        # hashes newly inserted by merge()
        self.refreshed = 0     # hashes whose expiry a merge extended
        self.expired = 0       # entries dropped by sweep/lazy expiry
        self.invalidated = 0   # entries dropped after a failed fetch

    # ------------------------------------------------------------------ write

    def merge(self, advert: FabricAdvert) -> int:
        """Fold one advert in; returns the number of NEW hashes."""
        ttl = advert.ttl_s if advert.ttl_s > 0 else self.default_ttl_s
        expires = self._clock() + ttl
        fresh = 0
        with self._lock:
            for digest in advert.hashes[:MAX_ADVERT_HASHES]:
                key = (advert.tenant, digest)
                entry = self._entries.get(key)
                if entry is None:
                    self._entries[key] = (advert.host, expires)
                    fresh += 1
                    self.merged += 1
                else:
                    # first-registration-wins on the origin host; the
                    # re-advert only extends (never shortens) the expiry
                    self._entries[key] = (entry[0],
                                          max(entry[1], expires))
                    self.refreshed += 1
        return fresh

    def invalidate(self, key_hash: bytes, tenant: str) -> None:
        """Drop one entry after a failed object fetch — a fabric promise
        the store could not keep must stop scoring as capacity, or every
        probe of the chain re-attempts the dead fetch."""
        with self._lock:
            if self._entries.pop((tenant, key_hash), None) is not None:
                self.invalidated += 1

    def sweep(self) -> int:
        """Drop expired entries eagerly (the publisher ticks this)."""
        now = self._clock()
        with self._lock:
            dead = [k for k, (_host, exp) in self._entries.items()
                    if exp <= now]
            for key in dead:
                del self._entries[key]
            self.expired += len(dead)
        return len(dead)

    # ----------------------------------------------------------------- lookup

    def covers(self, key_hash: bytes, tenant: str) -> bool:
        """True iff an unexpired advert covers ``(tenant, key_hash)``.
        Lazy-expires on read so a dead entry never outlives its TTL by
        more than one probe."""
        now = self._clock()
        with self._lock:
            entry = self._entries.get((tenant, key_hash))
            if entry is None:
                return False
            if entry[1] <= now:
                del self._entries[(tenant, key_hash)]
                self.expired += 1
                return False
            return True

    def lookup(self, key_hash: bytes, tenant: str) -> str | None:
        """The advertising origin host, or None (missing/expired)."""
        with self._lock:
            entry = self._entries.get((tenant, key_hash))
            if entry is None or entry[1] <= self._clock():
                return None
            return entry[0]

    def hashes(self, tenant: str) -> list[bytes]:
        """Unexpired hashes under one tenant namespace (wire echo for
        the HTTP gossip exchange)."""
        now = self._clock()
        with self._lock:
            return [h for (t, h), (_host, exp) in self._entries.items()
                    if t == tenant and exp > now]

    def adverts(self, host: str) -> list[FabricAdvert]:
        """Re-advertisable view of everything unexpired, grouped by
        tenant (the HTTP exchange returns the RECEIVER's view so a
        one-way peer config still converges both ways). ``host`` labels
        the relay, not the origin — origins stay pinned per entry on
        the receiving side only for entries it saw first."""
        now = self._clock()
        grouped: dict[str, list[bytes]] = {}
        with self._lock:
            for (tenant, digest), (_origin, exp) in self._entries.items():
                if exp > now:
                    grouped.setdefault(tenant, []).append(digest)
        return [FabricAdvert(tenant=tenant, host=host, hashes=hashes)
                for tenant, hashes in sorted(grouped.items())]

    # ------------------------------------------------------------------ stats

    def stats(self) -> dict[str, Any]:
        now = self._clock()
        with self._lock:
            live = sum(1 for _h, exp in self._entries.values()
                       if exp > now)
            hosts = {host for (host, exp) in self._entries.values()
                     if exp > now}
            tenants = {t for (t, _h), (_host, exp)
                       in self._entries.items() if exp > now}
        return {"keys": live, "hosts": sorted(hosts),
                "tenants": sorted(tenants), "merged": self.merged,
                "refreshed": self.refreshed, "expired": self.expired,
                "invalidated": self.invalidated,
                "default_ttl_s": self.default_ttl_s}


def merge_wire_adverts(index: FabricIndex,
                       payloads: Iterable[dict[str, Any]]) -> int:
    """Parse + merge a wire batch; returns new-hash count. Raises
    ``ValueError`` on the first malformed advert (the transport handler
    maps it to a protocol error)."""
    fresh = 0
    for payload in payloads:
        fresh += index.merge(FabricAdvert.from_wire(payload))
    return fresh
