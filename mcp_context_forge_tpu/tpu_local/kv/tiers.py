"""Spill tiers for the prefix/KV page cache: pinned host RAM -> disk.

The HBM prefix cache (``PageAllocator``) evicts cold pages under
allocation pressure; with a :class:`TieredPageStore` attached, eviction
SPILLS instead of drops:

- **T1 (host)**: evicted pages land in a bounded host-RAM map as int8
  bytes + per-(layer, kv-head) dequant scales. Engines whose resident
  pool is already int8 spill their bytes verbatim (a T1/T2 round trip is
  bit-exact); bf16 pools quantize-on-spill (int8 + running-max page
  scale — the same scheme the resident int8 mode uses, whose greedy
  parity is pinned in tests). On TPU the arrays are committed to pinned
  host memory when the runtime supports it, so the restore's host->HBM
  upload DMAs without a bounce copy; everywhere else they are plain
  numpy.
- **T2 (disk)**: when T1 overflows its byte budget, the oldest entries
  hand off to a write-behind worker thread (the ``spill`` lint-thread
  context) that persists them as ``.npz`` files under a bounded disk
  budget. Entries stay readable throughout (the pending map serves reads
  until the file lands). A T2 hit at match time re-onlines the payload
  into T1 on its way back to HBM.
- **T3 (object)**: with an :class:`~.fabric.object_store.ObjectStore`
  attached (docs/cache_fabric.md), the write-behind worker ALSO
  persists every displaced page as a content-addressed blob
  (``<namespace>/<chain-hash>.npz``) in the shared store — the
  cross-HOST hop. The local ``_object`` map plus the gossip-fed
  :class:`~.fabric.index.FabricIndex` tell probe/get which chains are
  object-reachable; a fabric hit fetches a page another HOST prefilled
  and re-onlines it here, behind the same verify gate. T3 has its own
  ``tier.object`` breaker: open means object reads MISS and writebacks
  drop (counted) while HBM/T1/T2 keep serving.

The store is POOL-SHARED: every replica spills into and restores from
the same instance, which is what makes admission-time **fetch-on-miss**
work across replicas — a prefix prefilled (then evicted) on replica 1
restores into replica 0's HBM inside replica 0's allocate path. The
pool-global :class:`~.prefix_index.PrefixIndex` learns tier residency
from the store (publish/unpublish on every transition) so the router
can score tier hits as affinity.

Collision safety: entries are keyed by the 32-byte chain hash, but every
payload carries its exact page tokens + parent hash, and ``get``
verifies both against the requester's expectation. A colliding key can
therefore only produce a MISS, never wrong pages.

Thread model: ``put``/``get``/``probe`` run on engine dispatch threads
(admission/eviction); the write-behind loop owns the disk state
(``# lint: thread[spill]``), with the store lock legalizing the
cross-thread handoffs; the router reads only the index, never the store.
"""

from __future__ import annotations

import io
import logging
import os
import queue
import shutil
import tempfile
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable, Sequence

import numpy as np

from ...observability.degradation import get_degradation
from ...observability.faults import FaultAction, FaultError, fault_point

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .fabric.index import FabricIndex
    from .fabric.object_store import ObjectStore
    from .prefix_index import PrefixIndex

logger = logging.getLogger(__name__)

TIERS = ("hbm", "host", "disk", "object")


def _backoff_s(base_ms: float, attempt: int, salt: int) -> float:
    """Bounded jittered backoff for transient disk-IO retries: doubles
    per attempt with a deterministic ±25% jitter derived from ``salt``
    (no RNG state — the same failure sequence retries identically)."""
    jitter = 0.75 + 0.5 * ((salt * 2654435761 + attempt) % 100) / 100.0
    return (base_ms / 1e3) * (2 ** attempt) * jitter


@dataclass
class SpilledPage:
    """One spilled prefix page: int8 K/V bytes + per-(layer, kv-head)
    dequant scales, plus the identity evidence ``get`` verifies."""

    chunk: tuple[int, ...]     # the page's exact prompt tokens
    parent: bytes              # parent chain hash (prefix_index.chain_hash)
    k: np.ndarray              # [L, page, KV, hd] int8
    v: np.ndarray              # [L, page, KV, hd] int8
    k_scales: np.ndarray       # [L, KV] float32
    v_scales: np.ndarray       # [L, KV] float32

    @property
    def nbytes(self) -> int:
        return int(self.k.nbytes + self.v.nbytes
                   + self.k_scales.nbytes + self.v_scales.nbytes)


def pin_host(arr: np.ndarray) -> Any:
    """Best-effort pinned-host placement of a spill buffer (TPU runtimes
    DMA from pinned memory without a bounce copy). Returns the input
    unchanged when the backend has no pinned_host space (CPU tests) —
    the store treats the result as array-like either way."""
    try:  # pragma: no cover - exercised only on TPU runtimes
        import jax

        device = jax.local_devices()[0]
        if device.platform != "tpu":
            return arr
        sharding = jax.sharding.SingleDeviceSharding(
            device, memory_kind="pinned_host")
        return jax.device_put(arr, sharding)
    except Exception:
        return arr


class TieredPageStore:
    """Bounded host-RAM + disk store for spilled prefix pages (module doc)."""

    def __init__(self, host_bytes: int, disk_bytes: int = 0,
                 disk_dir: str = "", index: "PrefixIndex | None" = None,
                 metrics=None, pin: bool = True,
                 io_retry_max: int = 2,
                 io_retry_backoff_ms: float = 10.0,
                 object_store: "ObjectStore | None" = None,
                 object_namespace: str = "shared",
                 fabric: "FabricIndex | None" = None) -> None:
        self.host_budget = max(0, int(host_bytes))
        self.disk_budget = max(0, int(disk_bytes))
        self.index = index
        self.metrics = metrics
        self._pin = pin
        # T3 object fabric (docs/cache_fabric.md): the shared backend,
        # the tenant namespace every key is qualified by, and the
        # gossip-fed index of chains OTHER hosts have persisted
        self.object_store = object_store
        self.object_namespace = object_namespace or "shared"
        if fabric is None and object_store is not None:
            from .fabric.index import FabricIndex as _FabricIndex
            fabric = _FabricIndex()
        self.fabric = fabric
        # disk IO hardening (docs/resilience.md): transient read/write
        # errors retry with bounded jittered backoff, then the ENTRY is
        # quarantined — dropped to a clean MISS, never a hang or a
        # poisoned serve; repeated failures open the tier.disk breaker
        # and the whole disk tier quarantines (HBM/T1 keep serving)
        # until a half-open probe succeeds
        self.io_retry_max = max(0, int(io_retry_max))
        self.io_retry_backoff_ms = max(0.0, float(io_retry_backoff_ms))
        self._disk_breaker = get_degradation().breaker("tier.disk")
        self._object_breaker = get_degradation().breaker("tier.object")
        self.io_errors = {("disk", "read"): 0, ("disk", "write"): 0,
                          ("host", "get"): 0,
                          ("object", "read"): 0, ("object", "write"): 0}
        self._lock = threading.Lock()  # lint: lock[spill]
        # T1: insertion-ordered = LRU-by-last-use (get() re-inserts)
        self._host: dict[bytes, SpilledPage] = {}
        self._host_nbytes = 0
        # handed to the writer but not yet on disk: still served from RAM
        self._pending: dict[bytes, SpilledPage] = {}  # lint: thread[spill]
        # T2 residency: hash -> (path, nbytes), insertion-ordered (FIFO
        # eviction when the disk budget overflows)
        self._disk: dict[bytes, tuple[str, int]] = {}  # lint: thread[spill]
        self._disk_nbytes = 0  # lint: thread[spill]
        # T3 LOCAL knowledge: hashes THIS host wrote (or fetched) from
        # the object store, hash -> nbytes. Remote residency lives in
        # self.fabric; the union is what probe/get consult.
        self._object: dict[bytes, int] = {}  # lint: thread[spill]
        self._object_nbytes = 0  # lint: thread[spill]
        self._writeq: "queue.Queue[bytes | None]" = queue.Queue()
        self._writer: threading.Thread | None = None
        self._closed = False
        self._disk_dir = disk_dir
        self._owns_dir = False
        # counters (read by stats surfaces; int ops are GIL-atomic)
        self.spilled = 0
        self.dropped = 0          # evicted past the last tier (truly gone)
        self.collisions = 0       # key matched, payload identity did not
        self.disk_writes = 0
        self.disk_reads = 0
        self.object_writes = 0
        self.object_reads = 0
        self.object_write_drops = 0  # writebacks dropped: breaker open

    # ------------------------------------------------------------- lifecycle

    def _ensure_dir(self) -> str:
        if not self._disk_dir:
            self._disk_dir = tempfile.mkdtemp(prefix="mcpforge-kv-tier-")
            self._owns_dir = True
        os.makedirs(self._disk_dir, exist_ok=True)
        return self._disk_dir

    def _ensure_writer(self) -> None:
        if self._writer is None or not self._writer.is_alive():
            self._writer = threading.Thread(
                target=self._writer_loop, name="kv-tier-spill", daemon=True)
            self._writer.start()

    def close(self) -> None:
        """Stop the write-behind worker and drop disk state this store
        owns (an operator-provided disk_dir is left in place — it may be
        a shared cache another pool still reads)."""
        self._closed = True
        writer = self._writer
        if writer is not None and writer.is_alive():
            self._writeq.put(None)
            writer.join(timeout=5.0)
        with self._lock:
            self._pending.clear()
            self._host.clear()
            self._host_nbytes = 0
            disk, self._disk = dict(self._disk), {}
            self._disk_nbytes = 0
        if self._owns_dir and self._disk_dir:
            shutil.rmtree(self._disk_dir, ignore_errors=True)
        elif disk:
            for path, _ in disk.values():
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # ------------------------------------------------------------------ write

    def put(self, key_hash: bytes, payload: SpilledPage) -> None:
        """Admit a spilled page into T1, displacing LRU entries toward T2
        (write-behind) when the host budget overflows. Duplicate keys
        are a no-op — shared prefixes spill from many replicas."""
        if self._closed or self.host_budget <= 0:
            return
        if self._pin:
            payload.k = pin_host(payload.k)
            payload.v = pin_host(payload.v)
        with self._lock:
            if (key_hash in self._host or key_hash in self._pending
                    or key_hash in self._disk or key_hash in self._object):
                return
            self._host[key_hash] = payload
            self._host_nbytes += payload.nbytes
            overflow = self._trim_host_locked()
        self.spilled += 1
        if self.index is not None:
            self.index.publish_tier(key_hash, "host")
        self._dispatch_overflow(overflow)

    def _trim_host_locked(self) -> list[bytes]:
        """Enforce the T1 byte budget (caller holds the lock): displace
        LRU entries toward the write-behind queue (or drop them when the
        disk tier is off). Returns the keys to hand to the worker —
        queueing happens OUTSIDE the lock."""
        overflow: list[bytes] = []
        while self._host_nbytes > self.host_budget and len(self._host) > 1:
            old_key, old = next(iter(self._host.items()))
            del self._host[old_key]
            self._host_nbytes -= old.nbytes
            if old_key in self._disk or old_key in self._object:
                # a displaced RE-ONLINED entry: its disk/object copy is
                # already durable — rewriting would double-count bytes
                if self.index is not None:
                    self.index.unpublish_tier(old_key, "host")
            elif self.disk_budget > 0 or self.object_store is not None:
                self._pending[old_key] = old  # lint: allow[cross-thread-mutation] _locked-suffix contract: every caller holds self._lock (the lint lock scope is per-method)
                overflow.append(old_key)
            else:
                self.dropped += 1
                if self.index is not None:
                    self.index.unpublish_tier(old_key, "host")
        return overflow

    def _dispatch_overflow(self, overflow: list[bytes]) -> None:
        if overflow:
            self._ensure_writer()
            for old_key in overflow:
                self._writeq.put(old_key)

    # ------------------------------------------------------------------- read

    def probe(self, key_hash: bytes) -> bool:
        """True iff some tier holds the key (no payload verification —
        the probe sizes buckets; the match verifies). Fabric-advertised
        chains count too — the allocator caps probes at its restore
        capacity, so a stale advert costs one failed fetch at match
        time (a clean MISS), never an admission livelock — UNLESS the
        object breaker is open: a quarantined T3 must not promise
        capacity its reads will refuse to deliver."""
        with self._lock:
            if (key_hash in self._host or key_hash in self._pending
                    or key_hash in self._disk
                    or key_hash in self._object):
                return True
        return (self.object_store is not None and self.fabric is not None
                and self._object_breaker.state != "open"
                and self.fabric.covers(key_hash, self.object_namespace))

    def get(self, key_hash: bytes, parent: bytes,
            chunk: Sequence[int]) -> tuple[SpilledPage, str] | None:
        """Fetch + VERIFY one page: the stored payload must carry exactly
        ``(parent, chunk)`` or the result is a miss (hash collision —
        wrong pages are never served). A disk hit re-onlines into T1.
        Returns ``(payload, source_tier)``.

        Fault points: ``tier.host.get`` covers the T1 fetch (error =
        clean MISS, corrupt = the payload fails identity verification
        and the entry quarantines — the collision path); the disk load
        below rides ``tier.disk.read`` inside :meth:`_read_disk`."""
        expected = tuple(chunk)
        corrupt_host = False
        act = fault_point("tier.host.get", scope=key_hash.hex())
        if act is not None:
            if act.kind == "corrupt":
                corrupt_host = True  # forces the verify-failure path
            else:
                try:
                    act.apply()
                except FaultError:
                    # an injected T1 fault degrades to a MISS — the
                    # match ends at the pages already secured, never a
                    # crash inside the admission path
                    self._count_io_error("host", "get")
                    return None
        path = None
        collided = False
        with self._lock:
            payload = self._host.get(key_hash)
            if payload is not None:
                # LRU touch: re-insert at the MRU end
                del self._host[key_hash]
                self._host[key_hash] = payload
                hit = None if corrupt_host \
                    else self._verify(payload, parent, expected, "host")
                if hit is None:  # collision: drop it, or probe() keeps
                    del self._host[key_hash]   # promising an unrestorable
                    self._host_nbytes -= payload.nbytes  # hist (livelock)
                    collided = True
            else:
                payload = self._pending.get(key_hash)
                if payload is not None:
                    hit = None if corrupt_host \
                        else self._verify(payload, parent, expected, "host")
                    if hit is None:
                        self._pending.pop(key_hash, None)
                        collided = True
                else:
                    hit = None
                    entry = self._disk.get(key_hash)
                    if entry is not None:
                        path = entry[0]
        if collided:
            # the dropped T1 copy must leave the index too, or the
            # router keeps scoring phantom tier affinity for the hash
            self.dropped += 1
            if corrupt_host:
                self._count_io_error("host", "get")
            if self.index is not None:
                self.index.unpublish_tier(key_hash, "host")
            return None
        if payload is not None and path is None:
            return hit
        if path is None:
            # T1/T2 miss: the object fabric is the last hop — locally
            # written blobs or chains a peer host advertised
            return self._get_object(key_hash, parent, expected)
        if not self._disk_breaker.allow():
            # disk tier quarantined (breaker open): clean MISS; the
            # entry STAYS — it may serve again once a half-open probe
            # closes the breaker
            return None
        payload = self._read_disk(path)
        if payload is None:
            self._disk_breaker.record_failure("disk read")
            self._count_io_error("disk", "read")
            with self._lock:
                entry = self._disk.pop(key_hash, None)
                if entry is not None:
                    self._disk_nbytes -= entry[1]
            if self.index is not None:
                self.index.unpublish_tier(key_hash, "disk")
            return None
        self._disk_breaker.record_success()
        self.disk_reads += 1
        hit = self._verify(payload, parent, expected, "disk")
        if hit is None:
            # collision on the disk copy: drop it too (see host path)
            with self._lock:
                entry = self._disk.pop(key_hash, None)
                if entry is not None:
                    self._disk_nbytes -= entry[1]
            try:
                os.unlink(path)
            except OSError:
                pass
            if self.index is not None:
                self.index.unpublish_tier(key_hash, "disk")
            return None
        if hit is not None:
            # re-online on match: later matches (any replica) serve from
            # RAM; the disk copy stays for durability until budget churn.
            # The SAME budget trim as put() applies — a restore-heavy
            # phase must not grow T1 past tier_host_bytes just because
            # the bytes arrived via re-onlining instead of spilling
            overflow: list[bytes] = []
            with self._lock:
                if key_hash not in self._host and not self._closed:
                    self._host[key_hash] = payload
                    self._host_nbytes += payload.nbytes
                    overflow = self._trim_host_locked()
            if self.index is not None:
                self.index.publish_tier(key_hash, "host")
            self._dispatch_overflow(overflow)
        return hit

    def _verify(self, payload: SpilledPage, parent: bytes,
                chunk: tuple[int, ...],
                tier: str) -> tuple[SpilledPage, str] | None:
        if payload.parent != parent or payload.chunk != chunk:
            self.collisions += 1
            logger.warning(
                "kv tier store: chain-hash collision (tier=%s) — "
                "payload identity mismatch, serving a miss", tier)
            return None
        return payload, tier

    # -------------------------------------------------------- T3 object fabric

    def _object_key(self, key_hash: bytes) -> str:
        """Content-addressed, tenant-namespaced blob key: the namespace
        segment is part of the KEY, so tenants in different namespaces
        cannot reach each other's pages even through a forged advert."""
        return f"{self.object_namespace}/{key_hash.hex()}.npz"

    def object_hashes(self) -> list[bytes]:
        """Chain hashes THIS host knows are object-resident (what the
        publisher advertises to peers)."""
        with self._lock:
            return list(self._object)

    def _drop_object_entry(self, key_hash: bytes) -> None:
        """Forget one object promise everywhere probes look: the local
        map, the fabric index, and the pool index — or every probe of
        the chain re-attempts the dead fetch."""
        with self._lock:
            nbytes = self._object.pop(key_hash, None)
            if nbytes is not None:
                self._object_nbytes -= nbytes
        if self.fabric is not None:
            self.fabric.invalidate(key_hash, self.object_namespace)
        if self.index is not None:
            self.index.unpublish_object(key_hash)

    def _get_object(self, key_hash: bytes, parent: bytes,
                    expected: tuple[int, ...]
                    ) -> tuple[SpilledPage, str] | None:
        """The T3 fetch: serve a page from the shared object store —
        written by THIS host (local ``_object`` map) or prefilled by a
        PEER host (fabric advert) — behind the same verify gate as
        every other tier. A hit re-onlines into T1 exactly like a disk
        hit, so the cross-host fetch happens once per chain, not once
        per request."""
        if self.object_store is None:
            return None
        with self._lock:
            known = key_hash in self._object
        if not known and (self.fabric is None or not self.fabric.covers(
                key_hash, self.object_namespace)):
            return None
        if not self._object_breaker.allow():
            # T3 quarantined (breaker open): clean MISS; local knowledge
            # and adverts STAY — the blob may serve again after a
            # half-open probe closes the breaker
            return None
        status, payload = self._read_object(key_hash)
        if status == "miss":
            # the blob is gone (stale advert / external cleanup): not an
            # IO failure — drop the promise, leave the breaker alone
            self._object_breaker.record_success()
            self._drop_object_entry(key_hash)
            return None
        if payload is None:
            self._object_breaker.record_failure("object read")
            self._count_io_error("object", "read")
            self._drop_object_entry(key_hash)
            return None
        self._object_breaker.record_success()
        self.object_reads += 1
        hit = self._verify(payload, parent, expected, "object")
        if hit is None:
            # collision/corrupt blob: a bad payload must stop being
            # findable (and servable) fabric-wide
            self._drop_object_entry(key_hash)
            self.object_store.delete(self._object_key(key_hash))
            return None
        # re-online on match (same budget discipline as the disk path);
        # a fabric-fetched page is PROOF of object residency — learn it
        # locally so a later displacement skips the redundant writeback
        # and this host's publisher re-advertises the chain
        overflow: list[bytes] = []
        with self._lock:
            if key_hash not in self._object:
                self._object[key_hash] = payload.nbytes
                self._object_nbytes += payload.nbytes
            if key_hash not in self._host and not self._closed:
                self._host[key_hash] = payload
                self._host_nbytes += payload.nbytes
                overflow = self._trim_host_locked()
        if self.index is not None:
            self.index.publish_object(key_hash, self._object_key(key_hash))
            self.index.publish_tier(key_hash, "host")
        self._dispatch_overflow(overflow)
        return hit

    def _read_object(self, key_hash: bytes
                     ) -> tuple[str, SpilledPage | None]:
        """One object fetch with bounded retries. Returns ``(status,
        payload)`` — ``("hit", page)``, ``("miss", None)`` for a clean
        not-found, ``("error", None)`` after exhausted retries or
        corrupt content. The ``tier.object.get`` fault point fires per
        ATTEMPT; a ``corrupt`` rule mangles the fetched bytes so the
        payload either fails to parse or fails identity verification —
        a MISS, never a served page."""
        key = self._object_key(key_hash)
        for attempt in range(self.io_retry_max + 1):
            corrupt = False
            act = fault_point("tier.object.get", scope=key)
            try:
                if act is not None:
                    if act.kind == "corrupt":
                        corrupt = True
                    else:
                        act.apply()
                raw = self.object_store.get(key)
                if raw is None:
                    return "miss", None
                if corrupt:
                    raw = FaultAction.corrupt_bytes(raw)
                with np.load(io.BytesIO(raw)) as data:
                    return "hit", self._payload_from(data)
            except OSError:
                if attempt >= self.io_retry_max:
                    return "error", None
                time.sleep(_backoff_s(self.io_retry_backoff_ms, attempt,
                                      len(key)))
            except Exception:
                # corrupt blob content: retrying cannot fix it
                logger.warning("kv tier store: corrupt object blob %s",
                               key)
                return "error", None
        return "error", None

    def _write_object_tier(self, key_hash: bytes,
                           payload: SpilledPage) -> bool:
        """One T3 writeback with bounded retries (write-behind worker
        only). Breaker open = drop immediately, counted — no retry
        storm against a dead backend. The ``tier.object.put`` fault
        point fires per attempt; a ``corrupt`` rule uploads mangled
        bytes, which every reader's verify gate turns into a MISS."""
        if self.object_store is None:
            return False
        if not self._object_breaker.allow():
            self.object_write_drops += 1
            return False
        key = self._object_key(key_hash)
        data = self._serialize(payload)
        started = time.monotonic()
        for attempt in range(self.io_retry_max + 1):
            blob = data
            act = fault_point("tier.object.put", scope=key)
            try:
                if act is not None:
                    if act.kind == "corrupt":
                        blob = FaultAction.corrupt_bytes(data)
                    else:
                        act.apply()
                self.object_store.put(key, blob)
            except OSError:
                if attempt >= self.io_retry_max:
                    self._object_breaker.record_failure("object write")
                    self._count_io_error("object", "write")
                    logger.warning(
                        "kv tier store: object write failed after %d "
                        "attempt(s) (%s); page stays local-only",
                        self.io_retry_max + 1, key)
                    return False
                time.sleep(_backoff_s(self.io_retry_backoff_ms, attempt,
                                      len(key)))
                continue
            self._object_breaker.record_success()
            if self.metrics is not None:
                self.metrics.llm_prefix_tier_io.labels(
                    op="writeback", tier="object").observe(
                    time.monotonic() - started)
            return True
        return False

    @staticmethod
    def _serialize(payload: SpilledPage) -> bytes:
        buf = io.BytesIO()
        np.savez(buf,
                 chunk=np.asarray(payload.chunk, dtype=np.int64),
                 parent=np.frombuffer(payload.parent, dtype=np.uint8),
                 k=np.asarray(payload.k), v=np.asarray(payload.v),
                 k_scales=np.asarray(payload.k_scales),
                 v_scales=np.asarray(payload.v_scales))
        return buf.getvalue()

    # ----------------------------------------------------------- spill worker

    def _writer_loop(self) -> None:  # lint: runs-on[spill]
        """Write-behind: persist pending T1 overflow to disk (bounded by
        the disk budget, oldest files evicted) AND — write-through —
        to the shared object store when one is attached. A page is
        dropped only when EVERY lower tier refused it; a disk-evicted
        page whose blob survives in T3 is still fetchable, so only a
        blob-less eviction counts as truly gone.

        Hardened (docs/resilience.md): transient write errors — real or
        injected at the ``tier.disk.write`` / ``tier.object.put`` fault
        points — retry with bounded jittered backoff, then that
        DESTINATION quarantines for the entry (clean skip, counted);
        repeated failures open the ``tier.disk`` / ``tier.object``
        breaker, after which writebacks to that tier drop immediately
        (no retry storm against a dead backend) until a half-open probe
        recovers. The tiers fail independently: an open object breaker
        never blocks disk writeback, and vice versa."""
        while True:
            key_hash = self._writeq.get()
            if key_hash is None:
                return
            with self._lock:
                payload = self._pending.get(key_hash)
            if payload is None:
                continue
            started = time.monotonic()
            wrote_disk = False
            path = ""
            if self.disk_budget > 0:
                path = os.path.join(self._ensure_dir(),
                                    key_hash.hex() + ".npz")
                if not self._disk_breaker.allow():
                    # disk tier quarantined: skip cleanly (stay bounded,
                    # never wedge the writer on a dead disk)
                    pass
                elif self._write_disk(path, payload):
                    self._disk_breaker.record_success()
                    wrote_disk = True
                else:
                    self._disk_breaker.record_failure("disk write")
                    self._count_io_error("disk", "write")
                    logger.warning(
                        "kv tier store: disk write failed after %d "
                        "attempt(s) (%s); dropping page",
                        self.io_retry_max + 1, path)
            wrote_object = self._write_object_tier(key_hash, payload)
            if not wrote_disk and not wrote_object:
                # no lower tier took the page: truly gone
                with self._lock:
                    self._pending.pop(key_hash, None)
                self.dropped += 1
                if self.index is not None:
                    self.index.unpublish_tier(key_hash, "host")
                continue
            nbytes = payload.nbytes
            evicted: list[tuple[bytes, str, bool]] = []
            with self._lock:
                self._pending.pop(key_hash, None)
                if wrote_disk:
                    self._disk[key_hash] = (path, nbytes)
                    self._disk_nbytes += nbytes
                    while self._disk_nbytes > self.disk_budget \
                            and len(self._disk) > 1:
                        old_key, (old_path, old_nbytes) = \
                            next(iter(self._disk.items()))
                        del self._disk[old_key]
                        self._disk_nbytes -= old_nbytes
                        evicted.append((old_key, old_path,
                                        old_key in self._object))
                if wrote_object and key_hash not in self._object:
                    self._object[key_hash] = nbytes
                    self._object_nbytes += nbytes
            if wrote_disk:
                self.disk_writes += 1
                if self.metrics is not None:
                    self.metrics.llm_prefix_tier_io.labels(
                        op="writeback", tier="disk").observe(
                        time.monotonic() - started)
            if wrote_object:
                self.object_writes += 1
            if self.index is not None:
                if wrote_disk:
                    self.index.publish_tier(key_hash, "disk")
                if wrote_object:
                    self.index.publish_object(
                        key_hash, self._object_key(key_hash))
                self.index.unpublish_tier(key_hash, "host")
            for old_key, old_path, still_object in evicted:
                try:
                    os.unlink(old_path)
                except OSError:
                    pass
                if not still_object:
                    # past the last tier — the blob-backed case is NOT a
                    # drop: the page is one object fetch away
                    self.dropped += 1
                if self.index is not None:
                    self.index.unpublish_tier(old_key, "disk")

    def _count_io_error(self, tier: str, op: str) -> None:
        self.io_errors[(tier, op)] += 1
        if self.metrics is not None:
            try:
                self.metrics.llm_prefix_tier_io_errors.labels(
                    tier=tier, op=op).inc()
            except Exception:
                pass  # accounting must never mask the IO failure itself

    def _write_disk(self, path: str, payload: SpilledPage) -> bool:
        """One writeback with bounded retries. The ``tier.disk.write``
        fault point fires per ATTEMPT (an ``error`` rule in ``always``
        mode exhausts the retries; ``one_in_n`` exercises the retry
        succeeding); a ``corrupt`` rule mangles the file AFTER a clean
        write — the read side's verification must turn it into a MISS."""
        corrupt_after = False
        for attempt in range(self.io_retry_max + 1):
            act = fault_point("tier.disk.write", scope=path)
            try:
                if act is not None:
                    if act.kind == "corrupt":
                        corrupt_after = True
                    else:
                        act.apply()
                self._write_file(path, payload)
            except OSError:
                if attempt >= self.io_retry_max:
                    return False
                time.sleep(_backoff_s(self.io_retry_backoff_ms, attempt,
                                      len(path)))
                continue
            if corrupt_after:
                try:
                    with open(path, "r+b") as fh:
                        data = fh.read()
                        fh.seek(0)
                        fh.write(FaultAction.corrupt_bytes(data))
                except OSError:
                    pass
            return True
        return False

    def _read_disk(self, path: str) -> SpilledPage | None:
        """One disk load with bounded retries: transient ``OSError``
        (or an injected ``tier.disk.read`` error) retries with jittered
        backoff; structurally corrupt content (real bit rot or an
        injected ``corrupt`` rule) quarantines immediately — retrying
        cannot fix a bad file, and the caller drops the entry to a
        clean MISS."""
        for attempt in range(self.io_retry_max + 1):
            data_override = None
            act = fault_point("tier.disk.read", scope=path)
            try:
                if act is not None:
                    if act.kind == "corrupt":
                        with open(path, "rb") as fh:
                            data_override = FaultAction.corrupt_bytes(
                                fh.read())
                    else:
                        act.apply()
                if data_override is not None:
                    import io
                    with np.load(io.BytesIO(data_override)) as data:
                        return self._payload_from(data)
                with np.load(path) as data:
                    return self._payload_from(data)
            except OSError:
                if attempt >= self.io_retry_max:
                    return None
                time.sleep(_backoff_s(self.io_retry_backoff_ms, attempt,
                                      len(path)))
            except Exception:
                # corrupt content (BadZipFile / KeyError / ValueError /
                # truncated pickle): unrecoverable, quarantine now
                logger.warning("kv tier store: corrupt spill file %s",
                               path)
                return None
        return None

    @staticmethod
    def _payload_from(data) -> SpilledPage:
        return SpilledPage(
            chunk=tuple(int(t) for t in data["chunk"]),
            parent=data["parent"].tobytes(),
            k=data["k"], v=data["v"],
            k_scales=data["k_scales"], v_scales=data["v_scales"])

    @staticmethod
    def _write_file(path: str, payload: SpilledPage) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez(fh,
                     chunk=np.asarray(payload.chunk, dtype=np.int64),
                     parent=np.frombuffer(payload.parent, dtype=np.uint8),
                     k=np.asarray(payload.k), v=np.asarray(payload.v),
                     k_scales=np.asarray(payload.k_scales),
                     v_scales=np.asarray(payload.v_scales))
        os.replace(tmp, path)

    @staticmethod
    def _read_file(path: str) -> SpilledPage | None:
        """Unhardened single-shot load (kept for tooling/tests); the
        serving path uses :meth:`_read_disk`."""
        try:
            with np.load(path) as data:
                return TieredPageStore._payload_from(data)
        except (OSError, KeyError, ValueError):
            logger.warning("kv tier store: unreadable spill file %s", path)
            return None

    def verify_chain(self, steps: Sequence[tuple[bytes, bytes,
                                                 tuple[int, ...]]]
                     ) -> tuple[int, int]:
        """Verify-before-serve over a whole exported chain (the pool's
        migration path): fetch + verify each ``(key_hash, parent,
        chunk)`` through :meth:`get` — the SAME identity check admission
        uses, so a corrupt or colliding payload degrades to a miss here
        exactly as it would at the decode target's fetch-on-miss.
        Returns ``(pages_verified, bytes_verified)``; stops at the first
        miss (nothing deeper can restore without its parent)."""
        pages = 0
        nbytes = 0
        for key_hash, parent, chunk in steps:
            hit = self.get(key_hash, parent, chunk)
            if hit is None:
                break
            pages += 1
            nbytes += hit[0].nbytes
        return pages, nbytes

    # ------------------------------------------------------------------ stats

    def stats(self) -> dict[str, Any]:
        with self._lock:
            host_entries = len(self._host) + len(self._pending)
            host_nbytes = self._host_nbytes + sum(
                p.nbytes for p in self._pending.values())
            disk_entries = len(self._disk)
            disk_nbytes = self._disk_nbytes
            object_entries = len(self._object)
            object_nbytes = self._object_nbytes
        out: dict[str, Any] = {
            "host_pages": host_entries, "host_bytes": host_nbytes,
            "host_budget_bytes": self.host_budget,
            "disk_pages": disk_entries, "disk_bytes": disk_nbytes,
            "disk_budget_bytes": self.disk_budget,
            "spilled": self.spilled, "dropped": self.dropped,
            "disk_writes": self.disk_writes, "disk_reads": self.disk_reads,
            "collisions": self.collisions,
            "io_errors": {f"{tier}.{op}": count for (tier, op), count
                          in self.io_errors.items()},
            "disk_breaker": self._disk_breaker.snapshot(),
        }
        if self.object_store is not None:
            out["object_pages"] = object_entries
            out["object_bytes"] = object_nbytes
            out["object_url"] = self.object_store.url
            out["object_namespace"] = self.object_namespace
            out["object_writes"] = self.object_writes
            out["object_reads"] = self.object_reads
            out["object_write_drops"] = self.object_write_drops
            out["object_breaker"] = self._object_breaker.snapshot()
            if self.fabric is not None:
                out["fabric"] = self.fabric.stats()
        return out


class TierClient:
    """One engine's binding to the (pool-shared) store + index.

    Owns the engine-provided device I/O callbacks — ``read_fn(page) ->
    SpilledPage-shaped arrays`` (device->host, quantize-on-spill under a
    bf16 pool) and ``write_fn(page, payload)`` (host->device upload into
    the admitting replica's HBM) — plus the spill/restore latency
    windows the stats surfaces report. The allocator calls ``spill`` at
    eviction and ``restore`` at match time; both run on the engine's
    dispatch thread (the only thread allowed to touch device state)."""

    def __init__(self, replica_id: str,
                 store: TieredPageStore | None = None,
                 index: "PrefixIndex | None" = None,
                 metrics=None, tracer=None) -> None:
        self.replica_id = replica_id
        self.store = store
        self.index = index
        self.metrics = metrics
        self.tracer = tracer
        # trace attribution handoff: the engine's admission path sets
        # this to the admitting request's (trace_id, span_id) around
        # match/allocate so spill/restore IO lands as tier.spill /
        # tier.restore spans inside that request's waterfall (set and
        # read on the same dispatch thread; None = unattributed, no span)
        self.trace_ctx: tuple[str, str] | None = None
        self.read_fn: Callable[[int], SpilledPage] | None = None
        self.write_fn: Callable[[int, SpilledPage], None] | None = None
        self.spills = 0
        self.restores = 0
        self.spill_ms: deque[float] = deque(maxlen=256)
        self.restore_ms: deque[float] = deque(maxlen=256)

    def _emit_io_span(self, name: str, wall_start: float,
                      attrs: dict[str, Any]) -> None:
        if self.tracer is None or self.trace_ctx is None:
            return
        try:
            self.tracer.emit_span(
                name, wall_start, time.time(), trace_ctx=self.trace_ctx,
                attributes={"llm.replica_id": self.replica_id, **attrs})
        except Exception:
            pass  # telemetry must never break the dispatch thread

    @property
    def active(self) -> bool:
        """True when spill/restore are actually wired (store + device IO);
        a client with only an index still publishes HBM residency for
        the router but never moves page bytes."""
        return (self.store is not None and self.read_fn is not None
                and self.write_fn is not None)

    # ------------------------------------------------------- index publication

    def publish_hbm(self, key_hash: bytes) -> None:
        if self.index is not None:
            self.index.publish_hbm(key_hash, self.replica_id)

    def unpublish_hbm(self, key_hash: bytes) -> None:
        if self.index is not None:
            self.index.unpublish_hbm(key_hash, self.replica_id)

    def drop_replica(self) -> None:
        if self.index is not None:
            self.index.drop_replica(self.replica_id)

    # ---------------------------------------------------------- byte movement

    def probe(self, key_hash: bytes) -> bool:
        return self.store is not None and self.store.probe(key_hash)

    def spill(self, key_hash: bytes, parent: bytes, chunk: Sequence[int],
              page: int) -> bool:
        """Evicted-page handoff: read the page's bytes off the device and
        admit them into T1. Skips the device read when some tier already
        holds the key (another replica spilled the same chain)."""
        if not self.active:
            return False
        if self.store.probe(key_hash):
            return True
        started = time.monotonic()
        wall_start = time.time()
        payload = self.read_fn(page)
        payload.chunk = tuple(chunk)
        payload.parent = parent
        self.store.put(key_hash, payload)
        elapsed = time.monotonic() - started
        self.spills += 1
        self.spill_ms.append(elapsed * 1e3)
        if self.metrics is not None:
            self.metrics.llm_prefix_tier_io.labels(
                op="spill", tier="host").observe(elapsed)
        self._emit_io_span("tier.spill", wall_start, {
            "tier.tier": "host", "tier.tokens": len(payload.chunk),
            "tier.bytes": payload.nbytes})
        return True

    def restore(self, key_hash: bytes, parent: bytes, chunk: Sequence[int],
                page: int) -> str | None:
        """Fetch-on-miss: verify + fetch the spilled page and upload it
        into ``page`` of THIS replica's HBM pool. Returns the source
        tier ("host"/"disk"/"object") or None (miss / collision)."""
        if not self.active:
            return None
        started = time.monotonic()
        wall_start = time.time()
        hit = self.store.get(key_hash, parent, chunk)
        if hit is None:
            return None
        payload, tier = hit
        self.write_fn(page, payload)
        elapsed = time.monotonic() - started
        self.restores += 1
        self.restore_ms.append(elapsed * 1e3)
        if self.metrics is not None:
            self.metrics.llm_prefix_tier_io.labels(
                op="restore", tier=tier).observe(elapsed)
        self._emit_io_span("tier.restore", wall_start, {
            "tier.tier": tier, "tier.tokens": len(payload.chunk),
            "tier.bytes": payload.nbytes})
        return tier

    # ------------------------------------------------------------------ stats

    def restore_p95_ms(self) -> float | None:
        if not self.restore_ms:
            return None
        window = sorted(self.restore_ms)
        return round(window[min(len(window) - 1,
                                int(len(window) * 0.95))], 3)

    def stats(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "replica": self.replica_id,
            "spills": self.spills, "restores": self.restores,
            "restore_p95_ms": self.restore_p95_ms(),
        }
        if self.store is not None:
            out["store"] = self.store.stats()
        return out
