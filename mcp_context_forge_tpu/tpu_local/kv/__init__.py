"""Paged KV cache (+ spill tiers and the pool-global prefix index)."""

from .paged_cache import (
    PagedKVState,
    PageAllocator,
    PrefixEvictionPolicy,
    init_kv_state,
    kv_page_bytes,
    num_pages_for_budget,
    write_prefill_kv,
    write_decode_kv,
    gather_kv,
    kv_logical,
)
from .prefix_index import PrefixIndex, chain_hash, chain_hashes
from .tiers import SpilledPage, TierClient, TieredPageStore

__all__ = ["PagedKVState", "PageAllocator", "PrefixEvictionPolicy",
           "init_kv_state", "kv_page_bytes",
           "num_pages_for_budget", "write_prefill_kv", "write_decode_kv",
           "gather_kv", "kv_logical",
           "PrefixIndex", "chain_hash", "chain_hashes",
           "SpilledPage", "TierClient", "TieredPageStore"]
