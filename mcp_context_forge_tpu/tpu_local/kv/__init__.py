"""Paged KV cache."""

from .paged_cache import (
    PagedKVState,
    PageAllocator,
    init_kv_state,
    kv_page_bytes,
    num_pages_for_budget,
    write_prefill_kv,
    write_decode_kv,
    gather_kv,
    kv_logical,
)

__all__ = ["PagedKVState", "PageAllocator", "init_kv_state", "kv_page_bytes",
           "num_pages_for_budget", "write_prefill_kv", "write_decode_kv",
           "gather_kv", "kv_logical"]
