"""Pool-global prefix index: hashed full-page prefix chains -> locations.

One instance is shared by every replica in an ``EnginePool`` (and by the
pool's router). Each entry maps the chained hash of a full-page prompt
prefix (see :func:`chain_hash`) to WHERE that page's KV is currently
materialized:

- ``hbm`` locations name the replica(s) whose resident prefix cache
  holds the page — the router treats those as affinity targets, because
  only that replica's own allocator can serve the page without a
  restore;
- ``tier`` locations name the pool-shared spill tiers (``host``/
  ``disk`` — ``tpu_local/kv/tiers.py``) — ANY replica can fetch-on-miss
  from them at admission, so a tier hit is affinity-neutral for
  placement but still counts as a hit for routing accounting;
- ``object:<key>`` locations name the chain's blob in the cross-host
  object fabric (``tpu_local/kv/fabric/``) — like a shared tier for
  routing purposes, but host-global: the key is the tenant-namespaced
  blob name any host sharing the store can fetch.

The index stores ONLY hashes, never token content: a hash collision can
therefore mis-route (the chosen replica's local probe then finds
nothing — harmless) or trigger a tier fetch whose payload verification
fails (tiers.py compares the stored parent hash + exact chunk tokens
before serving — the fetch degrades to a miss). Wrong pages are never
served on a collision; the payload check is the gate.

Thread model: published from engine dispatch threads (register/evict/
spill) and the store's spill worker, read from the gateway loop (router
scoring). Every access takes the internal lock; all operations are
dict-sized, never device-touching.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Iterable, Sequence

import numpy as np

#: chain root: the hash "parent" of the first page of every prompt.
ROOT_HASH = hashlib.sha256(b"mcpforge-prefix-chain-root").digest()


def chain_hash(parent: bytes, chunk: Sequence[int]) -> bytes:
    """Chained digest of one full page of prompt tokens under ``parent``
    (the previous page's chain hash, ``ROOT_HASH`` for the first page).
    Two prefixes share a chain hash iff they share every token of every
    page up to that depth — modulo sha256 collisions, which the tier
    payload verification (not this index) guards against."""
    h = hashlib.sha256(parent)
    h.update(np.asarray(list(chunk), dtype=np.int64).tobytes())
    return h.digest()


def chain_hashes(prompt_ids: Sequence[int], page_size: int) -> list[bytes]:
    """Chain hash per matchable full page of ``prompt_ids`` (matches never
    cover the prompt's last token — same rule as the allocator's walk)."""
    max_pages = max(0, (len(prompt_ids) - 1) // page_size)
    out: list[bytes] = []
    parent = ROOT_HASH
    for i in range(max_pages):
        parent = chain_hash(parent,
                            prompt_ids[i * page_size:(i + 1) * page_size])
        out.append(parent)
    return out


def chain_pages(prompt_ids: Sequence[int], page_size: int
                ) -> list[tuple[bytes, bytes, tuple[int, ...]]]:
    """``(key_hash, parent, chunk)`` per FULL page of ``prompt_ids`` —
    the registration-depth walk (``len // page_size`` pages, one deeper
    than :func:`chain_hashes`' matchable walk). This is the identity
    evidence the tier store's verify-before-serve compares, so the
    pool's migration path can verify an exported chain without touching
    any allocator state."""
    out: list[tuple[bytes, bytes, tuple[int, ...]]] = []
    parent = ROOT_HASH
    for i in range(len(prompt_ids) // page_size):
        chunk = tuple(int(t) for t in
                      prompt_ids[i * page_size:(i + 1) * page_size])
        key_hash = chain_hash(parent, chunk)
        out.append((key_hash, parent, chunk))
        parent = key_hash
    return out


class PrefixIndex:
    """Pool-global location map for prefix-chain pages (see module doc)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hbm: dict[bytes, set[str]] = {}   # hash -> replica ids
        self._tier: dict[bytes, set[str]] = {}  # hash -> {"host","disk"}
        self._object: dict[bytes, str] = {}     # hash -> object blob key

    # ------------------------------------------------------------ publication

    def publish_hbm(self, key_hash: bytes, replica: str) -> None:
        with self._lock:
            self._hbm.setdefault(key_hash, set()).add(replica)

    def unpublish_hbm(self, key_hash: bytes, replica: str) -> None:
        with self._lock:
            replicas = self._hbm.get(key_hash)
            if replicas is not None:
                replicas.discard(replica)
                if not replicas:
                    del self._hbm[key_hash]

    def drop_replica(self, replica: str) -> None:
        """Forget every HBM entry of one replica — called when its KV pool
        is rebuilt (crash restart, reload): the resident pages are gone
        and stale entries would mis-route until they aged out."""
        with self._lock:
            for key_hash in [k for k, v in self._hbm.items()
                             if replica in v]:
                self._hbm[key_hash].discard(replica)
                if not self._hbm[key_hash]:
                    del self._hbm[key_hash]

    def publish_tier(self, key_hash: bytes, tier: str) -> None:
        with self._lock:
            self._tier.setdefault(key_hash, set()).add(tier)

    def unpublish_tier(self, key_hash: bytes, tier: str) -> None:
        with self._lock:
            tiers = self._tier.get(key_hash)
            if tiers is not None:
                tiers.discard(tier)
                if not tiers:
                    del self._tier[key_hash]

    def publish_object(self, key_hash: bytes, object_key: str) -> None:
        """Record the chain page's blob in the shared object fabric.
        The ``object:<key>`` location class is host-global: unlike
        ``host``/``disk`` it survives this process and is reachable
        from any host sharing the store."""
        with self._lock:
            self._object[key_hash] = object_key

    def unpublish_object(self, key_hash: bytes) -> None:
        with self._lock:
            self._object.pop(key_hash, None)

    # ----------------------------------------------------------------- lookup

    def locations(self, key_hash: bytes) -> dict[str, Any]:
        with self._lock:
            object_key = self._object.get(key_hash)
            return {"hbm": set(self._hbm.get(key_hash, ())),
                    "tiers": set(self._tier.get(key_hash, ())),
                    "object": f"object:{object_key}"
                    if object_key is not None else None}

    def chain_locations(self, prompt_ids: Sequence[int], page_size: int
                        ) -> list[tuple[set[str], bool]]:
        """Per matchable full page of ``prompt_ids`` (depth order):
        ``(replicas_with_hbm_copy, shared_tier_available)``. The router
        folds this into per-replica affinity: replica R can serve depth i
        without prefill iff every depth <= i is in R's HBM set or in a
        shared tier (fetch-on-miss restores the latter at admission)."""
        hashes = chain_hashes(prompt_ids, page_size)
        with self._lock:
            return [(set(self._hbm.get(h, ())),
                     bool(self._tier.get(h)) or h in self._object)
                    for h in hashes]

    def reachable_tokens(self, chain: Iterable[tuple[set[str], bool]],
                         replica: str, page_size: int) -> int:
        """Tokens of the chain ``replica`` could serve without dense
        prefill: consecutive depths available locally (HBM) or from a
        shared tier. Stops at the first page only ANOTHER replica's HBM
        holds — cross-replica HBM reads don't exist (the router routes
        TO that replica instead)."""
        depth = 0
        for hbm, tiered in chain:
            if replica in hbm or tiered:
                depth += 1
            else:
                break
        return depth * page_size

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {"keys_hbm": len(self._hbm),
                    "keys_tiered": len(self._tier),
                    "keys_object": len(self._object)}
